"""Crash-safe state persistence — restart survivability for the exporter.

Everything the exporter has learned lives in process memory: the history
flight recorder's rings (PR 1), each source's circuit-breaker state (PR 2),
and the pre-encoded exposition snapshot. A DaemonSet rolling update, an
OOM-kill, or a node drain discards all of it — ``/readyz`` drops to 503,
the aggregator's ``--history-fallback-window`` has a hole it cannot fill,
and every breaker re-learns a still-wedged source from closed. The
reference exporter has the same amnesia (``main.go:74-114`` rebuilds from
scratch every cycle); at production scale the single most common scenario —
the process restarting — must be a non-event.

:class:`StatePersister` makes it one, with two cooperating files under
``--state-dir``:

- ``snapshot.bin`` — a periodic full checkpoint (history rings, breaker
  states with open-until wall timestamps, the last published exposition
  with its poll timestamp), written to a temp file, fsynced, and renamed
  into place — a crash mid-rotation can never leave a half checkpoint.
- ``wal.bin`` — an append-only log between checkpoints: one ``samples``
  record per poll (the tracked families' values in a layout-described
  order), plus ``layout`` records on churn and ``breaker`` records on
  state transitions.

Every record is individually CRC-checked. On boot :meth:`StatePersister.load`
replays snapshot + WAL with torn-write tolerance — the WAL is truncated at
the first corrupt record, a bad snapshot restores whatever consistent
prefix it holds, and NOTHING refuses to start: a hopeless state dir logs a
warning and cold-starts. The restored exposition is served immediately
(:class:`RestoredSnapshot` patches ``tpu_exporter_warm_start 1`` and the
measured ``tpu_exporter_snapshot_stale_seconds`` into the cached bytes) so
scrapes and the aggregator see continuity instead of a gap, while
``/readyz`` reports a distinct ``warm`` state until the first live poll.

Threading: the poll thread's per-poll cost is a breaker-signature check and
one queue put — snapshots are immutable after the swap, so the writer
thread extracts values, frames records, and does every byte of I/O off the
poll loop (the same discipline as the history append: persistence can
never stretch a poll, and a wedged disk drops WAL records rather than
wedging polling). ``--state-dir ""`` (the default) disables the layer
entirely.

CLI (``python -m tpu_pod_exporter.persist``):

- ``--restart-demo``  — the kill/restart chaos harness (``make
  restart-demo``): SIGKILLs a live exporter mid-poll via the chaos
  ``kill`` injection, restarts it on the same state dir, and asserts
  history continuity, breaker-state carryover, and corrupt-WAL cold-start.
- ``--fsync-check``   — fsync-latency budget on the persistence hot path.
- ``--overhead-check`` — poll-thread CPU with persistence on vs off.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import struct
import threading
import time
import zlib
from array import array
from collections import deque
from dataclasses import dataclass, field
from typing import IO, TYPE_CHECKING, Any, Callable, Iterator, Mapping

if TYPE_CHECKING:  # typing only — no runtime import cost
    from tpu_pod_exporter.history import HistoryStore
    from tpu_pod_exporter.metrics.registry import Snapshot
    from tpu_pod_exporter.supervisor import SourceSupervisor

from tpu_pod_exporter.utils import RateLimitedLogger

log = logging.getLogger("tpu_pod_exporter.persist")

# File magic: 8 bytes, versioned. A magic mismatch means "not ours /
# future format" — treated as an empty file, never a crash.
MAGIC = b"TPEPST01"

# Record framing: <payload_len, crc32(payload)> then payload. The CRC is
# the torn-write detector: a record whose bytes were cut by a crash (or
# scrambled by a bad disk) fails its checksum and everything from it on is
# ignored — the consistent prefix before it is the restored state.
_HDR = struct.Struct("<II")
_F64 = struct.Struct("<d")

# Hard sanity bound on one record: a corrupted length field must not make
# the reader allocate gigabytes before the CRC gets a chance to reject it.
MAX_RECORD_BYTES = 256 << 20

SNAPSHOT_NAME = "snapshot.bin"
WAL_NAME = "wal.bin"

# Payload type bytes (payload[0:1]):
#   J  JSON control: {"t": "meta" | "layout" | "breaker" | "end"}
#   S  per-poll samples: <d wall> + float64 values in current layout order
#   R  one series' ring dump: <I jlen> + json{"m","l"} + (wall, value)*
#   E  exposition: <d poll_timestamp> + raw exposition bytes


def append_record(f: IO[bytes], payload: bytes) -> int:
    """Frame + write one record; returns bytes written (buffered, not
    synced — fsync cadence is the caller's policy)."""
    f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
    f.write(payload)
    return _HDR.size + len(payload)


def _walk_records(
    path: str, collect_payloads: bool
) -> tuple[list[tuple[int, int]], list[bytes], int, str | None]:
    """The ONE CRC-framed record walker (read_record_file and the
    WalBuffer segment scan are both views of it — the framing rules must
    never exist twice). Returns (offsets, payloads, valid_bytes, error):
    ``offsets`` is [(payload_offset, payload_len), ...] for the longest
    clean prefix, ``payloads`` the corresponding bytes when requested,
    ``valid_bytes`` the file offset just past the prefix (the truncate
    point for a torn tail), ``error`` why reading stopped early (None for
    a clean end-of-file)."""
    offsets: list[tuple[int, int]] = []
    payloads: list[bytes] = []
    try:
        f = open(path, "rb")
    except FileNotFoundError:
        return offsets, payloads, 0, None
    except OSError as e:
        return offsets, payloads, 0, f"unreadable: {e}"
    with f:
        head = f.read(len(MAGIC))
        if len(head) < len(MAGIC):
            return offsets, payloads, 0, None if not head else "short magic"
        if head != MAGIC:
            return offsets, payloads, 0, f"bad magic {head!r}"
        valid = len(MAGIC)
        while True:
            hdr = f.read(_HDR.size)
            if not hdr:
                return offsets, payloads, valid, None
            if len(hdr) < _HDR.size:
                return offsets, payloads, valid, "torn record header"
            length, crc = _HDR.unpack(hdr)
            if length > MAX_RECORD_BYTES:
                return (offsets, payloads, valid,
                        f"implausible record length {length}")
            payload = f.read(length)
            if len(payload) < length:
                return offsets, payloads, valid, "torn record payload"
            if zlib.crc32(payload) != crc:
                return offsets, payloads, valid, "record CRC mismatch"
            offsets.append((valid + _HDR.size, length))
            if collect_payloads:
                payloads.append(payload)
            valid += _HDR.size + length


def read_record_file(path: str) -> tuple[list[bytes], int, str | None]:
    """Read a record file; returns (payloads, valid_bytes, error) — the
    longest clean prefix of records (see :func:`_walk_records`)."""
    _offsets, payloads, valid, err = _walk_records(path, True)
    return payloads, valid, err


def _fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it is durable (best-effort —
    some filesystems refuse directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes) -> None:
    """write-temp, fsync, rename — the snapshot-rotation discipline."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


# --------------------------------------------------------------- warm start


def _rewrite_counter_headers(body: bytes) -> bytes:
    """Plain-text exposition → OpenMetrics header shape: counter HELP/TYPE
    lines drop the ``_total`` suffix (same transform as
    ``Snapshot.encode_openmetrics``, but self-describing from the body so a
    restored exposition needs no schema objects)."""
    for line in body.split(b"\n"):
        if line.startswith(b"# TYPE ") and line.endswith(b" counter"):
            name = line[len(b"# TYPE "):-len(b" counter")]
            if not name.endswith(b"_total"):
                continue
            base = name[: -len(b"_total")]
            for old, new in (
                (b"# HELP " + name + b" ", b"# HELP " + base + b" "),
                (b"# TYPE " + name + b" counter",
                 b"# TYPE " + base + b" counter"),
            ):
                if body.startswith(old):
                    body = new + body[len(old):]
                else:
                    body = body.replace(b"\n" + old, b"\n" + new, 1)
    return body


class RestoredSnapshot:
    """A served-from-disk stand-in for :class:`metrics.Snapshot`.

    Wraps the persisted exposition bytes with the warm-start markers
    patched in: ``tpu_exporter_warm_start`` flips to 1 and
    ``tpu_exporter_snapshot_stale_seconds`` carries how old the restored
    data was when serving resumed (both series exist in every live body, so
    this is a value edit, not a header injection). ``timestamp`` is the
    restore instant — the snapshot starts *serving* now; the underlying
    poll's wall time stays readable as ``poll_timestamp`` (and as the
    body's own ``tpu_exporter_last_poll_timestamp_seconds``), which keeps
    ``/healthz``'s staleness rule measuring serving age, not data age — a
    warm boot must not be instantly "stale" and crash-looped by kubelet.
    """

    def __init__(self, body: bytes, poll_timestamp: float,
                 restored_at: float | None = None) -> None:
        import re

        now = time.time() if restored_at is None else restored_at
        self.poll_timestamp = poll_timestamp
        self.timestamp = now
        self.stale_s = max(now - poll_timestamp, 0.0)
        from tpu_pod_exporter.metrics.registry import format_value

        stale = format_value(round(self.stale_s, 3)).encode()
        body = re.sub(rb"^tpu_exporter_warm_start .*$",
                      b"tpu_exporter_warm_start 1", body, count=1,
                      flags=re.M)
        body = re.sub(rb"^tpu_exporter_snapshot_stale_seconds .*$",
                      b"tpu_exporter_snapshot_stale_seconds " + stale,
                      body, count=1, flags=re.M)
        self._body = body
        self._gzipped: bytes | None = None
        self._openmetrics: bytes | None = None
        self._openmetrics_gzipped: bytes | None = None
        self._series_count: int | None = None

    @property
    def series_count(self) -> int:
        if self._series_count is None:
            self._series_count = sum(
                1 for line in self._body.split(b"\n")
                if line and not line.startswith(b"#")
            )
        return self._series_count

    def encode(self) -> bytes:
        return self._body

    def encode_gzip(self) -> bytes:
        # Lock-free lazy cache (same idiom as registry.BodySet): racing
        # scrapers may both compress once — identical bytes, GIL-atomic
        # publish, and no thread ever holds a lock across the compression.
        gz = self._gzipped
        if gz is None:
            import gzip

            gz = gzip.compress(self._body, compresslevel=1)
            self._gzipped = gz
        return gz

    def encode_openmetrics(self) -> bytes:
        om = self._openmetrics
        if om is None:
            om = _rewrite_counter_headers(self._body) + b"# EOF\n"
            self._openmetrics = om
        return om

    def encode_openmetrics_gzip(self) -> bytes:
        gz = self._openmetrics_gzipped
        if gz is None:
            import gzip

            gz = gzip.compress(self.encode_openmetrics(), compresslevel=1)
            self._openmetrics_gzipped = gz
        return gz

    def cached_exposition(self, openmetrics: bool = False,
                          gzipped: bool = False) -> bytes | None:
        """Event-loop fast path (see ``Snapshot.cached_exposition``): the
        restored identity body is always in memory; derived encodings are
        served inline once the first (worker-rendered) request cached
        them."""
        if openmetrics:
            return self._openmetrics_gzipped if gzipped else self._openmetrics
        if gzipped:
            return self._gzipped
        return self._body


# ------------------------------------------------------------------- restore


@dataclass
class RestoredState:
    """What :meth:`StatePersister.load` brought back (all best-effort)."""

    exposition: bytes | None = None
    exposition_ts: float = 0.0
    breakers: dict[str, dict] = field(default_factory=dict)
    series: int = 0
    samples: int = 0
    wal_records: int = 0
    max_wall: float = 0.0
    errors: list[str] = field(default_factory=list)

    @property
    def restored(self) -> bool:
        return bool(
            self.exposition or self.series or self.samples or self.breakers
        )


class StatePersister:
    """Periodic checksummed snapshot + per-poll WAL under ``state_dir``.

    Construction never raises on a bad directory (it tries to create it
    and records the failure); ``load()`` restores whatever consistent
    state exists; ``start()`` spawns the writer thread; ``on_poll()`` is
    the poll thread's only touchpoint. ``close()`` drains the queue and
    writes a final fsynced snapshot — the SIGTERM flush.
    """

    def __init__(
        self,
        state_dir: str,
        history: "HistoryStore | None" = None,
        supervisors: Mapping[str, SourceSupervisor] | None = None,
        # () -> Snapshot-like (encode()/timestamp)
        exposition_fn: Callable[[], Any] | None = None,
        snapshot_interval_s: float = 60.0,
        fsync_interval_s: float = 5.0,
        queue_max: int = 8,
        clock: Callable[[], float] = time.monotonic,
        wallclock: Callable[[], float] = time.time,
    ) -> None:
        self.state_dir = state_dir
        self.snapshot_path = os.path.join(state_dir, SNAPSHOT_NAME)
        self.wal_path = os.path.join(state_dir, WAL_NAME)
        self._history = history
        self._supervisors = supervisors or {}
        self._exposition_fn = exposition_fn
        self.snapshot_interval_s = snapshot_interval_s
        self.fsync_interval_s = fsync_interval_s
        self._clock = clock
        self._wallclock = wallclock
        self._rlog = RateLimitedLogger(log)
        # Persisted families = exactly what the history recorder tracks;
        # sorted for a deterministic layout order.
        from tpu_pod_exporter.history import HISTORY_TRACKED_METRICS

        self._metric_order = tuple(sorted(HISTORY_TRACKED_METRICS))
        # Bounded handoff: queue items hold references to IMMUTABLE
        # snapshots, so the writer reads them without copies or locks. A
        # stalled disk fills the queue and drops WAL records (counted) —
        # persistence degrades, polling never does.
        self._q: queue.Queue = queue.Queue(maxsize=queue_max)
        self._thread: threading.Thread | None = None
        # Poll-side breaker change detection (cheap signatures).
        self._breaker_sigs: dict[str, tuple] = {}
        # Writer-side state (single-threaded: the writer owns these).
        self._wal = None
        self._wal_dirty = False
        self._last_fsync = 0.0
        self._last_rotate = 0.0
        self._fam_keys: dict[str, tuple] = {}
        self._fam_names: tuple[str, ...] = ()
        # Resource-pressure shed state (tpu_pod_exporter.pressure): the
        # governor's disk-ladder rungs flip these. Written on the governor
        # thread, read on the writer thread — plain attribute flips, no
        # lock needed (any single read is consistent).
        self._wal_stride = 1       # write every Nth samples record
        self._wal_enabled = True   # False = WAL-off-but-serving (last rung)
        self._snapshot_factor = 1.0  # checkpoint interval multiplier
        self._stride_seq = 0
        # Checkpoint retry: a failed rotation retries on this cadence
        # instead of waiting out a full snapshot interval (the WAL-reopen
        # discipline applied to the checkpoint path).
        self._snapshot_failed = False
        self._last_snapshot_attempt = 0.0
        self._pressure_hook: Callable[[BaseException], bool] | None = None
        self._stats_lock = threading.Lock()
        self._stats = {
            "wal_records": 0,
            "wal_samples": 0,
            "wal_bytes": 0,
            "snapshots": 0,
            "errors": 0,
            "dropped": 0,
            "fsyncs": 0,
            "last_fsync_s": 0.0,
            "last_snapshot_wall": 0.0,
        }
        # Reason splits (same totals as errors/dropped above): ENOSPC is a
        # FULL disk, not a flaky one — the DiskPressure alert keys on it.
        self._errors_by_reason = {"disk_full": 0, "io": 0}
        self._dropped_by_reason = {
            "queue": 0, "disk_full": 0, "io": 0, "shed": 0,
        }
        self.restored_info: dict = {"restored": False}
        self._dir_error: str | None = None
        try:
            os.makedirs(state_dir, exist_ok=True)
        except OSError as e:
            self._dir_error = str(e)
            log.error("state dir %s unusable (%s); persistence disabled "
                      "for this run", state_dir, e)

    # ------------------------------------------------------------------ load

    def load(self) -> RestoredState:
        """Replay snapshot + WAL into the attached history store and
        breakers. Never raises: any corruption restores the clean prefix
        before it; a hopeless state dir logs and returns an empty state
        (cold start)."""
        rs = RestoredState()
        if self._dir_error is not None:
            rs.errors.append(self._dir_error)
            return rs
        # Orphaned temp files from atomic writes a crash (or ENOSPC)
        # interrupted between write and rename: reclaim them before they
        # silently eat the very disk budget the pressure governor polices.
        # Age 0 is safe here — load() runs before the writer thread exists.
        from tpu_pod_exporter.pressure import reclaim_tmp_files

        reclaim_tmp_files([self.state_dir], min_age_s=0.0)
        try:
            self._load_inner(rs)
        except Exception as e:  # noqa: BLE001 — NEVER refuse to start
            rs.errors.append(f"unexpected restore failure: {e}")
            log.warning("state restore failed (%s); cold-starting", e,
                        exc_info=True)
        for err in rs.errors:
            log.warning("state restore: %s (continuing with the clean "
                        "prefix)", err)
        if rs.restored:
            log.info(
                "warm state restored from %s: %d series / %d samples, "
                "%d breaker(s), exposition %s (%d WAL records)",
                self.state_dir, rs.series, rs.samples, len(rs.breakers),
                "present" if rs.exposition else "absent", rs.wal_records,
            )
        self.restored_info = {
            "restored": rs.restored,
            "series": rs.series,
            "samples": rs.samples,
            "breakers": sorted(rs.breakers),
            "wal_records": rs.wal_records,
            "errors": list(rs.errors),
        }
        return rs

    def _load_inner(self, rs: RestoredState) -> None:
        now_mono = self._clock()
        now_wall = self._wallclock()
        offset = now_wall - now_mono

        def wall_to_mono(w: float) -> float:
            return w - offset

        # --- snapshot.bin: the checkpoint baseline ---
        payloads, _, err = read_record_file(self.snapshot_path)
        if err:
            rs.errors.append(f"{SNAPSHOT_NAME}: {err}")
        saw_end = False
        for payload in payloads:
            try:
                self._apply_snapshot_record(payload, rs, wall_to_mono)
                if payload[:1] == b"J":
                    doc = json.loads(payload[1:])
                    if doc.get("t") == "end":
                        saw_end = True
            except Exception as e:  # noqa: BLE001 — prefix semantics
                rs.errors.append(f"{SNAPSHOT_NAME}: bad record ({e})")
                break
        if payloads and not saw_end:
            rs.errors.append(f"{SNAPSHOT_NAME}: missing end marker "
                             f"(partial checkpoint restored)")

        # --- wal.bin: records since the checkpoint ---
        payloads, valid_bytes, err = read_record_file(self.wal_path)
        if err:
            rs.errors.append(f"{WAL_NAME}: {err}; truncating at the last "
                             f"clean record")
            try:
                os.truncate(self.wal_path, valid_bytes)
            except OSError as e:
                rs.errors.append(f"{WAL_NAME}: truncate failed ({e})")
        entries: list[tuple[str, dict]] | None = None
        acc: list[list[tuple[float, float]]] = []
        for payload in payloads:
            try:
                kind = payload[:1]
                if kind == b"J":
                    doc = json.loads(payload[1:])
                    t = doc.get("t")
                    if t == "layout":
                        self._flush_wal_batch(entries, acc, rs, wall_to_mono)
                        entries = self._layout_entries(doc)
                        acc = [[] for _ in entries]
                    elif t == "breaker":
                        rs.breakers[str(doc.get("name", ""))] = doc
                elif kind == b"S" and entries is not None:
                    wall = _F64.unpack_from(payload, 1)[0]
                    vals = array("d")
                    vals.frombytes(payload[1 + _F64.size:])
                    if len(vals) != len(entries):
                        rs.errors.append(
                            f"{WAL_NAME}: samples/layout length mismatch; "
                            f"stopping replay"
                        )
                        break
                    rs.wal_records += 1
                    if wall > rs.max_wall:
                        for a, v in zip(acc, vals):
                            a.append((wall, v))
                # unknown kinds: forward compatibility — skip silently
            except Exception as e:  # noqa: BLE001 — prefix semantics
                rs.errors.append(f"{WAL_NAME}: bad record ({e})")
                break
        self._flush_wal_batch(entries, acc, rs, wall_to_mono)

        # --- apply breaker states onto the live supervisors ---
        from tpu_pod_exporter.supervisor import CLOSED

        for name, doc in rs.breakers.items():
            sup = self._supervisors.get(name)
            if sup is None:
                continue
            try:
                sup.breaker.restore_state(doc, wallclock=self._wallclock)
                if sup.breaker.state != CLOSED:
                    log.warning(
                        "breaker for source %s restored %s (reopens=%d, "
                        "next probe in %.1fs) — carrying the quarantine "
                        "across the restart",
                        name, sup.breaker.state, sup.breaker.reopens,
                        sup.breaker.seconds_until_probe,
                    )
            except Exception as e:  # noqa: BLE001
                rs.errors.append(f"breaker {name}: restore failed ({e})")

    def _layout_entries(self, doc: dict) -> list[tuple[str, dict]]:
        from tpu_pod_exporter.metrics import schema

        spec_by_name = {s.name: s for s in schema.ALL_SPECS}
        entries: list[tuple[str, dict]] = []
        for fam in doc.get("fams", ()):
            name = fam["m"]
            spec = spec_by_name.get(name)
            label_names = spec.label_names if spec is not None else ()
            for lvs in fam["k"]:
                entries.append(
                    (name, dict(zip(label_names, (str(v) for v in lvs))))
                )
        return entries

    def _flush_wal_batch(self, entries: list[tuple[str, dict[str, str]]],
                         acc: list[list[tuple[float, float]]],
                         rs: RestoredState,
                         wall_to_mono: Callable[[float], float]) -> None:
        if not entries or self._history is None:
            return
        for (metric, labels), samples in zip(entries, acc):
            if samples:
                rs.samples += self._history.restore_series(
                    metric, labels, samples, wall_to_mono
                )

    def _apply_snapshot_record(self, payload: bytes, rs: RestoredState,
                               wall_to_mono: Callable[[float], float]) -> None:
        kind = payload[:1]
        if kind == b"J":
            doc = json.loads(payload[1:])
            t = doc.get("t")
            if t == "breaker":
                rs.breakers[str(doc.get("name", ""))] = doc
            elif t == "meta":
                rs.max_wall = max(rs.max_wall, float(doc.get("max_wall", 0.0)))
        elif kind == b"R":
            jlen = struct.unpack_from("<I", payload, 1)[0]
            head = 1 + 4
            doc = json.loads(payload[head:head + jlen])
            vals = array("d")
            vals.frombytes(payload[head + jlen:])
            samples = [
                (vals[i], vals[i + 1]) for i in range(0, len(vals) - 1, 2)
            ]
            if samples:
                rs.series += 1
                if self._history is not None:
                    rs.samples += self._history.restore_series(
                        doc["m"], dict(doc.get("l") or {}), samples,
                        wall_to_mono,
                    )
                last_wall = samples[-1][0]
                if last_wall > rs.max_wall:
                    rs.max_wall = last_wall
        elif kind == b"E":
            ts = _F64.unpack_from(payload, 1)[0]
            rs.exposition = payload[1 + _F64.size:]
            rs.exposition_ts = ts

    # ------------------------------------------------------------- poll side

    def start(self) -> None:
        if self._thread is not None or self._dir_error is not None:
            return
        self._last_rotate = self._clock()
        self._thread = threading.Thread(
            target=self._writer_run, name="tpu-exporter-persist", daemon=True
        )
        self._thread.start()

    def on_poll(self, snap: "Snapshot") -> int:
        """The poll thread's entire persistence cost: breaker-change
        signatures plus one non-blocking queue put (the snapshot is
        immutable — value extraction happens on the writer thread)."""
        if self._thread is None:
            return 0
        queued = 0
        for name, sup in self._supervisors.items():
            b = sup.breaker
            sig = (b.state, b.consecutive_failures, b.reopens)
            if self._breaker_sigs.get(name) != sig:
                self._breaker_sigs[name] = sig
                self._enqueue(("breaker", name))
        if self._enqueue(("samples", snap)):
            queued = 1
        return queued

    def _enqueue(self, item: tuple) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except queue.Full:
            self._count_dropped("queue")
            self._rlog.warning(
                "persist_drop",
                "persistence queue full (writer stalled?); dropping a WAL "
                "record — polling is unaffected",
            )
            return False

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
            out["errors_by_reason"] = dict(self._errors_by_reason)
            out["dropped_by_reason"] = dict(self._dropped_by_reason)
        out["queue_depth"] = self._q.qsize()
        out["restored"] = self.restored_info.get("restored", False)
        out["wal_stride"] = self._wal_stride
        out["wal_enabled"] = self._wal_enabled
        out["snapshot_factor"] = self._snapshot_factor
        return out

    # ------------------------------------------------- pressure-shed hooks
    # Flipped by the resource-pressure governor's disk ladder
    # (tpu_pod_exporter.pressure). Plain attribute writes read by the
    # writer thread; each rung is idempotent and individually reversible.

    def set_wal_stride(self, n: int) -> None:
        """Rung 1 (``wal_coarse``): write only every ``n``-th per-poll
        samples record. Skipped polls are counted as reason="shed" drops —
        a thinner WAL is a POLICY, and the restore-fidelity cost must stay
        visible. Layout/breaker records always write (tiny, and replay
        correctness needs them)."""
        self._wal_stride = max(int(n), 1)

    def set_wal_enabled(self, enabled: bool) -> None:
        """Rung 4 (``wal_off``): the deepest shed — no WAL records at all,
        checkpoints (at whatever cadence rung 3 left) remain the only
        durability. The exporter keeps serving throughout."""
        self._wal_enabled = bool(enabled)

    def set_snapshot_interval_factor(self, factor: float) -> None:
        """Rung 3 (``checkpoint_halved``): multiply the checkpoint
        interval (2.0 halves the frequency — worst-case restore staleness
        doubles, disk writes halve)."""
        self._snapshot_factor = max(float(factor), 1.0)

    def set_pressure_hook(self, hook: Callable[[BaseException], bool]) -> None:
        """Governor callback for write failures: ENOSPC reports shed the
        disk ladder immediately instead of waiting for a usage scan."""
        self._pressure_hook = hook

    def _count_dropped(self, reason: str) -> None:
        with self._stats_lock:
            self._stats["dropped"] += 1
            self._dropped_by_reason[reason] = (
                self._dropped_by_reason.get(reason, 0) + 1
            )

    @staticmethod
    def _io_reason(exc: BaseException | None) -> str:
        from tpu_pod_exporter.pressure import is_disk_full_error

        return "disk_full" if (
            exc is not None and is_disk_full_error(exc)
        ) else "io"

    def close(self, timeout: float = 10.0) -> None:
        """Drain the queue, write a final fsynced snapshot (the SIGTERM
        flush), and stop the writer."""
        t = self._thread
        if t is None:
            return
        done = threading.Event()
        try:
            self._q.put(("stop", done), timeout=timeout)
        except queue.Full:
            pass
        done.wait(timeout)
        t.join(timeout)
        self._thread = None

    # ----------------------------------------------------------- writer side

    def _writer_run(self) -> None:
        try:
            self._open_wal()
        except OSError as e:
            self._count_error("WAL open failed: %s", e, exc=e)
        while True:
            try:
                item = self._q.get(timeout=0.25)
            except queue.Empty:
                item = None
            try:
                if item is not None:
                    if item[0] == "stop":
                        self._drain_and_stop(item[1])
                        return
                    self._write_item(item)
                self._maybe_fsync()
                self._maybe_rotate()
            except Exception as e:  # noqa: BLE001 — the writer must survive I/O faults
                self._count_error("persistence write failed: %s", e, exc=e)

    def _drain_and_stop(self, done: threading.Event) -> None:
        try:
            while True:
                item = self._q.get_nowait()
                if item[0] != "stop":
                    self._write_item(item)
        except queue.Empty:
            pass
        except Exception as e:  # noqa: BLE001
            self._count_error("final drain failed: %s", e)
        try:
            self._write_snapshot()
        except Exception as e:  # noqa: BLE001
            self._count_error("final snapshot failed: %s", e)
        if self._wal is not None:
            try:
                self._wal.flush()
                os.fsync(self._wal.fileno())
                self._wal.close()
            except OSError:
                pass
            self._wal = None
        done.set()

    def _count_error(self, fmt: str, *args: object,
                     exc: BaseException | None = None) -> None:
        reason = self._io_reason(exc)
        with self._stats_lock:
            self._stats["errors"] += 1
            self._errors_by_reason[reason] = (
                self._errors_by_reason.get(reason, 0) + 1
            )
        hook = self._pressure_hook
        if hook is not None and exc is not None:
            try:
                hook(exc)
            except Exception:  # noqa: BLE001 — the governor must not break the writer
                pass
        self._rlog.warning("persist_error", fmt, *args)

    def _open_wal(self, truncate: bool = False) -> None:
        if self._wal is not None:
            try:
                self._wal.close()
            except OSError:
                pass
        # None until the open succeeds: a raise here must not leave _wal
        # pointing at the closed previous file (every write would then
        # fail with "closed file" until the next rotation).
        self._wal = None
        mode = "wb" if truncate else "ab"
        self._wal = open(self.wal_path, mode)
        if self._wal.tell() == 0:
            self._wal.write(MAGIC)
        # Reopening invalidates the reader-side layout assumption only on
        # truncate; on append the old file's last layout still stands, but
        # we cannot know it here — force a fresh layout record either way.
        self._fam_keys = {}
        self._fam_names = ()
        with self._stats_lock:
            self._stats["wal_bytes"] = self._wal.tell()

    def _write_item(self, item: tuple) -> None:
        kind = item[0]
        if kind == "breaker":
            self._write_breaker(item[1])
        elif kind == "samples":
            self._write_samples(item[1])

    def _ensure_wal(self) -> bool:
        """Reopen the WAL if a previous open failed — retried on every
        write attempt (not just at rotation), so persistence recovers as
        soon as the filesystem does. A record that cannot be written is a
        DROP (counted, alertable), never a silent discard."""
        if self._wal is not None:
            return True
        try:
            self._open_wal()
            return True
        except OSError as e:
            self._count_error("WAL reopen failed: %s", e, exc=e)
            self._count_dropped(self._io_reason(e))
            return False

    def _write_breaker(self, name: str) -> None:
        sup = self._supervisors.get(name)
        if sup is None or not self._ensure_wal():
            return
        doc = sup.breaker.export_state(wallclock=self._wallclock)
        doc.update({"t": "breaker", "scope": "source", "name": name})
        n = append_record(self._wal, b"J" + json.dumps(doc).encode())
        self._wal_dirty = True
        with self._stats_lock:
            self._stats["wal_records"] += 1
            self._stats["wal_bytes"] += n

    def _write_samples(self, snap: "Snapshot") -> None:
        # Pressure shedding (disk ladder): WAL-off drops everything, the
        # stride rung thins coverage to every Nth poll. Both are counted
        # as reason="shed" drops — deliberate, but never silent.
        self._stride_seq += 1
        if not self._wal_enabled:
            self._count_dropped("shed")
            return
        if self._wal_stride > 1 and self._stride_seq % self._wal_stride != 0:
            self._count_dropped("shed")
            return
        if not self._ensure_wal():
            return
        # Extract the tracked families from the (immutable) snapshot.
        fams: list[tuple[str, dict]] = []
        for name in self._metric_order:
            view = snap.samples_view(name)
            if view:
                fams.append((name, view))
        names = tuple(n for n, _ in fams)
        changed = names != self._fam_names
        vals = array("d")
        new_keys: list[tuple[str, tuple]] = []
        for name, view in fams:
            keys = tuple(view)
            if not changed and self._fam_keys.get(name) != keys:
                changed = True
            new_keys.append((name, keys))
            vals.extend(view.values())
        written = 0
        if changed:
            self._fam_names = names
            self._fam_keys = dict(new_keys)
            layout = {
                "t": "layout",
                "fams": [
                    {"m": name, "k": [list(k) for k in keys]}
                    for name, keys in new_keys
                ],
            }
            written += append_record(
                self._wal, b"J" + json.dumps(layout).encode()
            )
        ts = getattr(snap, "poll_timestamp", snap.timestamp)
        written += append_record(
            self._wal, b"S" + _F64.pack(ts) + vals.tobytes()
        )
        self._wal_dirty = True
        with self._stats_lock:
            self._stats["wal_records"] += 1 + (1 if changed else 0)
            self._stats["wal_samples"] += len(vals)
            self._stats["wal_bytes"] += written

    def _maybe_fsync(self) -> None:
        if self._wal is None or not self._wal_dirty:
            return
        now = self._clock()
        if self.fsync_interval_s > 0 and (
            now - self._last_fsync < self.fsync_interval_s
        ):
            return
        self._last_fsync = now
        t0 = self._clock()
        self._wal.flush()
        os.fsync(self._wal.fileno())
        self._wal_dirty = False
        with self._stats_lock:
            self._stats["fsyncs"] += 1
            self._stats["last_fsync_s"] = self._clock() - t0

    # A failed checkpoint retries on this cadence instead of waiting out a
    # full --state-snapshot-interval-s (the WAL-reopen discipline applied
    # to the checkpoint path: recover as soon as the filesystem does).
    SNAPSHOT_RETRY_S = 5.0

    def _maybe_rotate(self) -> None:
        now = self._clock()
        if self.snapshot_interval_s <= 0:
            return
        interval = self.snapshot_interval_s * self._snapshot_factor
        if self._snapshot_failed:
            # Failed-checkpoint retry cadence: every SNAPSHOT_RETRY_S, not
            # every writer iteration (a full disk must not be hammered
            # with checkpoint-sized writes 4x a second) and not the full
            # interval (recover as soon as the filesystem does).
            if now - self._last_snapshot_attempt < self.SNAPSHOT_RETRY_S:
                return
        elif now - self._last_rotate < interval:
            return
        self._last_snapshot_attempt = now
        try:
            self._write_snapshot()
        except Exception as e:  # noqa: BLE001 — a failed checkpoint must retry, not wait
            self._snapshot_failed = True
            # atomic_write may have left a partial .tmp behind (ENOSPC
            # mid-write): reclaim it now — a full disk is exactly when a
            # dead temp file hurts most.
            try:
                os.unlink(self.snapshot_path + ".tmp")
            except OSError:
                pass
            self._count_error("checkpoint rotation failed: %s (retrying "
                              "in %.0fs)", e, self.SNAPSHOT_RETRY_S, exc=e)
            return
        self._snapshot_failed = False
        self._last_rotate = now

    def _write_snapshot(self) -> None:
        """Full checkpoint: history rings + breaker states + exposition,
        write-temp → fsync → rename, then a fresh WAL."""
        import io

        buf = io.BytesIO()
        buf.write(MAGIC)
        rows = self._history.export_series() if self._history is not None else []
        max_wall = 0.0
        for _metric, _labels, samples in rows:
            if samples and samples[-1][0] > max_wall:
                max_wall = samples[-1][0]
        meta = {"t": "meta", "version": 1, "wall": self._wallclock(),
                "max_wall": max_wall, "series": len(rows)}
        append_record(buf, b"J" + json.dumps(meta).encode())
        for name, sup in self._supervisors.items():
            doc = sup.breaker.export_state(wallclock=self._wallclock)
            doc.update({"t": "breaker", "scope": "source", "name": name})
            append_record(buf, b"J" + json.dumps(doc).encode())
        for metric, labels, samples in rows:
            head = json.dumps({"m": metric, "l": labels}).encode()
            flat = array("d")
            for wall, value in samples:
                flat.append(wall)
                flat.append(value)
            append_record(
                buf,
                b"R" + struct.pack("<I", len(head)) + head + flat.tobytes(),
            )
        if self._exposition_fn is not None:
            try:
                snap = self._exposition_fn()
            except Exception:  # noqa: BLE001 — exposition is optional payload
                snap = None
            if snap is not None and snap.timestamp > 0:
                ts = getattr(snap, "poll_timestamp", snap.timestamp)
                append_record(buf, b"E" + _F64.pack(ts) + snap.encode())
        append_record(buf, b"J" + json.dumps({"t": "end"}).encode())
        atomic_write(self.snapshot_path, buf.getvalue())
        # The checkpoint covers everything; start a fresh WAL. A crash in
        # between leaves the old WAL alongside the new snapshot, which the
        # loader dedups via the checkpoint's max_wall.
        self._open_wal(truncate=True)
        self._last_fsync = self._clock()
        self._wal_dirty = False
        with self._stats_lock:
            self._stats["snapshots"] += 1
            self._stats["last_snapshot_wall"] = self._wallclock()


# ------------------------------------------------------ durable send buffer


def _scan_segment(path: str) -> tuple[list[tuple[int, int]], int, str | None]:
    """Scan one CRC-framed segment file; returns (records, valid_bytes,
    error). ``records`` is [(payload_offset, payload_len), ...] for the
    longest clean prefix — the offset/length pairs a consumer needs to
    re-read payloads lazily instead of materializing the whole backlog
    (the offsets-only view of :func:`_walk_records`)."""
    offsets, _payloads, valid, err = _walk_records(path, False)
    return offsets, valid, err


class WalBuffer:
    """Durable, segmented FIFO of opaque payload records — the reusable
    generalization of :class:`StatePersister`'s WAL machinery (same CRC32
    framing, rotation, and torn-write-tolerant replay) packaged as a queue
    with a persisted consumer cursor. Built for the remote-write egress
    send buffer (``tpu_pod_exporter.egress``); generic over payload bytes.

    Layout under ``dir``: ``seg-%08d.wal`` segment files (each MAGIC +
    CRC-framed records) plus ``cursor.json`` — ``{"seg": n, "rec": k}``
    means the first ``k`` records of segment ``n`` (and every earlier
    segment) are acknowledged and must NEVER be re-delivered, even across
    a crash: the cursor is written atomically (write-temp → fsync →
    rename) on every ack. Fully-acked segments are unlinked.

    Boot replay (:meth:`open`) tolerates torn writes: the newest segment
    is truncated at its last clean record (appends continue from there);
    an older segment corrupted mid-file keeps its clean prefix and the
    segments after it — corruption loses the torn records, never the
    buffer. A missing cursor segment means it was fully acked.

    Threading: one appender thread plus one consumer thread. The internal
    lock guards ONLY in-memory index state (entry deque, counters); all
    file I/O happens outside it, so neither thread can ever park the other
    inside a filesystem call — and the poll thread never touches this
    class at all.
    """

    SEGMENT_FMT = "seg-%08d.wal"
    CURSOR_NAME = "cursor.json"

    def __init__(self, path: str, segment_max_bytes: int = 4 << 20,
                 fsync: bool = True) -> None:
        self.dir = path
        self.segment_max_bytes = segment_max_bytes
        self.fsync_each = fsync
        self._lock = threading.Lock()
        # Pending (unacked) records, oldest first: (seg_no, rec_idx,
        # payload_offset, payload_len).
        self._entries: "deque[tuple[int, int, int, int]]" = deque()
        self._pending_bytes = 0
        self._acked_seg = -1   # cursor: segments <= this with...
        self._acked_rec = 0    # ...first _acked_rec records of _acked_seg acked
        # Lowest segment number that may still have a file on disk — the
        # unlink sweep's start. Advanced only past segments actually
        # removed (a failed unlink is retried on the next advance).
        self._min_seg = 0
        self._active_seg = 0
        self._active_count = 0   # records written to the active segment
        self._active_bytes = 0
        self._f: IO[bytes] | None = None
        self.corrupt_segments = 0
        self.errors: list[str] = []

    def _seg_path(self, seg: int) -> str:
        return os.path.join(self.dir, self.SEGMENT_FMT % seg)

    @property
    def _cursor_path(self) -> str:
        return os.path.join(self.dir, self.CURSOR_NAME)

    # ------------------------------------------------------------------ boot

    def open(self) -> dict:
        """Create the dir, load the cursor, replay segments into the
        pending index. Never raises on corruption (clean-prefix semantics);
        raises OSError only if the directory itself cannot be created."""
        os.makedirs(self.dir, exist_ok=True)
        cur_seg, cur_rec = -1, 0
        try:
            with open(self._cursor_path, encoding="utf-8") as f:
                doc = json.load(f)
            cur_seg = int(doc.get("seg", -1))
            cur_rec = max(int(doc.get("rec", 0)), 0)
        except FileNotFoundError:
            pass
        except Exception as e:  # noqa: BLE001 — a bad cursor restarts delivery, never boot
            self.errors.append(f"cursor unreadable ({e}); delivering from "
                               f"the oldest retained record")
        seg_nos = []
        try:
            for name in os.listdir(self.dir):
                if name.startswith("seg-") and name.endswith(".wal"):
                    try:
                        seg_nos.append(int(name[4:-4]))
                    except ValueError:
                        continue
        except OSError as e:
            self.errors.append(f"segment listing failed: {e}")
        seg_nos.sort()
        for seg in seg_nos:
            path = self._seg_path(seg)
            if seg < cur_seg:
                # Fully acked before the crash; reclaim the disk.
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            records, valid, err = _scan_segment(path)
            if err:
                self.corrupt_segments += 1
                self.errors.append(f"segment {seg}: {err}; kept the clean "
                                   f"prefix ({len(records)} records)")
                if seg == seg_nos[-1]:
                    # Newest segment: truncate the torn tail so appends
                    # continue from a clean boundary.
                    try:
                        os.truncate(path, valid)
                    except OSError as e:
                        self.errors.append(f"segment {seg}: truncate "
                                           f"failed ({e})")
            start = cur_rec if seg == cur_seg else 0
            for idx, (off, length) in enumerate(records):
                if idx < start:
                    continue
                self._entries.append((seg, idx, off, length))
                self._pending_bytes += _HDR.size + length
            if seg == seg_nos[-1]:
                if seg == cur_seg and len(records) < cur_rec:
                    # Corruption swallowed part of the ACKED region: new
                    # appends to this file would land below the cursor and
                    # be skipped as "already acked" on the next boot. Seal
                    # it and start a fresh segment instead.
                    self._active_seg = seg + 1
                    self._active_count = 0
                    self._active_bytes = 0
                else:
                    self._active_seg = seg
                    self._active_count = len(records)
                    self._active_bytes = valid if valid else len(MAGIC)
        if not seg_nos:
            # No segments on disk (fresh dir, or everything was acked and
            # unlinked). Start a FRESH segment past the cursor: record
            # indices within a file always start at 0 on rescan, so reusing
            # the cursor's segment number would make its "first rec acked"
            # offset swallow genuinely-new records after a restart.
            self._active_seg = cur_seg + 1 if cur_seg >= 0 else 0
            self._active_count = 0
            self._active_bytes = 0
        self._acked_seg, self._acked_rec = cur_seg, cur_rec
        self._min_seg = seg_nos[0] if seg_nos else self._active_seg
        return {"pending": len(self._entries),
                "pending_bytes": self._pending_bytes,
                "corrupt_segments": self.corrupt_segments,
                "errors": list(self.errors)}

    # ---------------------------------------------------------------- append

    def _ensure_writer(self) -> IO[bytes]:
        if self._f is not None:
            return self._f
        path = self._seg_path(self._active_seg)
        f = open(path, "ab")
        if f.tell() == 0:
            f.write(MAGIC)
            f.flush()
        self._active_bytes = f.tell()
        self._f = f
        return f

    def append(self, payload: bytes) -> None:
        """Durably append one record (raises OSError if the filesystem
        refuses — the caller counts a drop and retries on the next append,
        the StatePersister._ensure_wal discipline)."""
        try:
            if self._active_bytes >= self.segment_max_bytes and self._active_count > 0:
                self._rotate()
            f = self._ensure_writer()
            offset = self._active_bytes + _HDR.size
            n = append_record(f, payload)
            f.flush()
            if self.fsync_each:
                os.fsync(f.fileno())
        except OSError:
            # The failed write may have left a TORN partial record in the
            # segment; appending past it would strand every later record
            # behind the tear at the next rescan (clean-prefix semantics),
            # and rescan indices would no longer match the cursor's.
            # Seal the segment — already-indexed records sit before the
            # tear and stay readable — and start fresh on the next append.
            self._close_writer()
            self._active_seg += 1
            self._active_count = 0
            self._active_bytes = 0
            raise
        with self._lock:
            self._entries.append(
                (self._active_seg, self._active_count, offset, len(payload))
            )
            self._pending_bytes += n
        self._active_count += 1
        self._active_bytes += n

    def _rotate(self) -> None:
        self._close_writer()
        self._active_seg += 1
        self._active_count = 0
        self._active_bytes = 0
        self._ensure_writer()

    def seal_active(self) -> int:
        """Rotate the active segment off WITHOUT waiting for the next
        append, then reclaim every fully-acked segment this unblocks.

        Rotation is normally append-lazy, which is fine in steady state —
        but under disk pressure with a stalled producer the active
        segment can hold nothing but already-acked records, and those
        bytes stay on disk until an append that may never come. The ack
        sweep cannot touch them either (it never unlinks the active
        segment). Sealing makes the segment sweepable now. Returns the
        bytes reclaimed; 0 when the active segment was already empty."""
        with self._lock:
            if self._active_count == 0:
                return 0
            self._close_writer()
            self._active_seg += 1
            self._active_count = 0
            self._active_bytes = 0
            head_seg = (
                self._entries[0][0] if self._entries else self._active_seg
            )
        freed = 0
        for seg in range(self._min_seg, head_seg):
            if seg == self._active_seg:
                break
            path = self._seg_path(seg)
            try:
                size = os.path.getsize(path)
                os.unlink(path)
                freed += size
            except FileNotFoundError:
                pass
            except OSError:
                break
            self._min_seg = seg + 1
        else:
            self._min_seg = max(self._min_seg, head_seg)
        return freed

    def _close_writer(self) -> None:
        f = self._f
        self._f = None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    # --------------------------------------------------------------- consume

    def peek(self) -> bytes | None:
        """Oldest unacknowledged payload (None when drained). Re-reads from
        disk — the backlog is never held in memory."""
        return self._read_entry(0)

    def peek_last(self) -> bytes | None:
        """NEWEST pending payload (None when drained) — lets a consumer
        resume monotonic bookkeeping (e.g. the egress batch sequence) from
        the tail without materializing the whole backlog."""
        return self._read_entry(-1)

    def peek_at(self, index: int) -> bytes | None:
        """Pending payload at ``index`` from the head (None past the end)
        — lets a consumer walk the backlog (e.g. the egress age-cap scan)
        without advancing the cursor."""
        with self._lock:
            if index >= len(self._entries):
                return None
        return self._read_entry(index)

    def iter_payloads(self) -> Iterator[bytes]:
        """Every pending payload, oldest first, reading each segment file
        ONCE — the boot-replay path for consumers that rebuild in-memory
        state from the whole backlog (the fleet store's tier restore),
        where a peek_at() walk would reopen the segment per record. The
        entry index is snapshotted under the lock; all file I/O happens
        outside it. Unreadable/torn entries are skipped, never raised —
        replay keeps whatever prefix the disk still answers for."""
        with self._lock:
            entries = list(self._entries)
        cur_seg = -1
        f: IO[bytes] | None = None
        try:
            for seg, _idx, off, length in entries:
                if seg != cur_seg:
                    if f is not None:
                        f.close()
                    f = None
                    cur_seg = seg
                    try:
                        f = open(self._seg_path(seg), "rb")
                    except OSError:
                        continue
                if f is None:
                    continue
                try:
                    f.seek(off)
                    payload = f.read(length)
                except OSError:
                    continue
                if len(payload) == length:
                    yield payload
        finally:
            if f is not None:
                f.close()

    def trim_to_bytes(self, max_bytes: int) -> int:
        """Drop as many OLDEST records as needed to bring the pending
        byte total under ``max_bytes``, in ONE cursor advance (one fsynced
        cursor write however many records shed — a long-outage trim must
        not pay a cursor fsync per dropped batch). Returns the count."""
        with self._lock:
            count = 0
            excess = self._pending_bytes - max_bytes
            for _seg, _idx, _off, length in self._entries:
                if excess <= 0:
                    break
                excess -= _HDR.size + length
                count += 1
        if count == 0:
            return 0
        return self._advance(count)

    def _read_entry(self, index: int) -> bytes | None:
        with self._lock:
            if not self._entries:
                return None
            seg, _idx, off, length = self._entries[index]
        try:
            with open(self._seg_path(seg), "rb") as f:
                f.seek(off)
                payload = f.read(length)
        except OSError:
            return None
        return payload if len(payload) == length else None

    def ack(self) -> None:
        """Mark the oldest pending record delivered: advance + durably
        persist the cursor, unlink fully-acked segments. A crash right
        after this call must never re-deliver the record."""
        self._advance(1)

    def drop_oldest(self, n: int) -> int:
        """Advance the cursor past up to ``n`` oldest records WITHOUT
        delivery (backlog caps). Returns how many were dropped."""
        return self._advance(n)

    def _advance(self, n: int) -> int:
        advanced = 0
        with self._lock:
            while advanced < n and self._entries:
                seg, idx, _off, length = self._entries.popleft()
                self._pending_bytes -= _HDR.size + length
                self._acked_seg, self._acked_rec = seg, idx + 1
                advanced += 1
            head_seg = (
                self._entries[0][0] if self._entries else self._active_seg
            )
            acked_seg, acked_rec = self._acked_seg, self._acked_rec
        if advanced:
            try:
                atomic_write(
                    self._cursor_path,
                    json.dumps({"seg": acked_seg, "rec": acked_rec}).encode(),
                )
            except OSError as e:
                self.errors.append(f"cursor write failed: {e}")
            # Sweep EVERY fully-acked segment below the new head (a single
            # multi-segment advance — e.g. an age-cap trim after a long
            # outage — must reclaim all of them now, not at the next
            # boot). _min_seg advances only past successful unlinks so a
            # transient failure is retried on the next advance.
            for seg in range(self._min_seg, head_seg):
                if seg == self._active_seg:
                    break
                try:
                    os.unlink(self._seg_path(seg))
                except FileNotFoundError:
                    pass
                except OSError:
                    break
                self._min_seg = seg + 1
            else:
                self._min_seg = max(self._min_seg, head_seg)
        return advanced

    # ----------------------------------------------------------------- stats

    def pending(self) -> int:
        with self._lock:
            return len(self._entries)

    def pending_bytes(self) -> int:
        with self._lock:
            return self._pending_bytes

    def close(self) -> None:
        self._close_writer()


# ------------------------------------------------- aggregator breaker state


class _JsonStateFile:
    """Shared skeleton for the tiny keyed-JSON state files (atomic write,
    tolerant load, wall-stamped wrapper) — one crash discipline for every
    subclass, at a scale where a WAL would be overkill: state that changes
    on transitions, not per round. Subclasses set ``INNER_KEY`` (the one
    document key under the wall stamp) and ``WHAT`` (log wording)."""

    INNER_KEY = "state"
    WHAT = "state"

    def __init__(self, path: str,
                 wallclock: Callable[[], float] = time.time) -> None:
        self.path = path
        self._wallclock = wallclock
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        except OSError as e:
            log.error("%s dir for %s unusable: %s", self.WHAT, path, e)

    def _load_inner(self) -> dict:
        """The inner document ({} when absent/corrupt — callers rebuild
        from live inputs and the next save repairs the file)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                raise TypeError("top-level value must be an object")
            inner = doc.get(self.INNER_KEY, {})
            return inner if isinstance(inner, dict) else {}
        except FileNotFoundError:
            return {}
        except Exception as e:  # noqa: BLE001 — never refuse to start
            log.warning("%s %s unreadable (%s); rebuilding from live "
                        "inputs", self.WHAT, self.path, e)
            return {}

    def _save_inner(self, inner: dict) -> None:
        doc = {"wall": self._wallclock(), self.INNER_KEY: inner}
        try:
            atomic_write(self.path, json.dumps(doc).encode())
        except OSError as e:
            log.warning("%s save to %s failed: %s", self.WHAT, self.path, e)


class BreakerStateFile(_JsonStateFile):
    """Per-target circuit-breaker persistence for the aggregator tiers:
    a restart keeps its quarantines instead of re-learning every dead
    target from closed."""

    INNER_KEY = "targets"
    WHAT = "breaker state"

    def load(self) -> dict[str, dict]:
        return {
            str(k): v for k, v in self._load_inner().items()
            if isinstance(v, dict)
        }

    def save(self, states: dict[str, dict]) -> None:
        self._save_inner(states)


class ShardMapFile(_JsonStateFile):
    """Consistent-hash shard-map persistence
    (``tpu_pod_exporter.shard``): a restarted leaf or root resumes the
    assignment view it last acted on, so the first refresh after a
    restart counts real reshard moves instead of re-learning the whole
    map as churn."""

    INNER_KEY = "shard_map"
    WHAT = "shard map"

    def load(self) -> dict[str, object]:
        return self._load_inner()

    def save(self, doc: dict[str, object]) -> None:
        self._save_inner(doc)


# ------------------------------------------------------------ status helper


def state_dir_summary(state_dir: str) -> dict:
    """Lightweight on-disk summary for ``status --watch`` and /debug/vars:
    file sizes plus the checkpoint's age (mtime — no record parsing)."""
    out = {
        "state_dir": state_dir,
        "exists": os.path.isdir(state_dir),
        "snapshot_bytes": 0,
        "snapshot_age_s": None,
        "wal_bytes": 0,
        "total_bytes": 0,
    }
    if not out["exists"]:
        return out
    snap = os.path.join(state_dir, SNAPSHOT_NAME)
    wal = os.path.join(state_dir, WAL_NAME)
    try:
        st = os.stat(snap)
        out["snapshot_bytes"] = st.st_size
        out["snapshot_age_s"] = round(max(time.time() - st.st_mtime, 0.0), 1)
    except OSError:
        pass
    try:
        out["wal_bytes"] = os.stat(wal).st_size
    except OSError:
        pass
    out["total_bytes"] = out["snapshot_bytes"] + out["wal_bytes"]
    return out


# ------------------------------------------------------------------- checks


def _fsync_check(records: int, doubles: int, budget_s: float,
                 state_dir: str) -> int:
    """fsync-latency budget on the persistence hot path: append + fsync
    WAL-shaped records (the 256-chip samples payload is ~4.4k float64s)
    and fail if the p99 exceeds the budget — a state dir on a pathological
    filesystem (NFS, throttled EBS) must be caught by CI, not discovered
    as a wedged writer thread in production."""
    import statistics
    import tempfile

    own_dir = not state_dir
    if own_dir:
        state_dir = tempfile.mkdtemp(prefix="tpe-fsync-check-")
    os.makedirs(state_dir, exist_ok=True)
    path = os.path.join(state_dir, "fsync-check.bin")
    payload = b"S" + _F64.pack(time.time()) + array(
        "d", [1.0] * doubles
    ).tobytes()
    lat: list[float] = []
    with open(path, "wb") as f:
        f.write(MAGIC)
        for _ in range(records):
            append_record(f, payload)
            t0 = time.perf_counter()
            f.flush()
            os.fsync(f.fileno())
            lat.append(time.perf_counter() - t0)
    os.unlink(path)
    if own_dir:
        try:
            os.rmdir(state_dir)
        except OSError:
            pass
    lat.sort()
    p50 = statistics.median(lat)
    p99 = lat[min(int(len(lat) * 0.99), len(lat) - 1)]
    print(f"WAL fsync latency over {records} records of "
          f"{len(payload)} bytes: p50 {1e3 * p50:.2f}ms  "
          f"p99 {1e3 * p99:.2f}ms  max {1e3 * lat[-1]:.2f}ms  "
          f"(budget p99 {1e3 * budget_s:.0f}ms)")
    if p99 > budget_s:
        print("FAIL: fsync p99 exceeds budget — this filesystem cannot "
              "sustain the persistence hot path")
        return 1
    print("OK: fsync latency within budget")
    return 0


def _overhead_check(polls: int, chips: int, budget: float) -> int:
    """Persistence-on vs persistence-off POLL-THREAD CPU at the bench
    shape. The budget applies to the poll loop (persistence I/O runs on
    its own writer thread by design — the same exclusion as the history
    append); whole-process CPU, which includes the writer thread, is
    reported alongside for honesty. Interleaved segments with alternating
    order, same methodology as ``trace --overhead-check`` (whole-run A/B
    drowns in scheduler drift)."""
    import tempfile

    from tpu_pod_exporter.attribution.fake import FakeAttribution
    from tpu_pod_exporter.backend.fake import FakeBackend
    from tpu_pod_exporter.collector import Collector
    from tpu_pod_exporter.history import HistoryStore
    from tpu_pod_exporter.metrics import SnapshotStore
    from tpu_pod_exporter import utils

    state_dir = tempfile.mkdtemp(prefix="tpe-persist-overhead-")

    def make(with_persist: bool) -> tuple:
        history = HistoryStore(capacity=64, max_series=8192, retention_s=0.0)
        store = SnapshotStore()
        persister = None
        if with_persist:
            persister = StatePersister(
                state_dir, history=history,
                snapshot_interval_s=0.0,  # steady state: WAL only
                fsync_interval_s=1.0,
                exposition_fn=store.current,
            )
            persister.start()
        collector = Collector(
            FakeBackend(chips=chips), FakeAttribution(), store,
            history=history, persister=persister,
        )
        for _ in range(30):  # warm caches/layouts
            collector.poll_once()
        return collector, persister

    def segment(collector: Any, n: int) -> tuple[float, float]:
        t0 = time.thread_time()
        c0 = utils.process_cpu_seconds()
        for _ in range(n):
            collector.poll_once()
        return (time.thread_time() - t0,
                utils.process_cpu_seconds() - c0)

    (off, _), (on, persister) = make(False), make(True)
    seg_len = max(polls // 8, 10)
    t_off = t_on = p_off = p_on = 0.0
    try:
        for seg in range(16):
            order = ((on, True), (off, False)) if seg % 2 else ((off, False), (on, True))
            for collector, is_on in order:
                t, p = segment(collector, seg_len)
                if is_on:
                    t_on += t
                    p_on += p
                else:
                    t_off += t
                    p_off += p
    finally:
        if persister is not None:
            persister.close()
        import shutil

        shutil.rmtree(state_dir, ignore_errors=True)
    overhead = t_on / t_off - 1.0 if t_off > 0 else 0.0
    proc = p_on / p_off - 1.0 if p_off > 0 else 0.0
    print(f"poll-thread CPU over {16 * seg_len} interleaved polls/mode at "
          f"{chips} chips: persist-off {t_off:.3f}s, persist-on {t_on:.3f}s "
          f"→ overhead {100 * overhead:+.1f}% (budget {100 * budget:.0f}%)")
    print(f"whole-process CPU (incl. the persistence writer thread): "
          f"{p_off:.3f}s → {p_on:.3f}s ({100 * proc:+.1f}%)")
    if overhead > budget:
        print("FAIL: persistence poll-loop overhead exceeds budget")
        return 1
    print("OK: persistence poll-loop overhead within budget")
    return 0


# --------------------------------------------------------------- restart demo


def _wait_http(url: str, timeout_s: float) -> tuple[int, bytes]:
    """Poll a URL until it answers (any status); returns (status, body)."""
    import urllib.error
    import urllib.request

    deadline = time.monotonic() + timeout_s
    last: Exception | None = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()
        except Exception as e:  # noqa: BLE001 — not up yet
            last = e
            time.sleep(0.05)
    raise TimeoutError(f"{url} did not answer within {timeout_s:g}s: {last}")


def _get_json(url: str, timeout_s: float = 10.0) -> dict:
    status, body = _wait_http(url, timeout_s)
    if status != 200:
        raise RuntimeError(f"{url} → {status}: {body[:200]!r}")
    return json.loads(body)


def _restart_demo(ns: Any) -> int:
    """``make restart-demo``: the kill/restart chaos harness.

    Phase 1 runs a live exporter whose device source errors until the
    breaker is open, then a chaos ``kill`` injection SIGKILLs the process
    MID-POLL (no drain, no flush beyond the WAL's own fsync cadence).
    Phase 2 restarts on the same state dir and asserts (a) the history
    series is contiguous across the boundary — restored pre-kill samples
    meet fresh post-restart samples with no hole beyond the measured
    downtime plus one poll interval; (b) the device breaker carried its
    state over instead of re-learning the failure from closed. Phase 3
    corrupts the WAL mid-file and asserts the exporter still boots (cold
    or partial-warm) — torn state must never crash-loop the DaemonSet.
    """
    import shutil
    import signal as _signal
    import socket
    import subprocess
    import sys
    import tempfile

    own_dir = not ns.state_dir
    state_dir = ns.state_dir or tempfile.mkdtemp(prefix="tpe-restart-demo-")
    os.makedirs(state_dir, exist_ok=True)
    interval = 0.25
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    base_cmd = [
        sys.executable, "-m", "tpu_pod_exporter",
        "--host", "127.0.0.1", "--port", str(port),
        "--backend", "fake", "--fake-chips", "4",
        "--attribution", "none",
        "--interval-s", f"{interval:g}",
        "--state-dir", state_dir,
        "--state-snapshot-interval-s", "3",
        # fsync every WAL record: the demo's continuity assertion is
        # "gap ≤ one poll interval", which needs a durable tail.
        "--state-fsync-interval-s", "0",
        "--breaker-failures", "2",
        "--breaker-backoff-s", "0.5",
        "--breaker-backoff-max-s", "30",
        "--history-retention-s", "120",
        "--log-level", "warning",
    ]
    base = f"http://127.0.0.1:{port}"
    child = None
    rc = 1
    try:
        # ---- phase 1: poll, wedge the breaker open, SIGKILL mid-poll ----
        # Device calls 8.. all error (breaker opens after 2); the kill rule
        # sits first so call 14 — a half-open probe, mid-poll — dies by
        # SIGKILL. Deterministic: seeded chaos, probability 1 rules.
        spec = "kill:device:1:@14:x1,err:device:1:@8"
        print(f"phase 1: exporter on {base}, state dir {state_dir}")
        print(f"         chaos spec {spec} (SIGKILL mid-poll on device "
              f"call 14)")
        t_start = time.time()
        child = subprocess.Popen(
            base_cmd + ["--chaos-spec", spec, "--chaos-seed", "7"]
        )
        _wait_http(base + "/readyz", 30)
        child.wait(timeout=120)
        t_killed = time.time()
        if child.returncode != -_signal.SIGKILL:
            print(f"FAIL: expected death by SIGKILL, got rc={child.returncode}")
            return 1
        print(f"         killed by SIGKILL after {t_killed - t_start:.1f}s "
              f"(mid-poll, no drain)")

        # ---- phase 2: restart on the same state dir ----
        print("phase 2: restarting on the same state dir (no chaos)")
        child = subprocess.Popen(base_cmd)
        _wait_http(base + "/readyz", 30)
        t_up = time.time()
        downtime = t_up - t_killed
        dv = _get_json(base + "/debug/vars")
        persist = dv.get("persist") or {}
        if not persist.get("restored"):
            print(f"FAIL: /debug/vars reports no restored state: {persist}")
            return 1
        sup = (dv.get("supervisors") or {}).get("device") or {}
        errors_new = (dv.get("last_poll") or {}).get("errors") or []
        reopens = sup.get("reopens", 0)
        opens = (sup.get("transitions") or {}).get("open", 0)
        if sup.get("state") == "open" and reopens >= 1:
            print(f"         breaker carryover: device restored OPEN "
                  f"(reopens={reopens}, next probe in "
                  f"{sup.get('seconds_until_probe', 0):.1f}s) — no "
                  f"re-learning storm")
        elif opens >= 1 and not errors_new:
            # The open window elapsed during the restart and the (now
            # healthy) probe closed it — carryover is still proven by the
            # restored transition counters with zero fresh device errors.
            print(f"         breaker carryover: restored transitions "
                  f"(open={opens}) with no fresh device errors")
        else:
            print(f"FAIL: no breaker carryover: {sup}")
            return 1

        # History continuity across the boundary: let a few live polls land,
        # then walk tpu_exporter_up's samples over the whole window.
        time.sleep(6 * interval)
        doc = _get_json(
            base + f"/api/v1/query_range?metric=tpu_exporter_up"
                   f"&start={t_start - 5:.3f}&end={time.time() + 1:.3f}"
        )
        series = doc["data"]["result"]
        if len(series) != 1:
            print(f"FAIL: expected one tpu_exporter_up series, got "
                  f"{len(series)}")
            return 1
        ts = [t for t, _v in series[0]["values"]]
        pre = [t for t in ts if t <= t_killed]
        post = [t for t in ts if t > t_up - 1.0]
        if not pre or not post:
            print(f"FAIL: no samples on both sides of the restart "
                  f"(pre={len(pre)}, post={len(post)})")
            return 1
        tail_gap = t_killed - max(pre)
        gaps = [b - a for a, b in zip(ts, ts[1:])]
        worst = max(gaps)
        budget = downtime + 2 * interval + 0.5
        print(f"         history continuity: {len(ts)} samples, pre-kill "
              f"tail {tail_gap:.2f}s before SIGKILL (≤ {2 * interval + 0.2:.2f}s "
              f"budget), worst gap {worst:.2f}s (downtime {downtime:.2f}s "
              f"+ 2 intervals = {budget:.2f}s budget)")
        if tail_gap > 2 * interval + 0.2:
            print("FAIL: pre-kill history tail lost more than one poll")
            return 1
        if worst > budget:
            print("FAIL: history gap across the restart exceeds downtime "
                  "+ one poll interval")
            return 1
        # The same continuity for a LABELED series: restored and live
        # samples must land in ONE series per chip, not fork into two
        # identically-labeled series (the restore-key discipline in
        # HistoryStore.restore_series). tpu_exporter_up alone cannot catch
        # that — its label set is empty, so both key shapes coincide.
        doc = _get_json(
            base + f"/api/v1/query_range?metric=tpu_hbm_used_bytes"
                   f"&match%5Bchip_id%5D=0"
                   f"&start={t_start - 5:.3f}&end={time.time() + 1:.3f}"
        )
        chip_series = doc["data"]["result"]
        if len(chip_series) != 1:
            print(f"FAIL: chip 0's HBM history forked into "
                  f"{len(chip_series)} series across the restart")
            return 1
        cts = [t for t, _v in chip_series[0]["values"]]
        if not (
            any(t <= t_killed for t in cts)
            and any(t > t_up - 1.0 for t in cts)
        ):
            print("FAIL: chip 0's HBM series lacks samples on both sides "
                  "of the restart")
            return 1
        print(f"         labeled-series continuity: chip 0 HBM is ONE "
              f"series with {len(cts)} samples spanning the restart")

        # ---- phase 3: corrupt the WAL mid-file; boot must survive ----
        print("phase 3: SIGKILL again, corrupt wal.bin mid-file, restart")
        child.send_signal(_signal.SIGKILL)
        child.wait(timeout=30)
        wal_path = os.path.join(state_dir, WAL_NAME)
        size = os.path.getsize(wal_path)
        with open(wal_path, "r+b") as f:
            f.seek(max(size // 2, len(MAGIC)))
            f.write(b"\xde\xad\xbe\xef" * 8)
        child = subprocess.Popen(base_cmd)
        status, _body = _wait_http(base + "/readyz", 30)
        dv = _get_json(base + "/debug/vars")
        persist = dv.get("persist") or {}
        print(f"         boot survived the corrupt WAL (readyz {status}, "
              f"restored={persist.get('restored')}) — truncated at the "
              f"torn record, no crash loop")
        print("restart-demo: OK (kill mid-poll → warm restore → "
              "contiguous history, breaker carryover, corrupt-WAL boot)")
        rc = 0
    finally:
        if child is not None and child.poll() is None:
            child.terminate()
            try:
                child.wait(timeout=10)
            except subprocess.TimeoutExpired:
                child.kill()
        if own_dir and rc == 0:
            shutil.rmtree(state_dir, ignore_errors=True)
        elif rc != 0:
            print(f"state dir kept for inspection: {state_dir}")
    return rc


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="tpu-pod-exporter-persist",
        description="Restart-survivability harness: kill/restart demo, "
                    "fsync budget check, persistence overhead check.",
    )
    p.add_argument("--restart-demo", action="store_true",
                   help="SIGKILL a live exporter mid-poll, restart it on "
                        "the same --state-dir, assert history continuity "
                        "+ breaker carryover + corrupt-WAL boot")
    p.add_argument("--state-dir", default="",
                   help="state dir for --restart-demo/--fsync-check "
                        "(default: a temp dir, removed on success)")
    p.add_argument("--fsync-check", action="store_true",
                   help="measure WAL append+fsync latency and fail past "
                        "--budget-ms")
    p.add_argument("--records", type=int, default=100)
    p.add_argument("--doubles", type=int, default=4400,
                   help="float64s per record (256-chip tracked-set shape)")
    p.add_argument("--budget-ms", type=float, default=50.0)
    p.add_argument("--overhead-check", action="store_true",
                   help="measure persistence-on vs -off poll-thread CPU "
                        "and fail past --budget")
    p.add_argument("--polls", type=int, default=200)
    p.add_argument("--chips", type=int, default=256)
    p.add_argument("--budget", type=float, default=0.02,
                   help="max tolerated fractional poll-thread CPU overhead "
                        "(0.02 = 2%%)")
    ns = p.parse_args(argv)

    if ns.restart_demo:
        return _restart_demo(ns)
    if ns.fsync_check:
        return _fsync_check(ns.records, ns.doubles, ns.budget_ms / 1e3,
                            ns.state_dir)
    if ns.overhead_check:
        return _overhead_check(ns.polls, ns.chips, ns.budget)
    p.error("need --restart-demo, --fsync-check, or --overhead-check")
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
