import sys

from tpu_pod_exporter.app import main

if __name__ == "__main__":
    sys.exit(main())
