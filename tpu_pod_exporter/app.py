"""Wiring: config → backend + attribution → collector loop → HTTP server.

The analog of the reference's ``main()`` (``main.go:38-72``) but with
dependency injection, backend auto-detection, SIGTERM drain, and no
``log.Fatal`` anywhere on the steady-state path.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Any

from tpu_pod_exporter import utils
from tpu_pod_exporter.attribution import AttributionProvider
from tpu_pod_exporter.attribution.fake import FakeAttribution
from tpu_pod_exporter.backend import DeviceBackend
from tpu_pod_exporter.backend.fake import FakeBackend
from tpu_pod_exporter.collector import Collector, CollectorLoop
from tpu_pod_exporter.config import ExporterConfig
from tpu_pod_exporter.metrics import HistogramStore, SnapshotStore
from tpu_pod_exporter.metrics import schema
from tpu_pod_exporter.server import MetricsServer
from tpu_pod_exporter.topology import detect_host_topology

log = logging.getLogger("tpu_pod_exporter.app")


def build_backend(cfg: ExporterConfig) -> DeviceBackend:
    choice = cfg.backend
    if choice == "auto":
        # Production preference: libtpu metrics service (does not open the
        # devices) > nothing. The jax backend is never auto-selected: it
        # grabs the TPU runtime and would starve the workload.
        from tpu_pod_exporter.backend.discovery import local_chip_count

        if local_chip_count() > 0:
            try:
                return _build_named_backend("libtpu", cfg)
            except Exception as e:  # noqa: BLE001
                # Auto-detection must degrade, not crash-loop the DaemonSet:
                # a monitoring agent that dies on init monitors nothing.
                log.error("auto-selected libtpu backend unavailable (%s); "
                          "serving 0-chip surface", e)
                return FakeBackend(chips=0)
        log.info("no local TPU devices found; using 0-chip fake backend")
        return FakeBackend(chips=0)
    # Explicit selection fails fast — a typo'd flag should be loud.
    return _build_named_backend(choice, cfg)


def _maybe_record(backend: DeviceBackend, cfg: ExporterConfig) -> DeviceBackend:
    if cfg.record_to:
        from tpu_pod_exporter.backend.recorded import RecordingBackend

        return RecordingBackend(backend, cfg.record_to)
    return backend


def _build_named_backend(choice: str, cfg: ExporterConfig) -> DeviceBackend:
    if choice == "recorded":
        from tpu_pod_exporter.backend.recorded import RecordedBackend

        return RecordedBackend(cfg.recording_path)
    if choice == "fake":
        return FakeBackend(chips=cfg.fake_chips)
    if choice == "jax":
        from tpu_pod_exporter.backend.jaxdev import JaxDeviceBackend

        return JaxDeviceBackend()
    if choice == "libtpu":
        from tpu_pod_exporter.backend.libtpu import LibtpuMetricsBackend

        return LibtpuMetricsBackend(addr=cfg.libtpu_metrics_addr)
    if choice == "nvml":
        from tpu_pod_exporter.backend.nvml import (
            NvmlBackend,
            SimulatedNvmlDriver,
            sim_driver_from_spec,
        )

        driver = None
        if cfg.nvml_sim_spec:
            import json

            with open(cfg.nvml_sim_spec, encoding="utf-8") as f:
                driver = sim_driver_from_spec(json.load(f))
        elif cfg.nvml_sim_gpus > 0:
            driver = SimulatedNvmlDriver(cfg.nvml_sim_gpus)
        # driver=None → the real pynvml binding (BackendError naming the
        # sim flags when the wheel is absent — explicit selection is loud).
        return NvmlBackend(driver=driver)
    raise ValueError(f"unknown backend: {choice}")


def build_attribution(cfg: ExporterConfig,
                      resource_name: str | None = None) -> AttributionProvider:
    choice = cfg.attribution
    if resource_name is None:
        resource_name = cfg.resource_name
    if choice == "auto":
        if os.path.exists(cfg.podresources_socket):
            choice = "podresources"
        elif os.path.exists(cfg.checkpoint_path):
            choice = "checkpoint"
        else:
            log.info("no kubelet attribution source found; attribution disabled")
            return FakeAttribution()
        try:
            return _build_named_attribution(choice, cfg, resource_name)
        except Exception as e:  # noqa: BLE001
            log.error("auto-selected %s attribution unavailable (%s); "
                      "attribution disabled", choice, e)
            return FakeAttribution()
    return _build_named_attribution(choice, cfg, resource_name)


def _build_named_attribution(choice: str, cfg: ExporterConfig,
                             resource_name: str | None = None) -> AttributionProvider:
    if resource_name is None:
        resource_name = cfg.resource_name
    if choice in ("fake", "none"):
        return FakeAttribution()
    if choice == "podresources":
        from tpu_pod_exporter.attribution.podresources import PodResourcesAttribution

        return PodResourcesAttribution(
            socket_path=cfg.podresources_socket, resource_name=resource_name
        )
    if choice == "checkpoint":
        from tpu_pod_exporter.attribution.checkpoint import CheckpointAttribution

        return CheckpointAttribution(
            path=cfg.checkpoint_path, uid_source=_build_uid_source(cfg)
        )
    raise ValueError(f"unknown attribution: {choice}")


def _build_uid_source(cfg: ExporterConfig) -> Any:
    """UID→name resolver for the checkpoint path (None = uid-keyed series).
    A static file wins over the kubelet /pods endpoint when both are set."""
    if cfg.uid_map_file:
        from tpu_pod_exporter.attribution.uidmap import StaticUidMap

        return StaticUidMap(cfg.uid_map_file)
    if cfg.kubelet_pods_url:
        from tpu_pod_exporter.attribution.uidmap import (
            DEFAULT_CA_FILE,
            DEFAULT_TOKEN_FILE,
            KubeletPodsUidMap,
        )

        token_file = cfg.kubelet_token_file
        ca_file = cfg.kubelet_ca_file
        if cfg.kubelet_pods_url.startswith("https:"):
            if not ca_file and os.path.exists(DEFAULT_CA_FILE):
                ca_file = DEFAULT_CA_FILE
            # Auto-default the bearer token ONLY when TLS will actually be
            # verified (CA resolved, or the operator explicitly opted out):
            # a token over unverified TLS is a leaked cluster credential.
            # Explicitly-configured tokens are policed by KubeletPodsUidMap
            # itself, which refuses the combination at startup.
            if not token_file and os.path.exists(DEFAULT_TOKEN_FILE):
                if ca_file or cfg.kubelet_insecure_tls:
                    token_file = DEFAULT_TOKEN_FILE
                else:
                    log.warning(
                        "service-account token present but no CA bundle at "
                        "%s; fetching %s WITHOUT auth rather than sending "
                        "the token over unverified TLS (set "
                        "--kubelet-ca-file or --kubelet-insecure-tls)",
                        DEFAULT_CA_FILE, cfg.kubelet_pods_url,
                    )
        return KubeletPodsUidMap(
            cfg.kubelet_pods_url,
            token_file=token_file or None,
            ca_file=ca_file or None,
            refresh_s=cfg.kubelet_pods_refresh_s,
            insecure_tls=cfg.kubelet_insecure_tls,
        )
    return None


class ExporterApp:
    """Everything needed to run (and cleanly stop) one exporter instance.

    Also the harness object for multi-instance tests: N apps with distinct
    fakes model N hosts of a v5p slice (SURVEY.md §4.4).
    """

    def __init__(
        self,
        cfg: ExporterConfig,
        backend: DeviceBackend | None = None,
        attribution: AttributionProvider | None = None,
    ) -> None:
        self.cfg = cfg
        self.store = SnapshotStore()
        self.backend = _maybe_record(
            backend if backend is not None else build_backend(cfg), cfg
        )
        # GPU-family backends join attribution on the GPU resource name
        # (nvidia.com/gpu device-plugin UUIDs) — one DaemonSet codebase,
        # the node pool's backend flag selects the family end to end.
        self.resource_name = (
            cfg.gpu_resource_name
            if getattr(self.backend, "family", "tpu") == "gpu"
            else cfg.resource_name
        )
        self.attribution = (
            attribution if attribution is not None
            else build_attribution(cfg, self.resource_name)
        )
        topo = detect_host_topology(
            accelerator=cfg.accelerator,
            slice_name=cfg.slice_name,
            host=cfg.node_name,
            worker_id=cfg.worker_id,
            multislice_group=cfg.multislice_group,
        )
        self.topology = topo  # effective (detected) values, for /debug/vars
        scanner = None
        if cfg.process_metrics:
            from tpu_pod_exporter.procscan import ProcScanner

            scanner = ProcScanner(
                proc_root=cfg.proc_root,
                full_scan_every=cfg.process_full_scan_every,
            )
        self.process_scanner = scanner
        # Deterministic fault injection (TEST ONLY, --chaos-spec): wraps the
        # sources BEFORE supervision so injected hangs/errors exercise the
        # real deadline/breaker/reconnect path.
        self.chaos = {}
        if cfg.chaos_spec:
            from tpu_pod_exporter.chaos import apply_chaos

            log.warning("chaos injection active (spec=%r seed=%d) — "
                        "test-only configuration", cfg.chaos_spec, cfg.chaos_seed)
            self.backend, self.attribution, scanner, self.chaos = apply_chaos(
                cfg.chaos_spec, cfg.chaos_seed,
                self.backend, self.attribution, scanner,
            )
            self.process_scanner = scanner
        # Source supervision (tpu_pod_exporter.supervisor): per-phase
        # deadlines + circuit breakers + breaker-gated reconnects.
        # --phase-deadline-s 0 disables (direct in-thread calls).
        self.supervisors = {}
        if cfg.phase_deadline_s > 0:
            from tpu_pod_exporter.supervisor import (
                CircuitBreaker,
                SourceSupervisor,
            )

            def _breaker() -> CircuitBreaker:
                # --breaker-failures 0 disables the breaker (same contract
                # as the aggregator flag) while keeping phase deadlines: an
                # unreachable threshold means the state machine never
                # leaves closed. Backoffs are clamped sane rather than
                # crashing startup on a zero/inverted pair.
                threshold = (
                    cfg.breaker_failures if cfg.breaker_failures > 0
                    else (1 << 30)
                )
                base = (
                    cfg.breaker_backoff_s if cfg.breaker_backoff_s > 0 else 1.0
                )
                return CircuitBreaker(
                    failure_threshold=threshold,
                    backoff_base_s=base,
                    backoff_max_s=max(cfg.breaker_backoff_max_s, base),
                )

            # Late-bound fns (lambda: self.backend...) so tests that
            # monkeypatch .sample/.snapshot on the instances keep working;
            # reconnect = close(): both gRPC clients lazily rebuild their
            # channel on the next call, so close-then-call IS the reconnect.
            self.supervisors["device"] = SourceSupervisor(
                "device",
                lambda: self.backend.sample(),
                reconnect=lambda: self.backend.close(),
                deadline_s=cfg.phase_deadline_s,
                breaker=_breaker(),
            )
            self.supervisors["attribution"] = SourceSupervisor(
                "attribution",
                lambda: self.attribution.snapshot(),
                reconnect=lambda: self.attribution.close(),
                deadline_s=cfg.phase_deadline_s,
                breaker=_breaker(),
            )
            if self.process_scanner is not None:
                self.supervisors["process_scan"] = SourceSupervisor(
                    "process_scan",
                    lambda: self.process_scanner.scan(),
                    reconnect=None,  # procfs has no channel to replace
                    deadline_s=cfg.phase_deadline_s,
                    breaker=_breaker(),
                )
        # Flight-recorder history (--history-retention-s 0 disables): ring
        # capacity is one sample per poll over the retention window, capped
        # so a sub-second interval cannot balloon the preallocation. Hard
        # memory bound: max_series x capacity x 24 bytes, allocated only
        # for series actually present (~32 MB at 256 chips; ceiling ~59 MB
        # at the 300 s / 1 s / 8192-series defaults).
        self.history = None
        if cfg.history_retention_s > 0:
            from tpu_pod_exporter.history import HistoryStore, parse_tier_spec

            capacity = max(
                2, min(int(cfg.history_retention_s / cfg.interval_s) + 1, 4096)
            )
            self.history = HistoryStore(
                capacity=capacity,
                max_series=cfg.history_max_series,
                retention_s=cfg.history_retention_s,
                # Downsample tiers (--history-tiers): a bad spec must fail
                # startup loudly, same as any other malformed flag.
                tiers=parse_tier_spec(cfg.history_tiers),
            )
        # End-to-end poll tracing (tpu_pod_exporter.trace): per-phase spans
        # on every poll, a slow-poll stack profiler, and a bounded trace
        # ring exported at /debug/trace. On by default (--trace off
        # disables; the collector then runs the exact untraced code path).
        self.trace = None
        self.tracer = None
        if cfg.trace:
            from tpu_pod_exporter.trace import StackSampler, Tracer, TraceStore

            self.trace = TraceStore(max_traces=cfg.trace_max_traces)
            self.tracer = Tracer(
                self.trace,
                slow_poll_s=cfg.trace_slow_poll_s,
                sampler=(
                    StackSampler() if cfg.trace_slow_poll_s > 0 else None
                ),
            )
        # Crash-safe state persistence (tpu_pod_exporter.persist): periodic
        # checksummed checkpoint + WAL under --state-dir covering the
        # history rings, breaker states, and the last published exposition.
        # Restored state is applied HERE, before the first poll: breakers
        # resume their quarantine, history answers across the restart, and
        # the restored exposition serves immediately (warm start).
        # --state-dir "" (the default) cleanly disables the whole layer.
        self.persister = None
        self._warm_snapshot = None
        if cfg.state_dir:
            from tpu_pod_exporter.persist import (
                RestoredSnapshot,
                StatePersister,
            )

            self.persister = StatePersister(
                cfg.state_dir,
                history=self.history,
                supervisors=self.supervisors,
                # Late-bound: whatever is being served when a checkpoint
                # rotates (live snapshot, or the restored one during warm).
                exposition_fn=lambda: self.store.current(),
                snapshot_interval_s=cfg.state_snapshot_interval_s,
                fsync_interval_s=cfg.state_fsync_interval_s,
            )
            restored = self.persister.load()
            if restored.exposition:
                self._warm_snapshot = RestoredSnapshot(
                    restored.exposition, restored.exposition_ts
                )
        # Remote-write egress (tpu_pod_exporter.egress): WAL-buffered push
        # shipping of the tracked families to --egress-url. The durable
        # send buffer replays at construction (a backlog left by a crash
        # resumes delivery from the fsynced ack cursor — zero loss, no
        # acked re-send). --egress-url "" (the default) disables.
        self.shipper = None
        if cfg.egress_url:
            from tpu_pod_exporter.egress import (
                RemoteWriteShipper,
                build_breaker,
            )

            egress_breaker = build_breaker(
                cfg.egress_breaker_failures,
                cfg.egress_breaker_backoff_s,
                cfg.egress_breaker_backoff_max_s,
            )
            t = topo.labels()
            self.shipper = RemoteWriteShipper(
                cfg.egress_url,
                cfg.egress_dir,
                interval_s=cfg.egress_interval_s,
                timeout_s=cfg.egress_timeout_s,
                max_backlog_mb=cfg.egress_max_backlog_mb,
                max_backlog_age_s=cfg.egress_max_backlog_age_s,
                breaker=egress_breaker,
                # Label-less self-series (tpu_exporter_up) must not collide
                # across hosts in the shared receiving TSDB; series that
                # already carry topology labels keep theirs.
                extra_labels={
                    "host": t["host"],
                    "slice_name": t["slice_name"],
                },
            )
            self.shipper.load()
        # Resource-pressure governor (tpu_pod_exporter.pressure): explicit
        # degradation ladders for disk (--state-max-disk-mb + reported
        # ENOSPC over the persist WAL/checkpoint and egress send buffer)
        # and memory (--memory-budget-mb over trace ring + history rings).
        # None when nothing is governable; runs on its own thread so the
        # poll loop never pays the disk-usage walk.
        from tpu_pod_exporter.pressure import build_exporter_governor

        self.governor = build_exporter_governor(
            cfg,
            persister=self.persister,
            shipper=self.shipper,
            history=self.history,
            trace_store=self.trace,
        )
        # Scrape-latency distribution: handler threads observe, the
        # collector emits it into each snapshot (one poll behind, which is
        # fine for a cumulative histogram).
        scrape_hist = HistogramStore(schema.TPU_EXPORTER_SCRAPE_DURATION_HIST)
        self.collector = Collector(
            backend=self.backend,
            attribution=self.attribution,
            store=self.store,
            topology=topo,
            resource_name=self.resource_name,
            attribution_max_stale_s=cfg.attribution_max_stale_s,
            legacy_metrics=cfg.legacy_metrics,
            process_scanner=scanner,
            # Deferred attribute read: self.server is constructed below;
            # the first poll (in start()) runs after __init__ completes.
            scrape_rejects_fn=lambda: dict(self.server.scrape_rejects),
            loop_overruns_fn=lambda: self.loop.overruns,
            scrape_duration_hist=scrape_hist,
            history=self.history,
            supervisors=self.supervisors,
            tracer=self.tracer,
            persister=self.persister,
            shipper=self.shipper,
            governor=self.governor,
            client_write_timeouts_fn=lambda: self.server.write_timeouts["total"],
            render_splice=cfg.render_splice,
        )
        self.loop = CollectorLoop(self.collector, interval_s=cfg.interval_s)
        # Liveness trips when the poll thread stops swapping snapshots
        # (wedged device runtime): generous multiple of the interval so slow
        # polls don't flap, floored for sub-second intervals.
        self.server = MetricsServer(
            self.store,
            host=cfg.host,
            port=cfg.port,
            debug_vars=self._debug_vars,
            health_max_age_s=max(10.0 * cfg.interval_s, 10.0),
            max_concurrent_scrapes=cfg.max_concurrent_scrapes,
            max_scrapes_per_s=cfg.max_scrapes_per_s,
            scrape_observer=scrape_hist.observe,
            history=self.history,
            trace=self.trace,
            debug_addr=cfg.debug_addr,
            live_fn=self._live_check,
            ready_detail_fn=self._ready_detail,
            client_write_timeout_s=cfg.client_write_timeout_s,
            warm_fn=self._warm_state,
            max_open_connections=cfg.max_open_connections,
            max_requests_per_client=cfg.max_requests_per_client,
            max_workers=cfg.server_max_workers,
        )

    def _warm_state(self) -> dict | None:
        """Non-None while the restored pre-restart snapshot is still what
        /metrics serves (warm start, no live poll yet); the /readyz body
        then reports state="warm" with the restored data's age."""
        snap = self._warm_snapshot
        if snap is None:
            return None
        if self.store.current() is not snap:
            # Warm period over (first live poll swapped in): release the
            # restored body and its lazy gzip/OpenMetrics caches — low-MB
            # of dead memory otherwise held for the DaemonSet pod's life.
            self._warm_snapshot = None
            return None
        return {
            "restored_poll_age_s": round(time.time() - snap.poll_timestamp, 3),
            "snapshot_stale_s": round(snap.stale_s, 3),
        }

    def _live_check(self) -> str | None:
        """Immediate liveness failure when the poll loop is truly dead (its
        one supervised restart is spent) — /healthz must not wait out
        health_max_age_s to report a thread that will never poll again."""
        if self.loop.dead:
            return (
                f"poll loop dead (thread died twice; "
                f"{self.loop.restarts} restart(s) used)"
            )
        return None

    def _ready_detail(self) -> dict:
        """Degraded-source detail for the /readyz JSON body: any source
        whose breaker has (re-)opened across several probes, plus the
        egress shipper's receiver state once it is degraded the same way.
        Detail only — the HTTP status stays governed by first-poll
        completion (a down RECEIVER must never pull the exporter out of
        rotation; its scrapes are exactly the fallback)."""
        degraded = [
            {
                "source": source,
                "breaker_state": st["state"],
                "reopens": st["reopens"],
                "abandoned": st["abandoned"],
                "reconnects": st["reconnects"],
                "next_probe_in_s": round(st["seconds_until_probe"], 3),
            }
            for source, sup in self.supervisors.items()
            if (st := sup.stats())["degraded"]
        ]
        out: dict = {"degraded_sources": degraded} if degraded else {}
        if self.shipper is not None:
            try:
                detail = self.shipper.ready_detail()
                if detail["degraded"] or detail["backlog_batches"]:
                    out["egress"] = detail
            except Exception:  # noqa: BLE001 — detail must not break probes
                pass
        return out

    def _debug_vars(self) -> dict:
        """Introspection payload for /debug/vars (SURVEY.md §5: per-phase
        tracing beyond what fits in Prometheus gauges)."""
        stats = self.collector.last_stats
        snap = self.store.current()  # bind once: series + age must agree
        out = {
            "config": {
                "interval_s": self.cfg.interval_s,
                "backend": getattr(self.backend, "name", "?"),
                "attribution": getattr(self.attribution, "name", "?"),
                "resource_name": self.resource_name,
                "max_concurrent_scrapes": self.cfg.max_concurrent_scrapes,
                "max_scrapes_per_s": self.cfg.max_scrapes_per_s,
                # Effective (detected) membership, not the raw override —
                # the GKE auto-detected case would otherwise show "".
                "multislice_group": self.topology.multislice_group,
                "num_slices": self.topology.num_slices,
            },
            "last_poll": {
                "ok": stats.ok,
                "trace_id": stats.trace_id,  # join key into /debug/trace
                "errors": list(stats.errors),
                "skipped": list(stats.skipped),
                "device_read_s": stats.device_read_s,
                "attribution_s": stats.attribution_s,
                "process_scan_s": stats.process_scan_s,
                "join_s": stats.join_s,
                "publish_s": stats.publish_s,
                "total_s": stats.total_s,
            },
            "loop_overruns": self.loop.overruns,
            "loop_restarts": self.loop.restarts,
            "loop_dead": self.loop.dead,
            "series": snap.series_count,
            "snapshot_age_s": max(time.time() - snap.timestamp, 0.0),
            "scrape_rejects": dict(self.server.scrape_rejects),
            # Event-loop serving counters (slow-client drops, inline vs
            # worker split) — the RUNBOOK's first stop for scrape-path
            # triage.
            "server": self.server.stats(),
        }
        render = self.collector.render_stats()
        if render is not None:
            # Splice-render cache: generation bumps on layout churn,
            # revision on any byte change; spliced_cells vs rebuilt_blocks
            # shows whether the incremental path is actually incremental.
            out["render"] = render
        if self.process_scanner is not None:
            out["process_scanner"] = {
                "full_scans": self.process_scanner.full_scans,
                "verify_scans": self.process_scanner.verify_scans,
            }
        if self.history is not None:
            out["history"] = self.history.stats()
        if self.persister is not None:
            from tpu_pod_exporter.persist import state_dir_summary

            out["persist"] = {
                **self.persister.stats(),
                # Nested, not splatted: restore-time counts (wal_records,
                # errors) would otherwise shadow the live writer counters
                # under the same names.
                "restore": dict(self.persister.restored_info),
                "dir": state_dir_summary(self.cfg.state_dir),
                "warm": self._warm_state() is not None,
            }
        if self.shipper is not None:
            from tpu_pod_exporter.egress import egress_dir_summary

            out["egress"] = {
                **self.shipper.stats(),
                "dir": egress_dir_summary(self.cfg.egress_dir),
            }
        if self.governor is not None:
            out["pressure"] = {
                **self.governor.stats(),
                # The per-component byte breakdown the memory ladder's
                # shed decision sums — same numbers, one source.
                "memory_components": self.governor.memory_component_bytes(),
            }
        out["client_write_timeouts"] = self.server.write_timeouts["total"]
        out["connections"] = dict(self.server.conn_stats)
        if self.trace is not None:
            out["trace"] = self.trace.stats()
        if self.supervisors:
            out["supervisors"] = {
                source: sup.stats() for source, sup in self.supervisors.items()
            }
        if self.chaos:
            out["chaos"] = {
                source: {"calls": w.calls, "injected": w.injected[-50:]}
                for source, w in self.chaos.items()
            }
        return out

    @property
    def port(self) -> int:
        return self.server.port

    def start(self) -> None:
        if self.governor is not None:
            self.governor.start()
        if self.persister is not None:
            self.persister.start()
        if self.shipper is not None:
            # Before the first poll: a restart with a backlog starts
            # draining immediately, even while the first live poll runs.
            self.shipper.start()
        if self._warm_snapshot is not None:
            # Warm start: serve the restored exposition IMMEDIATELY and let
            # the first live poll run on the loop thread — blocking serving
            # on a first poll against a possibly-still-wedged source is
            # exactly the gap persistence exists to close. /readyz reports
            # "warm" until the loop's first snapshot swap replaces it.
            warm = self._warm_snapshot
            self.store.swap(warm)
            log.info(
                "warm start: serving restored exposition (%.1fs stale, "
                "%d series) while the first live poll runs",
                warm.stale_s, warm.series_count,
            )
            self.loop.start()
            self.server.start()

            # Release the restored body (plus its lazy gzip/OpenMetrics
            # caches — low-MB at 256 chips) as soon as the first live poll
            # swaps it out. A watcher thread, not an HTTP-path hook: with
            # no kubelet probing /readyz the memory would otherwise stay
            # pinned for the process lifetime. Exits after one live poll.
            def _release_warm() -> None:
                poll_s = min(max(self.cfg.interval_s, 0.05), 1.0)
                while self.store.current() is warm and not self.loop.dead:
                    time.sleep(poll_s)
                if self.store.current() is not warm:
                    self._warm_snapshot = None
                # else: the loop died while still warm — keep the warm
                # marker truthful (readyz stays "warm"); /healthz's dead-
                # loop 503 is already driving a pod restart.

            threading.Thread(
                target=_release_warm, name="tpu-exporter-warm-release",
                daemon=True,
            ).start()
        else:
            # Cold start: first poll synchronously so /readyz flips as soon
            # as we listen.
            self.collector.poll_once()
            self.loop.start()
            self.server.start()
        log.info("serving on :%d every %.3fs", self.port, self.cfg.interval_s)

    def stop(self) -> None:
        self.loop.stop()
        self.server.stop()
        self.collector.close()
        if self.persister is not None:
            # SIGTERM drain: final fsynced checkpoint (history + breakers +
            # the exposition being served), so a rolling update warm-starts
            # with zero staleness. After loop.stop() no poll can enqueue.
            self.persister.close()
        if self.shipper is not None:
            # Undelivered batches stay durably buffered; the restarted
            # process resumes them from the ack cursor (no drain wait — a
            # down receiver must not stall the SIGTERM grace period).
            self.shipper.close()
        if self.governor is not None:
            self.governor.close()
        if self.tracer is not None:
            self.tracer.close()


def main(argv: list[str] | None = None) -> int:
    cfg = ExporterConfig.from_args(argv)
    utils.setup_logging(cfg.log_level, cfg.log_format)
    app = ExporterApp(cfg)
    stop = threading.Event()

    def _on_signal(signum: int, frame: object) -> None:  # noqa: ARG001
        log.info("signal %d: draining", signum)
        stop.set()

    # Real SIGTERM drain for DaemonSet rolling updates (reference has none —
    # its only exits are log.Fatalf/panic, SURVEY.md §3.4).
    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    app.start()
    stop.wait()
    app.stop()
    return 0
