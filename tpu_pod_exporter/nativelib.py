"""Single loader for libtpumon.so — shared by device discovery and the
exposition renderer.

One CDLL handle, one candidate search (``TPE_NATIVE_LIB`` env override →
in-repo build → system path), one ABI check. Any load/symbol/ABI surprise
disables the native path; callers always have a pure-Python fallback, so a
bad .so can never take the exporter down.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from pathlib import Path

log = logging.getLogger("tpu_pod_exporter.nativelib")

ABI_VERSION = 4

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _candidates():
    env = os.environ.get("TPE_NATIVE_LIB")
    if env:
        yield Path(env)
    repo_root = Path(__file__).resolve().parent.parent
    yield repo_root / "native" / "libtpumon.so"
    yield Path("/usr/local/lib/libtpumon.so")


def load() -> ctypes.CDLL | None:
    global _lib, _tried
    # Load outcomes are collected here and logged AFTER the lock releases
    # (lock-io discipline): log handlers do stream I/O, and the first
    # caller to race in during startup must not serialize behind it.
    notes: list[tuple[int, str, tuple]] = []
    with _lock:
        lib = _load_locked(notes)
    for level, fmt, args in notes:
        log.log(level, fmt, *args)
    return lib


def _load_locked(notes: list) -> ctypes.CDLL | None:
    """Candidate search + ABI check; caller holds ``_lock``. Messages are
    appended to ``notes`` as (level, fmt, args) instead of logged."""
    global _lib, _tried
    if not _tried:
        _tried = True
        for cand in _candidates():
            if not cand.exists():
                continue
            try:
                lib = ctypes.CDLL(str(cand))
                lib.tpumon_abi_version.restype = ctypes.c_int
                if lib.tpumon_abi_version() != ABI_VERSION:
                    notes.append((
                        logging.WARNING, "%s: ABI version mismatch, ignoring",
                        (cand,),
                    ))
                    continue
                lib.tpumon_count_devices.restype = ctypes.c_int
                lib.tpumon_count_devices.argtypes = [ctypes.c_char_p]
                lib.tpumon_list_devices.restype = ctypes.c_int
                lib.tpumon_list_devices.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_char_p,
                    ctypes.c_long,
                ]
                lib.tpumon_render.restype = ctypes.c_long
                lib.tpumon_render.argtypes = [
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.POINTER(ctypes.c_double),
                    ctypes.c_long,
                    ctypes.c_char_p,
                    ctypes.c_long,
                ]
                lib.tpumon_render2.restype = ctypes.c_long
                lib.tpumon_render2.argtypes = [
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.POINTER(ctypes.c_int),
                    ctypes.POINTER(ctypes.c_double),
                    ctypes.c_long,
                    ctypes.c_char_p,
                    ctypes.c_long,
                ]
                lib.tpumon_scan_proc.restype = ctypes.c_long
                lib.tpumon_scan_proc.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_char_p,
                    ctypes.c_char_p,
                    ctypes.c_long,
                ]
                lib.tpumon_parse_layout.restype = ctypes.c_long
                lib.tpumon_parse_layout.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_long,
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.POINTER(ctypes.c_int),
                    ctypes.POINTER(ctypes.c_ubyte),
                    ctypes.c_long,
                    ctypes.POINTER(ctypes.c_double),
                ]
                _lib = lib
                notes.append((
                    logging.INFO, "libtpumon loaded from %s", (cand,),
                ))
                break
            except (OSError, AttributeError) as e:
                notes.append((
                    logging.WARNING, "cannot load native lib %s: %s",
                    (cand, e),
                ))
    return _lib


def reset_for_tests() -> None:
    global _lib, _tried
    with _lock:
        _lib = None
        _tried = False
