"""Remote-write egress — push shipping that survives receiver outages.

The exporter (and the aggregator) are pull-only: fan-in is capped at
whatever scrapes them, and a dead scraper silently loses telemetry.
Production fleets push into a central TSDB. This module turns the node
agent into a complete telemetry shipper by composing the two hard pieces
the repo already owns — ``persist.py``'s crash-safe WAL machinery and
``supervisor.py``'s breaker/backoff discipline — into an egress path where
an unreachable, hanging, or flapping receiver degrades gracefully and
drops nothing:

- :class:`RemoteWriteShipper` hangs off the same snapshot-swap hook the
  history store uses. Each swap enqueues a **delta-aware** batch (full
  series on a layout-generation change, changed samples otherwise) into a
  durable on-disk send buffer (:class:`~tpu_pod_exporter.persist.WalBuffer`
  under ``--egress-dir``: CRC32-framed segments, rotation, torn-write-
  tolerant replay, a fsynced ack cursor), so a receiver outage or a
  process restart loses zero samples — on reconnect the backlog drains
  oldest-first under ``--egress-max-backlog-mb`` / ``-age-s`` caps.
- The sender thread speaks Prometheus **remote-write** (protobuf +
  snappy; both codecs vendored stdlib-only below — no new runtime deps)
  behind a :class:`~tpu_pod_exporter.supervisor.CircuitBreaker`: timeouts,
  connection errors, 5xx and 429 open it with exponential backoff +
  jitter; half-open sends a single probe batch; other 4xx are **poison**
  (counted, skipped — a batch the receiver rejects must not wedge the
  queue behind it).
- Backpressure is **counted, not blocking**: the poll/scrape path's entire
  egress cost is one non-blocking queue put (the persist discipline); a
  wedged receiver grows an on-disk backlog and a metric, never a poll.

Everything is auditable from the exposition (``tpu_exporter_egress_*``,
``metrics/schema.py``) and from ``status`` (the ``egress:`` footer).

CLI (``python -m tpu_pod_exporter.egress``):

- ``--demo``        — ``make egress-demo``: a seeded chaos receiver
  (hangs, 5xx, 429s, a mid-body truncation) wedges a live exporter's
  egress, the breaker opens, the backlog grows on disk, a SIGKILL lands
  mid-send, and the restarted shipper drains the backlog with **zero
  loss and no acked re-send** — while scrape/poll p99 stay within budget
  of an egress-off baseline throughout the wedge.
- ``--drain-check`` — backlog-drain budget: a simulated N-second receiver
  outage's backlog must drain within budget once the receiver returns.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import socket
import struct
import threading
import time
import urllib.error
import urllib.request
from typing import TYPE_CHECKING, Any, Callable, Mapping, Sequence

from tpu_pod_exporter.metrics import HistogramStore, schema
from tpu_pod_exporter.persist import WalBuffer, atomic_write
from tpu_pod_exporter.supervisor import (
    DEGRADED_AFTER_REOPENS,
    STATE_VALUES,
    CircuitBreaker,
    CLOSED,
)
from tpu_pod_exporter.utils import RateLimitedLogger

if TYPE_CHECKING:  # typing only — no runtime import cost
    from tpu_pod_exporter.metrics.registry import MetricSpec, Snapshot

log = logging.getLogger("tpu_pod_exporter.egress")

# Remote-write wire headers (Prometheus remote-write 1.0).
CONTENT_TYPE = "application/x-protobuf"
REMOTE_WRITE_VERSION = "0.1.0"
# Exactly-once bookkeeping for the chaos receiver / demo: the batch's
# durable sequence number rides a private header real receivers ignore.
SEQ_HEADER = "X-Tpe-Egress-Seq"

STATUS_NAME = "egress-status.json"

# Segment size the send buffer rotates at while the disk-pressure ladder's
# egress rung is applied: small segments mean acked records (the bulk of a
# healthy shipper's on-disk footprint between 4 MB rotations) become
# reclaimable within one ack sweep instead of one rotation — steady-state
# disk then holds roughly one segment plus the pending backlog. Rotation
# per ~8 KB is ~one extra open/close per batch at exposition batch sizes:
# trivial, and only paid while the disk is actually under pressure.
SHED_SEGMENT_BYTES = 8 << 10

_U32 = struct.Struct("<I")


# --------------------------------------------------------------- snappy codec
# Vendored snappy BLOCK format (github.com/google/snappy format_description):
# a varint uncompressed length, then literal/copy elements. Stdlib-only —
# the container has no python-snappy, and a hard dep for one encoder would
# violate the no-new-runtime-deps rule. The encoder is a greedy 4-byte-hash
# matcher emitting 2-byte-offset copies (a strict subset of valid snappy,
# decodable by every real receiver); the decoder handles every element type
# (the chaos receiver and tests round-trip through it).

_MAX_LITERAL = 1 << 16


def _emit_literal(out: bytearray, data: bytes, start: int, end: int) -> None:
    i = start
    while i < end:
        n = min(end - i, _MAX_LITERAL)
        if n <= 60:
            out.append((n - 1) << 2)
        elif n <= 256:
            out.append(60 << 2)
            out.append(n - 1)
        else:
            out.append(61 << 2)
            out += (n - 1).to_bytes(2, "little")
        out += data[i:i + n]
        i += n


def snappy_compress(data: bytes) -> bytes:
    """Snappy block-format compression (literals + 2-byte-offset copies)."""
    out = bytearray()
    # Preamble: uncompressed length, little-endian varint.
    n = len(data)
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    table: dict[bytes, int] = {}
    i = 0
    lit = 0
    limit = len(data) - 4
    while i <= limit:
        key = data[i:i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is None or i - cand > 0xFFFF:
            i += 1
            continue
        # Extend the match (the 4-byte key already matches by identity).
        mlen = 4
        maxlen = min(len(data) - i, 64)
        while mlen < maxlen and data[cand + mlen] == data[i + mlen]:
            mlen += 1
        _emit_literal(out, data, lit, i)
        out.append(2 | ((mlen - 1) << 2))  # copy, 2-byte offset
        out += (i - cand).to_bytes(2, "little")
        i += mlen
        lit = i
    _emit_literal(out, data, lit, len(data))
    return bytes(out)


def snappy_decompress(data: bytes) -> bytes:
    """Snappy block-format decompression (all element types)."""
    # Preamble varint.
    expected = 0
    shift = 0
    i = 0
    while True:
        if i >= len(data):
            raise ValueError("snappy: truncated preamble")
        b = data[i]
        i += 1
        expected |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
        if shift > 35:
            raise ValueError("snappy: preamble varint too long")
    out = bytearray()
    n = len(data)
    while i < n:
        tag = data[i]
        typ = tag & 3
        if typ == 0:  # literal
            length = (tag >> 2) + 1
            i += 1
            if length > 60:
                extra = length - 60
                if i + extra > n:
                    raise ValueError("snappy: truncated literal length")
                length = int.from_bytes(data[i:i + extra], "little") + 1
                i += extra
            if i + length > n:
                raise ValueError("snappy: truncated literal")
            out += data[i:i + length]
            i += length
            continue
        if typ == 1:  # copy, 1-byte offset
            length = 4 + ((tag >> 2) & 0x7)
            if i + 2 > n:
                raise ValueError("snappy: truncated copy-1")
            offset = ((tag >> 5) << 8) | data[i + 1]
            i += 2
        elif typ == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            if i + 3 > n:
                raise ValueError("snappy: truncated copy-2")
            offset = int.from_bytes(data[i + 1:i + 3], "little")
            i += 3
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            if i + 5 > n:
                raise ValueError("snappy: truncated copy-4")
            offset = int.from_bytes(data[i + 1:i + 5], "little")
            i += 5
        if offset == 0 or offset > len(out):
            raise ValueError("snappy: copy offset out of range")
        for _ in range(length):  # may overlap — byte-at-a-time is the spec
            out.append(out[-offset])
    if len(out) != expected:
        raise ValueError(
            f"snappy: length mismatch (got {len(out)}, want {expected})"
        )
    return bytes(out)


# ------------------------------------------------------- remote-write protobuf
# Hand-rolled wire encoding of the four-message prometheus remote-write
# schema (WriteRequest{timeseries=1} / TimeSeries{labels=1,samples=2} /
# Label{name=1,value=2} / Sample{value=1,timestamp=2}) — ~60 lines beats a
# vendored _pb2 module for a fixed, tiny schema, and the decoder gives the
# chaos receiver and the tests an independent read-back path.


def _pb_varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _pb_len(field: int, payload: bytes) -> bytes:
    return _pb_varint((field << 3) | 2) + _pb_varint(len(payload)) + payload


def _pb_label(name: str, value: str) -> bytes:
    return (
        _pb_len(1, name.encode("utf-8")) + _pb_len(2, value.encode("utf-8"))
    )


def _pb_sample(value: float, ts_ms: int) -> bytes:
    return (
        _pb_varint((1 << 3) | 1) + struct.pack("<d", value)
        + _pb_varint(2 << 3) + _pb_varint(ts_ms)
    )


def encode_write_request(
    series: Sequence[tuple[Sequence[tuple[str, str]], Sequence[tuple[float, int]]]],
) -> bytes:
    """[(labels, samples)] → WriteRequest bytes. Labels are sorted by name
    (the remote-write contract); samples are (value, unix-ms)."""
    out = bytearray()
    for labels, samples in series:
        ts = bytearray()
        for name, value in sorted(labels):
            ts += _pb_len(1, _pb_label(name, value))
        for value, ts_ms in samples:
            ts += _pb_len(2, _pb_sample(value, ts_ms))
        out += _pb_len(1, bytes(ts))
    return bytes(out)


def _pb_scan(data: bytes, i: int, end: int) -> tuple[int, int, int]:
    """One field header + varint/skip bookkeeping → (field, wire, i)."""
    key = 0
    shift = 0
    while True:
        if i >= end:
            raise ValueError("protobuf: truncated field key")
        b = data[i]
        i += 1
        key |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
    return key >> 3, key & 7, i


def _pb_read_varint(data: bytes, i: int, end: int) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        if i >= end:
            raise ValueError("protobuf: truncated varint")
        b = data[i]
        i += 1
        n |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return n, i


def parse_write_request(
    data: bytes,
) -> list[tuple[dict[str, str], list[tuple[float, int]]]]:
    """WriteRequest bytes → [(labels dict, [(value, unix-ms)])] — the
    decoder side for the chaos receiver and round-trip tests."""
    out: list[tuple[dict[str, str], list[tuple[float, int]]]] = []
    i, end = 0, len(data)
    while i < end:
        field, wire, i = _pb_scan(data, i, end)
        if field != 1 or wire != 2:
            raise ValueError(f"WriteRequest: unexpected field {field}/{wire}")
        length, i = _pb_read_varint(data, i, end)
        ts_end = i + length
        if ts_end > end:
            raise ValueError("protobuf: truncated TimeSeries")
        labels: dict[str, str] = {}
        samples: list[tuple[float, int]] = []
        while i < ts_end:
            f2, w2, i = _pb_scan(data, i, ts_end)
            ln2, i = _pb_read_varint(data, i, ts_end)
            sub_end = i + ln2
            if sub_end > ts_end:
                raise ValueError("protobuf: truncated submessage")
            if f2 == 1 and w2 == 2:  # Label
                name = value = ""
                while i < sub_end:
                    f3, _w3, i = _pb_scan(data, i, sub_end)
                    ln3, i = _pb_read_varint(data, i, sub_end)
                    if i + ln3 > sub_end:
                        raise ValueError("protobuf: truncated string")
                    text = data[i:i + ln3].decode("utf-8")
                    i += ln3
                    if f3 == 1:
                        name = text
                    elif f3 == 2:
                        value = text
                labels[name] = value
            elif f2 == 2 and w2 == 2:  # Sample
                val = 0.0
                ts_ms = 0
                while i < sub_end:
                    f3, w3, i = _pb_scan(data, i, sub_end)
                    if w3 == 1:
                        if i + 8 > sub_end:
                            raise ValueError("protobuf: truncated fixed64")
                        (num,) = struct.unpack_from("<d", data, i)
                        i += 8
                        if f3 == 1:
                            val = num
                    else:
                        num_i, i = _pb_read_varint(data, i, sub_end)
                        if f3 == 2:
                            ts_ms = num_i
                samples.append((val, ts_ms))
            else:
                i = sub_end
        i = ts_end
        out.append((labels, samples))
    return out


# ------------------------------------------------------------- batch framing
# One WalBuffer record per batch: b"B" + <u32 header_len> + JSON header +
# raw (uncompressed) WriteRequest bytes. The proto is stored uncompressed
# so a backlog is inspectable with parse_write_request; snappy is applied
# per send attempt (cheap at batch scale, and a resend recompresses).


def frame_batch(seq: int, wall: float, kind: str, samples: int,
                proto: bytes, mono: float = 0.0) -> bytes:
    # ``mono`` is the writer's MONOTONIC clock at enqueue: meaningful only
    # within the process that wrote it (seqs above the boot seq), where it
    # gives an exact, NTP-step-immune batch age. Pre-restart batches age
    # on their wall stamp instead (see RemoteWriteShipper._head_age).
    head = json.dumps(
        {"seq": seq, "wall": wall, "kind": kind, "samples": samples,
         "mono": mono}
    ).encode()
    return b"B" + _U32.pack(len(head)) + head + proto


def parse_batch(payload: bytes) -> tuple[dict[str, Any], bytes]:
    """→ (header dict, proto bytes); raises ValueError on a foreign frame."""
    if payload[:1] != b"B" or len(payload) < 5:
        raise ValueError("not an egress batch record")
    (jlen,) = _U32.unpack_from(payload, 1)
    head = json.loads(payload[5:5 + jlen])
    return head, payload[5 + jlen:]


# --------------------------------------------------------------- the shipper


def default_send(url: str, body: bytes, headers: Mapping[str, str],
                 timeout_s: float) -> int:
    """POST one compressed batch; returns the HTTP status. Raises on
    connection-level failure (timeout, refused, reset)."""
    req = urllib.request.Request(
        url, data=body, headers=dict(headers), method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:  # noqa: S310 — operator-supplied receiver
        resp.read()
        return int(resp.status)


# Always included in delta batches (fresh timestamp every batch): the
# liveness series a receiving TSDB alerts on.
_HEARTBEAT_METRICS = ("tpu_exporter_up", "tpu_aggregator_target_up")


def build_breaker(failures: int, backoff_s: float,
                  backoff_max_s: float) -> CircuitBreaker:
    """The ONE egress-breaker construction (exporter app + aggregator CLI
    both call it — duplicated clamping had the same flag values configure
    different breakers per tier): ``failures <= 0`` disables via an
    unreachable threshold (the source-breaker contract), zero/inverted
    backoffs clamp sane instead of crashing startup."""
    base = backoff_s if backoff_s > 0 else 1.0
    return CircuitBreaker(
        failure_threshold=failures if failures > 0 else (1 << 30),
        backoff_base_s=base,
        backoff_max_s=max(backoff_max_s, base),
    )


# The exporter ships exactly the families the history recorder tracks (the
# same "what matters for forensics" judgment); the aggregator ships its
# rollup surface. Both orders are sorted for deterministic batch layouts.
def exporter_egress_metrics() -> tuple[str, ...]:
    from tpu_pod_exporter.history import HISTORY_TRACKED_METRICS

    return tuple(sorted(HISTORY_TRACKED_METRICS))


def aggregator_egress_metrics() -> tuple[str, ...]:
    return tuple(sorted(
        spec.name for spec in schema.AGGREGATE_EGRESS_SPECS
    ))


class RemoteWriteShipper:
    """WAL-buffered Prometheus remote-write sender for snapshot swaps.

    Three threads touch it, with strictly bounded coupling:

    - the POLL thread calls :meth:`on_snapshot` — one non-blocking queue
      put of an immutable snapshot reference (drops + counts when the
      writer stalls; polling never waits on egress);
    - the WRITER thread extracts the delta, frames the batch, and appends
      it durably to the :class:`~tpu_pod_exporter.persist.WalBuffer`
      (fsync per batch — batches are ~1/s, and the zero-loss contract
      needs a durable tail), then enforces the backlog byte/age caps;
    - the SENDER thread drains the buffer oldest-first behind the
      breaker: 2xx acks (fsynced cursor — never re-sent, even across a
      crash), timeout/connection/5xx/429 are failures that open the
      breaker with expo backoff + jitter, other 4xx are poison (counted,
      acked-without-delivery so the queue never wedges).
    """

    def __init__(
        self,
        url: str,
        egress_dir: str,
        metrics: Sequence[str] | None = None,
        interval_s: float = 1.0,
        timeout_s: float = 5.0,
        max_backlog_mb: float = 64.0,
        max_backlog_age_s: float = 3600.0,
        breaker: CircuitBreaker | None = None,
        extra_labels: Mapping[str, str] | None = None,
        send: Callable[[str, bytes, Mapping[str, str], float], int] = default_send,
        queue_max: int = 4,
        full_sync_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
        wallclock: Callable[[], float] = time.time,
    ) -> None:
        self.url = url
        self.egress_dir = egress_dir
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.max_backlog_bytes = int(max_backlog_mb * (1 << 20))
        self.max_backlog_age_s = max_backlog_age_s
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self._extra_labels = dict(extra_labels or {})
        self._send = send
        self._clock = clock
        self._wallclock = wallclock
        self._metric_order = tuple(
            metrics if metrics is not None else exporter_egress_metrics()
        )
        spec_map: dict[str, "MetricSpec"] = {}
        for spec in (*schema.ALL_SPECS, *schema.AGGREGATE_SPECS,
                     *schema.HISTORY_SPECS, *schema.PERSIST_SPECS,
                     *schema.EGRESS_SPECS, *schema.FLEET_QUERY_SPECS):
            spec_map[spec.name] = spec
        self._spec_map = spec_map
        self._rlog = RateLimitedLogger(log)
        self.buffer = WalBuffer(egress_dir)
        self.send_hist = HistogramStore(
            schema.TPU_EXPORTER_EGRESS_SEND_SECONDS_HIST
        )
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=queue_max)
        self._work = threading.Event()     # sender wake-up on append
        self._stop = threading.Event()
        self._writer: threading.Thread | None = None
        self._sender: threading.Thread | None = None
        # Writer-thread state (single owner).
        self._last_values: dict[tuple[str, tuple[str, ...]], float] = {}
        self._last_keys: frozenset[tuple[str, tuple[str, ...]]] = frozenset()
        self._last_batch_wall = 0.0
        # Periodic full resync: delta-only shipping of STATIC gauges would
        # let the receiving TSDB mark them stale (Prometheus drops series
        # 5 min after their last sample); a full batch at this cadence
        # keeps every series fresh. Must stay under that 5 min window.
        self.full_sync_s = full_sync_s
        self._last_full_wall = 0.0
        self._seq = 0
        # Sender-thread cache of the head batch's header (age accounting):
        # (seq, wall stamp, monotonic ENQUEUE stamp from the frame header).
        # Batches created by THIS process age on their enqueue-monotonic
        # stamp — exact, and an NTP step (clock_step chaos) can neither
        # inflate their age into an age-cap mass-drop nor hide a genuinely
        # stale slowly-draining backlog; only batches restored from a
        # pre-restart backlog age on wall time (their true age genuinely
        # predates this process, and their mono stamp belongs to a dead
        # clock).
        self._head_meta: tuple[int, float, float] | None = None
        self._boot_seq = 0  # seqs <= this predate this process (see load)
        # Resource-pressure shed (tpu_pod_exporter.pressure, disk ladder
        # rung "egress_compact"): under disk pressure the buffer rotates
        # TINY segments — acked-but-unrotated bytes are the bulk of a
        # healthy shipper's disk footprint, and small segments let the
        # ack sweep reclaim them promptly (no data loss) — and the
        # pending-backlog byte cap tightens (bounded, counted loss, only
        # while the receiver is down). Flag flipped by the governor
        # thread, read by the writer/sender threads.
        self._disk_pressure = False
        self._normal_segment_bytes = self.buffer.segment_max_bytes
        self._pressure_hook: Callable[[BaseException], bool] | None = None
        self._stats_lock = threading.Lock()
        self._stats: dict[str, Any] = {
            "enqueued_batches": 0,
            "enqueued_samples": 0,
            "sent_batches": 0,
            "sent_samples": 0,
            "failed_sends": 0,
            "dropped": {"backlog": 0, "poison": 0, "queue": 0, "corrupt": 0},
            "last_send_latency_s": 0.0,
            "last_send_ok_wall": 0.0,
            "last_error": "",
        }
        self._open_errors: list[str] = []

    # ------------------------------------------------------------------ boot

    def load(self) -> dict:
        """Open + replay the send buffer; resumes the durable batch
        sequence. Never refuses to start: a hopeless dir records the error
        and the shipper runs degraded (every append drops, counted)."""
        try:
            info = self.buffer.open()
        except OSError as e:
            self._open_errors.append(str(e))
            log.error("egress dir %s unusable (%s); egress will drop until "
                      "it recovers", self.egress_dir, e)
            return {"pending": 0, "errors": [str(e)]}
        dropped = 0
        max_seq = 0
        # Seqs are monotonic in queue order, so the NEWEST pending batch
        # carries the highest one; a head corrupted into unparseability is
        # dropped so delivery can proceed (counted below).
        tail = self.buffer.peek_last()
        if tail is not None:
            try:
                head, _proto = parse_batch(tail)
                max_seq = int(head.get("seq", 0))
            except (ValueError, KeyError):
                pass
        while self.buffer.pending():
            payload = self.buffer.peek()
            if payload is None:
                break
            try:
                head, _proto = parse_batch(payload)
                with self._stats_lock:
                    self._head_meta = (int(head.get("seq", 0)),
                                       float(head.get("wall", 0.0)),
                                       float(head.get("mono", 0.0)))
                break
            except (ValueError, KeyError, TypeError):
                self.buffer.drop_oldest(1)
                dropped += 1
        # Belt over the scan's braces: the status sidecar (written on
        # every send attempt and after every cap-drop — i.e. whenever the
        # pending set can shrink toward empty) carries the last issued
        # seq, covering the drained-buffer restart where no pending batch
        # is left to read the sequence from. No extra fsync: the sidecar
        # is written anyway for the `status` footer.
        try:
            with open(os.path.join(self.egress_dir, STATUS_NAME),
                      encoding="utf-8") as f:
                max_seq = max(max_seq, int(json.load(f).get("seq", 0)))
        except FileNotFoundError:
            pass
        except Exception:  # noqa: BLE001 — a torn sidecar restarts from the scan
            pass
        self._seq = max_seq
        # Everything at or below this seq predates this process: its age
        # is genuinely its wall age. Batches ABOVE it age monotonically
        # (clock-step fence — see _head_age).
        self._boot_seq = max_seq
        corrupt = info.get("corrupt_segments", 0) + dropped
        if corrupt:
            with self._stats_lock:
                self._stats["dropped"]["corrupt"] += corrupt
        if info.get("pending"):
            log.info("egress backlog restored from %s: %d batch(es), %d "
                     "bytes pending (resuming at seq %d)", self.egress_dir,
                     info["pending"], info.get("pending_bytes", 0),
                     self._seq)
        return info

    def start(self) -> None:
        if self._writer is not None:
            return
        self._writer = threading.Thread(
            target=self._writer_run, name="tpu-egress-writer", daemon=True
        )
        self._sender = threading.Thread(
            target=self._sender_run, name="tpu-egress-sender", daemon=True
        )
        self._writer.start()
        self._sender.start()

    # ------------------------------------------------------------- poll side

    def on_snapshot(self, snap: "Snapshot") -> int:
        """The poll thread's entire egress cost: one non-blocking put of
        the (immutable) snapshot. Returns 1 when queued, 0 when dropped."""
        if self._writer is None:
            return 0
        try:
            self._q.put_nowait(snap)
            return 1
        except queue.Full:
            with self._stats_lock:
                self._stats["dropped"]["queue"] += 1
            self._rlog.warning(
                "egress_queue",
                "egress writer queue full; dropping a snapshot from the "
                "egress stream — polling is unaffected",
            )
            return 0

    # ----------------------------------------------------------- writer side

    def _writer_run(self) -> None:
        while not self._stop.is_set():
            try:
                snap = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            try:
                self._write_snapshot(snap)
            except Exception as e:  # noqa: BLE001 — egress must survive anything
                self._rlog.warning("egress_write", "egress batch build "
                                   "failed: %s", e)

    def _extract(self, snap: "Snapshot") -> dict[tuple[str, tuple[str, ...]], float]:
        current: dict[tuple[str, tuple[str, ...]], float] = {}
        for name in self._metric_order:
            view = snap.samples_view(name)
            if view:
                for key, value in view.items():
                    current[(name, key)] = value
        return current

    def _same_poll_instant(self, wall: float) -> bool:
        """True when a batch for this exact poll instant was already
        framed. Every sample timestamp in a batch derives solely from the
        snapshot's poll wall, so framing the same instant twice emits
        identical (series, timestamp) samples under a fresh seq — the
        receiving ledger counts them as duplicates and exactly-once is
        gone. Reached when the poller stalls (root death, wedged store)
        and the same frozen snapshot keeps arriving: with ``interval_s``
        of 0 the interval gate passes (0 < 0 is false) and the heartbeat
        ride-along would re-send at the frozen timestamp every cycle."""
        return wall == self._last_batch_wall

    def _write_snapshot(self, snap: "Snapshot") -> None:
        wall = float(getattr(snap, "poll_timestamp", snap.timestamp))
        if self._same_poll_instant(wall):
            return
        if wall < self._last_batch_wall:
            # Wall clock stepped BACKWARDS (NTP correction): without this
            # clamp the interval gate `wall - last < interval` stays
            # negative until the clock catches back up and egress silently
            # stops shipping for the whole step width. Resync the
            # reference points to the new timeline instead.
            self._last_batch_wall = wall
            self._last_full_wall = min(self._last_full_wall, wall)
        if wall - self._last_batch_wall < self.interval_s:
            return
        current = self._extract(snap)
        if not current:
            return
        keys = frozenset(current)
        if (
            keys != self._last_keys
            or (self.full_sync_s > 0
                and wall - self._last_full_wall >= self.full_sync_s)
        ):
            kind = "full"
            batch = current
            self._last_full_wall = wall
        else:
            kind = "delta"
            last = self._last_values
            batch = {k: v for k, v in current.items() if last.get(k) != v}
            # Heartbeat: the up-series always rides along (fresh timestamp,
            # tiny cost) so the receiving TSDB sees a live exporter even
            # across a perfectly static poll — delta-aware must not read
            # as dead-air.
            for hb in _HEARTBEAT_METRICS:
                for k in current:
                    if k[0] == hb:
                        batch.setdefault(k, current[k])
        self._last_keys = keys
        self._last_values = current
        if not batch:
            return
        mono = self._clock()
        ts_ms = int(wall * 1000.0)
        series: list[tuple[list[tuple[str, str]], list[tuple[float, int]]]] = []
        extra = self._extra_labels
        for (metric, key), value in batch.items():
            spec = self._spec_map.get(metric)
            label_names = spec.label_names if spec is not None else ()
            labels = [("__name__", metric)]
            labels.extend(zip(label_names, key))
            if extra:
                have = {n for n, _ in labels}
                labels.extend(
                    (n, v) for n, v in extra.items() if n not in have
                )
            series.append((labels, [(value, ts_ms)]))
        proto = encode_write_request(series)
        self._seq += 1
        payload = frame_batch(self._seq, wall, kind, len(series), proto,
                              mono=mono)
        try:
            self.buffer.append(payload)
        except OSError as e:
            # The append FAILED, so seq N was never durably issued and may
            # be reused — rolling back after a SUCCESSFUL append would
            # stamp two different batches with one seq and break the
            # exactly-once ledger.
            self._seq -= 1
            with self._stats_lock:
                self._stats["dropped"]["queue"] += 1
            hook = self._pressure_hook
            if hook is not None:
                try:
                    hook(e)  # ENOSPC sheds the disk ladder immediately
                except Exception:  # noqa: BLE001 — governor must not break the writer
                    pass
            self._rlog.warning("egress_append", "egress buffer append "
                               "failed: %s", e)
            return
        self._last_batch_wall = wall
        with self._stats_lock:
            self._stats["enqueued_batches"] += 1
            self._stats["enqueued_samples"] += len(series)
            if self._head_meta is None:
                # First pending batch: seed the cached head metadata so the
                # poll thread's backlog-age read never touches the disk.
                self._head_meta = (self._seq, wall, mono)
        self._work.set()

    def _enforce_caps(self) -> None:
        """Backlog byte/age caps. Runs ONLY on the sender thread — the one
        thread that moves the ack cursor. A cap-drop concurrent with an
        in-flight send would shift the head under the sender's feet and
        make its eventual ack() discard an UNDELIVERED batch; single-
        consumer discipline makes that impossible. Each cap sheds in ONE
        cursor advance: trimming a long outage's backlog must not pay a
        cursor fsync per dropped batch."""
        cap = self.max_backlog_bytes
        if self._disk_pressure:
            cap = max(cap // 8, SHED_SEGMENT_BYTES)
        dropped = self.buffer.trim_to_bytes(cap)
        if self.max_backlog_age_s > 0:
            now = self._wallclock()
            # Cached head age first: the scan below re-reads batches from
            # disk, and paying that on EVERY sender iteration just to
            # learn the head is fresh would double the per-send head I/O.
            # _head_age is the clock-step-fenced read: this-process
            # batches age monotonically, so an NTP step can never trip
            # the age cap into mass-dropping a healthy backlog.
            if self._head_meta is None or (
                self._head_age(now) > self.max_backlog_age_s
            ):
                over_age = 0
                while True:
                    payload = self.buffer.peek_at(over_age)
                    if payload is None:
                        break
                    try:
                        head, _ = parse_batch(payload)
                        # Per-batch age with the SAME clock-step fence as
                        # the trigger: this-process batches age on their
                        # enqueue-monotonic stamp, so a forward NTP step
                        # sheds exactly the genuinely-over-age prefix —
                        # never the healthy batches behind it.
                        if self._batch_age(head, now) <= self.max_backlog_age_s:
                            break
                    except (ValueError, KeyError, TypeError):
                        pass  # unparseable: over-age by policy, shed with it
                    over_age += 1
                if over_age:
                    dropped += self.buffer.drop_oldest(over_age)
        if dropped:
            self._peek_meta()
            with self._stats_lock:
                self._stats["dropped"]["backlog"] += dropped
            self._rlog.warning(
                "egress_backlog",
                "egress backlog over cap while the receiver is unreachable; "
                "dropped %d oldest batch(es) (bounded loss by design — see "
                "--egress-max-backlog-mb/-age-s)", dropped,
            )
            # A drop can empty the buffer; persist the issued seq so a
            # restart right now cannot reuse the dropped batches' numbers.
            self._write_status()

    def _peek_meta(self) -> tuple[int, float, float] | None:
        """(seq, wall, seen_mono) of the oldest pending batch; refreshes
        the cached head metadata. Sender-thread only (reads the buffer
        from disk)."""
        payload = self.buffer.peek()
        meta: tuple[int, float, float] | None = None
        if payload is not None:
            try:
                head, _ = parse_batch(payload)
                meta = (int(head["seq"]), float(head["wall"]),
                        float(head.get("mono", 0.0)))
            except (ValueError, KeyError, TypeError):
                meta = None
        with self._stats_lock:
            self._head_meta = meta
        return meta

    # ----------------------------------------------------------- sender side

    def _sender_run(self) -> None:
        while not self._stop.is_set():
            if self.buffer.pending() == 0:
                self._work.clear()
                self._work.wait(0.25)
                continue
            self._enforce_caps()
            if self.buffer.pending() == 0:
                continue
            decision = self.breaker.decide()
            if decision == "skip":
                self._stop.wait(
                    min(max(self.breaker.seconds_until_probe, 0.05), 0.25)
                )
                continue
            try:
                progressed = self._send_one()
            except Exception as e:  # noqa: BLE001 — the sender must survive anything
                progressed = False
                self.breaker.record_failure()
                with self._stats_lock:
                    self._stats["failed_sends"] += 1
                    self._stats["last_error"] = f"unexpected: {e}"
                self._rlog.warning("egress_send", "egress send failed "
                                   "unexpectedly: %s", e)
            if not progressed and self.breaker.state == CLOSED:
                # Failure floor for the disabled-breaker configuration
                # (--egress-breaker-failures 0 never opens): a connection-
                # refused receiver fails in microseconds, and retrying
                # with zero delay would spin a full core re-compressing
                # the same head batch at kHz rates.
                self._stop.wait(0.05)

    def _send_one(self) -> bool:
        """One send attempt against the head batch. Returns True when the
        queue progressed (ack, poison skip, corrupt drop), False on a
        failed attempt. EVERY exit must leave the breaker with a recorded
        outcome: decide() already consumed this turn (possibly the single
        half-open probe), and an outcome-less return would park the
        breaker in HALF_OPEN forever — decide() then answers 'skip' until
        restart while the backlog rots."""
        payload = self.buffer.peek()
        if payload is None:
            # Transient read failure (the index says pending > 0): count
            # it against the breaker so a consumed half-open probe reopens
            # instead of wedging.
            if self.breaker.state != CLOSED:
                self.breaker.record_failure()
            return False
        try:
            head, proto = parse_batch(payload)
        except (ValueError, KeyError):
            # A foreign/torn record at the head must not wedge the queue.
            self.buffer.drop_oldest(1)
            with self._stats_lock:
                self._stats["dropped"]["corrupt"] += 1
            self._peek_meta()
            self._write_status()  # a drop can empty the buffer (seq source)
            if self.breaker.state != CLOSED:
                # The probe never reached the receiver; reopen and let the
                # next probe try the (now different) head.
                self.breaker.record_failure()
            return True
        body = snappy_compress(proto)
        headers = {
            "Content-Type": CONTENT_TYPE,
            "Content-Encoding": "snappy",
            "X-Prometheus-Remote-Write-Version": REMOTE_WRITE_VERSION,
            SEQ_HEADER: str(head.get("seq", 0)),
        }
        t0 = self._clock()
        status: int | None = None
        error = ""
        try:
            status = self._send(self.url, body, headers, self.timeout_s)
        except urllib.error.HTTPError as e:
            status = e.code
            error = f"HTTP {e.code}"
        except (urllib.error.URLError, TimeoutError, socket.timeout,
                ConnectionError, OSError) as e:
            error = f"{type(e).__name__}: {e}"
        latency = self._clock() - t0
        self.send_hist.observe(latency)
        if status is not None and 200 <= status < 300:
            self.breaker.record_success()
            self.buffer.ack()
            self._peek_meta()
            samples = int(head.get("samples", 0))
            wall = self._wallclock()
            with self._stats_lock:
                self._stats["sent_batches"] += 1
                self._stats["sent_samples"] += samples
                self._stats["last_send_latency_s"] = latency
                self._stats["last_send_ok_wall"] = wall
                self._stats["last_error"] = ""
            self._write_status()
            return True
        if status is not None and 400 <= status < 500 and status != 429:
            # Poison: the receiver is UP and rejects this batch's body.
            # Retrying forever would park every batch behind it; skip it,
            # loudly. 429 is deliberate backpressure, handled as a failure
            # (retry with backoff) below — skipping would LOSE the batch.
            self.breaker.record_success()
            self.buffer.ack()
            self._peek_meta()
            with self._stats_lock:
                self._stats["dropped"]["poison"] += 1
                self._stats["last_error"] = f"poison: HTTP {status}"
            self._rlog.warning(
                "egress_poison",
                "receiver rejected batch seq=%s with HTTP %d; skipping it "
                "(poison batches must not wedge the queue)",
                head.get("seq"), status,
            )
            self._write_status()
            return True
        self.breaker.record_failure()
        with self._stats_lock:
            self._stats["failed_sends"] += 1
            self._stats["last_send_latency_s"] = latency
            self._stats["last_error"] = error or f"HTTP {status}"
        if self.breaker.state != CLOSED:
            self._rlog.warning(
                "egress_fail",
                "egress send failed (%s); breaker %s, next probe in %.1fs, "
                "%d batch(es) buffered on disk",
                error or f"HTTP {status}", self.breaker.state,
                self.breaker.seconds_until_probe, self.buffer.pending(),
            )
        self._write_status()
        return False

    def _write_status(self) -> None:
        """Small operator-facing sidecar for `status`'s egress footer —
        written by the sender thread per attempt (~1/s), atomically."""
        doc = {
            "wall": self._wallclock(),
            "url": self.url,
            "breaker": self.breaker.state,
            "backlog_batches": self.buffer.pending(),
            "backlog_bytes": self.buffer.pending_bytes(),
            # Last issued batch seq — the drained-buffer restart's only
            # seq source (see load()).
            "seq": self._seq,
        }
        with self._stats_lock:
            doc.update(
                last_send_latency_s=self._stats["last_send_latency_s"],
                last_send_ok_wall=self._stats["last_send_ok_wall"],
                last_error=self._stats["last_error"],
                sent_batches=self._stats["sent_batches"],
            )
        try:
            atomic_write(
                os.path.join(self.egress_dir, STATUS_NAME),
                json.dumps(doc).encode(),
            )
        except OSError:
            pass

    # ------------------------------------------------- pressure-shed hooks

    def set_disk_pressure(self, on: bool) -> None:
        """Disk-ladder rung ``egress_compact`` (tpu_pod_exporter.pressure):
        tiny segment rotation so acked bytes reclaim promptly (lossless)
        plus a tightened pending-backlog cap (bounded loss only while the
        receiver is down). Idempotent; reversed on recovery."""
        self._disk_pressure = bool(on)
        self.buffer.segment_max_bytes = (
            SHED_SEGMENT_BYTES if on else self._normal_segment_bytes
        )
        if on:
            # Reclaim acked bytes NOW, not at the next append: with the
            # producer stalled, the lazily-rotated active segment can be
            # 100% acked yet hold the disk over budget forever (the
            # fuzzer's one-round disk_full find).
            self.buffer.seal_active()
        self._work.set()  # wake the sender so the cap applies promptly

    def set_pressure_hook(self, hook: Callable[[BaseException], bool]) -> None:
        """Governor callback for buffer-append failures (ENOSPC sheds the
        disk ladder immediately instead of waiting for a usage scan)."""
        self._pressure_hook = hook

    # ----------------------------------------------------------------- state

    @property
    def degraded(self) -> bool:
        """/readyz degraded predicate — same reopen threshold as sources."""
        return (
            self.breaker.state != CLOSED
            and self.breaker.reopens >= DEGRADED_AFTER_REOPENS
        )

    def _batch_age(self, head: Mapping[str, Any], now_wall: float) -> float:
        """Clock-step-fenced age of one batch header: batches created by
        this process age on their enqueue-MONOTONIC stamp (exact — an NTP
        step can neither inflate their age into an age-cap mass-drop nor
        hide a genuinely stale slowly-draining backlog); batches restored
        from a pre-restart backlog age on wall time (their mono stamp
        belongs to a dead clock). Never negative either way (a
        future-stamped batch reads as fresh, not as a fault)."""
        mono = float(head.get("mono", 0.0))
        # mono == 0: an unstamped frame (externally appended / older
        # format) — wall age is the only honest read, never "monotonic
        # since boot" (which would mass-expire it as ancient).
        if mono > 0 and int(head.get("seq", 0)) > self._boot_seq:
            return max(self._clock() - mono, 0.0)
        return max(now_wall - float(head.get("wall", 0.0)), 0.0)

    def _head_age(self, now_wall: float) -> float:
        """:meth:`_batch_age` of the CACHED head metadata (poll-thread
        safe: no buffer file reads)."""
        with self._stats_lock:
            meta = self._head_meta
        if meta is None:
            return 0.0
        seq, wall, mono = meta
        return self._batch_age({"seq": seq, "wall": wall, "mono": mono},
                               now_wall)

    def backlog_age_s(self) -> float:
        """Age of the oldest pending batch, from the CACHED head metadata
        only — this is read on the poll thread (collector emit), which
        must never touch the buffer's files."""
        if self.buffer.pending() == 0:
            return 0.0
        return self._head_age(self._wallclock())

    def stats(self) -> dict:
        with self._stats_lock:
            out: dict[str, Any] = dict(self._stats)
            out["dropped"] = dict(self._stats["dropped"])
        out["backlog_batches"] = self.buffer.pending()
        out["backlog_bytes"] = self.buffer.pending_bytes()
        out["backlog_age_s"] = self.backlog_age_s()
        out["breaker_state"] = self.breaker.state
        out["breaker_state_value"] = STATE_VALUES[self.breaker.state]
        out["breaker_reopens"] = self.breaker.reopens
        out["seq"] = self._seq
        out["degraded"] = self.degraded
        out["disk_pressure"] = self._disk_pressure
        if self._open_errors:
            out["open_errors"] = list(self._open_errors)
        return out

    def emit(self, b: Any) -> None:
        """Publish the egress self-metric surface into a SnapshotBuilder
        (called from the collector's / aggregator's publish)."""
        for spec in schema.EGRESS_SPECS:
            b.declare(spec)
        s = self.stats()
        b.add(schema.TPU_EXPORTER_EGRESS_SENT_BATCHES_TOTAL,
              float(s["sent_batches"]))
        b.add(schema.TPU_EXPORTER_EGRESS_SENT_SAMPLES_TOTAL,
              float(s["sent_samples"]))
        b.add(schema.TPU_EXPORTER_EGRESS_FAILED_SENDS_TOTAL,
              float(s["failed_sends"]))
        for reason, n in s["dropped"].items():
            b.add(schema.TPU_EXPORTER_EGRESS_DROPPED_TOTAL, float(n),
                  (reason,))
        b.add(schema.TPU_EXPORTER_EGRESS_BACKLOG_BATCHES,
              float(s["backlog_batches"]))
        b.add(schema.TPU_EXPORTER_EGRESS_BACKLOG_BYTES,
              float(s["backlog_bytes"]))
        b.add(schema.TPU_EXPORTER_EGRESS_BACKLOG_AGE_SECONDS,
              s["backlog_age_s"])
        b.add(schema.TPU_EXPORTER_EGRESS_BREAKER_STATE,
              s["breaker_state_value"])
        self.send_hist.emit(b)

    def ready_detail(self) -> dict:
        """Egress block for the /readyz JSON body."""
        s = self.stats()
        return {
            "breaker_state": s["breaker_state"],
            "backlog_batches": s["backlog_batches"],
            "backlog_bytes": s["backlog_bytes"],
            "backlog_age_s": round(s["backlog_age_s"], 3),
            "last_error": s["last_error"],
            "degraded": s["degraded"],
        }

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._work.set()
        for t in (self._writer, self._sender):
            if t is not None:
                t.join(timeout)
        self._writer = self._sender = None
        self._write_status()
        self.buffer.close()


# ------------------------------------------------------------- status footer


def egress_dir_summary(egress_dir: str) -> dict:
    """Lightweight on-disk summary for ``status``'s ``egress:`` footer and
    /debug/vars: segment sizes plus the shipper's own status sidecar (no
    record parsing — same cheapness contract as state_dir_summary)."""
    out: dict[str, Any] = {
        "egress_dir": egress_dir,
        "exists": os.path.isdir(egress_dir),
        "segment_bytes": 0,
        "segments": 0,
        "status": None,
    }
    if not out["exists"]:
        return out
    try:
        for name in os.listdir(egress_dir):
            if name.startswith("seg-") and name.endswith(".wal"):
                try:
                    out["segment_bytes"] += os.stat(
                        os.path.join(egress_dir, name)
                    ).st_size
                    out["segments"] += 1
                except OSError:
                    continue
    except OSError:
        pass
    try:
        with open(os.path.join(egress_dir, STATUS_NAME),
                  encoding="utf-8") as f:
            out["status"] = json.load(f)
    except (OSError, ValueError):
        pass
    return out


# -------------------------------------------------------------------- checks


def _wait(predicate: Callable[[], bool], timeout_s: float,
          interval_s: float = 0.05) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


def _metric_value(base: str, name: str, timeout: float = 5.0) -> float:
    with urllib.request.urlopen(base + "/metrics", timeout=timeout) as r:
        body = r.read().decode()
    for line in body.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            try:
                return float(line.rsplit(" ", 1)[1])
            except ValueError:
                continue
    return float("nan")


def _p99(samples: list[float]) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(int(len(s) * 0.99), len(s) - 1)]


def _sample_perf(base: str, n: int, interval_s: float) -> tuple[float, float]:
    """(scrape_p99_s, poll_total_p99_s) over n samples against a live
    exporter — the demo's egress-on vs -off perf comparison."""
    scrapes: list[float] = []
    polls: list[float] = []
    for _ in range(n):
        t0 = time.perf_counter()
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            r.read()
        scrapes.append(time.perf_counter() - t0)
        with urllib.request.urlopen(base + "/debug/vars", timeout=5) as r:
            dv = json.loads(r.read())
        total = (dv.get("last_poll") or {}).get("total_s")
        if isinstance(total, (int, float)):
            polls.append(float(total))
        time.sleep(interval_s)
    return _p99(scrapes), _p99(polls)


def _demo(ns: Any) -> int:
    """``make egress-demo``: wedge → open → backlog → SIGKILL mid-send →
    WAL-backed resume → drain, with zero loss and no acked re-send."""
    import shutil
    import signal as _signal
    import subprocess
    import sys
    import tempfile

    from tpu_pod_exporter.chaos import ChaosReceiver, parse_chaos_spec
    from tpu_pod_exporter.persist import _wait_http

    own_dir = not ns.egress_dir
    egress_dir = ns.egress_dir or tempfile.mkdtemp(prefix="tpe-egress-demo-")
    os.makedirs(egress_dir, exist_ok=True)
    interval = 0.2
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    base = f"http://127.0.0.1:{port}"

    # Seeded flap schedule: requests 0-5 healthy, then two 2.5 s hangs,
    # three 500s, two 429s, one mid-body truncation, healthy after.
    spec = ("hang:recv:1:2500ms:@6:x2,err:recv:1:@8:x3,"
            "reject:recv:1:@11:x2,truncate:recv:1:@13:x1")
    recv = ChaosReceiver(parse_chaos_spec(spec), seed=ns.seed)
    recv.start()
    print(f"chaos receiver on {recv.url}  (spec: {spec}, seed {ns.seed})")

    def cmd(egress: bool) -> list[str]:
        out = [
            sys.executable, "-m", "tpu_pod_exporter",
            "--host", "127.0.0.1", "--port", str(port),
            "--backend", "fake", "--fake-chips", "4",
            "--attribution", "none",
            "--interval-s", f"{interval:g}",
            "--history-retention-s", "60",
            "--log-level", "warning",
        ]
        if egress:
            out += [
                "--egress-url", recv.url,
                "--egress-dir", egress_dir,
                "--egress-interval-s", f"{interval:g}",
                "--egress-timeout-s", "1",
                "--egress-breaker-failures", "2",
                "--egress-breaker-backoff-s", "0.5",
                "--egress-breaker-backoff-max-s", "2",
            ]
        return out

    child: subprocess.Popen | None = None
    rc = 1
    try:
        # ---- phase 0: egress-OFF perf baseline --------------------------
        print("phase 0: egress-off baseline (scrape + poll p99)")
        child = subprocess.Popen(cmd(egress=False))
        _wait_http(base + "/readyz", 30)
        base_scrape, base_poll = _sample_perf(base, ns.perf_samples, 0.05)
        child.terminate()
        child.wait(timeout=10)
        print(f"         baseline: scrape p99 {1e3 * base_scrape:.2f}ms, "
              f"poll p99 {1e3 * base_poll:.2f}ms")

        # ---- phase 1: healthy egress ------------------------------------
        print("phase 1: egress on, receiver healthy")
        child = subprocess.Popen(cmd(egress=True))
        _wait_http(base + "/readyz", 30)
        if not _wait(lambda: recv.accepted_batches() >= 3, 20):
            print(f"FAIL: receiver accepted only "
                  f"{recv.accepted_batches()} batches")
            return 1
        print(f"         {recv.accepted_batches()} batches delivered")

        # ---- phase 2: seeded wedge — breaker opens, backlog grows -------
        print("phase 2: receiver flapping (hang/5xx/429/truncate) — "
              "expecting breaker open + disk backlog")
        saw_open = _wait(
            lambda: _metric_value(
                base, "tpu_exporter_egress_breaker_state") >= 1.0,
            30,
        )
        if not saw_open:
            print("FAIL: egress breaker never opened during the wedge")
            return 1
        _wait(
            lambda: _metric_value(
                base, "tpu_exporter_egress_backlog_batches") >= 2.0,
            20,
        )
        backlog = _metric_value(base, "tpu_exporter_egress_backlog_batches")
        wedge_scrape, wedge_poll = _sample_perf(base, ns.perf_samples, 0.05)
        print(f"         breaker OPEN, backlog {backlog:g} batch(es); "
              f"during wedge: scrape p99 {1e3 * wedge_scrape:.2f}ms, "
              f"poll p99 {1e3 * wedge_poll:.2f}ms")
        # Poll/scrape isolation: egress ON + wedged receiver must stay
        # within budget of the egress-OFF baseline (absolute floor keeps
        # micro-benchmark noise from failing a passing design).
        scrape_budget = base_scrape * (1.0 + ns.perf_budget) + 0.002
        poll_budget = base_poll * (1.0 + ns.perf_budget) + 0.002
        if wedge_scrape > scrape_budget or wedge_poll > poll_budget:
            print(f"FAIL: wedged-receiver p99 over budget (scrape "
                  f"{1e3 * wedge_scrape:.2f} > {1e3 * scrape_budget:.2f}ms "
                  f"or poll {1e3 * wedge_poll:.2f} > "
                  f"{1e3 * poll_budget:.2f}ms)")
            return 1

        # ---- phase 3: SIGKILL mid-send ----------------------------------
        print("phase 3: SIGKILL mid-send (receiver holds the in-flight "
              "request; no drain, no ack)")
        inflight = recv.hold_next(hold_s=10.0)
        if not inflight.wait(30):
            print("FAIL: no send arrived to hold")
            return 1
        child.send_signal(_signal.SIGKILL)
        child.wait(timeout=10)
        recv.release_hold()
        print("         killed mid-send; backlog is on disk, cursor "
              "fsynced at the last ack")

        # ---- phase 4: restart → WAL-backed resume → drain ---------------
        print("phase 4: restart on the same egress dir; receiver healthy")
        t_restart = time.monotonic()
        child = subprocess.Popen(cmd(egress=True))
        _wait_http(base + "/readyz", 30)
        drained = _wait(
            lambda: _metric_value(
                base, "tpu_exporter_egress_backlog_batches") == 0.0
            and recv.accepted_batches() > 0,
            ns.drain_budget_s,
            interval_s=0.1,
        )
        drain_s = time.monotonic() - t_restart
        if not drained:
            print(f"FAIL: backlog did not drain within "
                  f"{ns.drain_budget_s:g}s budget")
            return 1
        print(f"         backlog drained {drain_s:.1f}s after restart "
              f"(budget {ns.drain_budget_s:g}s)")
        # Let a few more healthy sends land, then audit the ledger.
        time.sleep(6 * interval)
        stats = recv.stats()
        seqs = stats["accepted_seqs"]
        if not seqs:
            print("FAIL: receiver accepted nothing")
            return 1
        missing = sorted(set(range(min(seqs), max(seqs) + 1)) - set(seqs))
        if missing:
            print(f"FAIL: zero-loss violated — batch seq(s) {missing} "
                  f"were enqueued but never delivered")
            return 1
        if stats["duplicate_seqs"]:
            print(f"FAIL: acked batches re-sent: {stats['duplicate_seqs']}")
            return 1
        if stats["duplicate_samples"]:
            print(f"FAIL: {stats['duplicate_samples']} duplicate "
                  f"(series, timestamp) samples accepted")
            return 1
        print(f"         ledger: {len(seqs)} batches seq "
              f"{min(seqs)}..{max(seqs)} contiguous, 0 duplicate batches, "
              f"0 duplicate samples, {stats['accepted_samples']} samples "
              f"delivered exactly once")
        print("egress-demo: OK (wedge → open → backlog → SIGKILL mid-send "
              "→ WAL resume → drain; zero loss, no acked re-send, poll/"
              "scrape p99 within budget while wedged)")
        rc = 0
    finally:
        if child is not None and child.poll() is None:
            child.terminate()
            try:
                child.wait(timeout=10)
            except subprocess.TimeoutExpired:
                child.kill()
        recv.stop()
        if own_dir and rc == 0:
            shutil.rmtree(egress_dir, ignore_errors=True)
        elif rc != 0:
            print(f"egress dir kept for inspection: {egress_dir}")
    return rc


def _drain_check(ns: Any) -> int:
    """Backlog-drain budget: synthesize the backlog an ``--outage-s``
    receiver outage leaves behind (one batch per egress interval), then
    let the sender drain it against an in-process receiver and fail if the
    drain exceeds ``--budget-s``. In-process and send-injected: this
    measures the shipper's drain throughput, not socket setup."""
    import tempfile

    batches = max(int(ns.outage_s / max(ns.egress_interval_s, 0.05)), 1)
    egress_dir = ns.egress_dir or tempfile.mkdtemp(prefix="tpe-drain-check-")
    accepted: list[int] = []

    def send(url: str, body: bytes, headers: Mapping[str, str],
             timeout_s: float) -> int:
        parse_write_request(snappy_decompress(body))  # must decode
        accepted.append(int(headers[SEQ_HEADER]))
        return 200

    shipper = RemoteWriteShipper(
        "http://drain-check.invalid/api/v1/write", egress_dir, send=send,
        interval_s=0.0,
    )
    shipper.load()
    # Writer-thread work done inline: frame batches the shape a 4-chip
    # exporter produces (the demo shape), straight into the buffer.
    labels = [("__name__", "tpu_hbm_used_bytes"), ("chip_id", "0"),
              ("host", "drain-check")]
    t_build = time.monotonic()
    for i in range(batches):
        proto = encode_write_request(
            [(labels, [(float(i), 1_700_000_000_000 + i)])] * 24
        )
        shipper.buffer.append(frame_batch(i + 1, time.time(), "delta", 24,
                                          proto))
    build_s = time.monotonic() - t_build
    t0 = time.monotonic()
    shipper.start()
    ok = _wait(lambda: shipper.buffer.pending() == 0, ns.budget_s + 5,
               interval_s=0.02)
    drain_s = time.monotonic() - t0
    shipper.close()
    import shutil

    if not ns.egress_dir:
        shutil.rmtree(egress_dir, ignore_errors=True)
    print(f"drain-check: {batches} batches (a {ns.outage_s:g}s outage at "
          f"{ns.egress_interval_s:g}s cadence, built+fsynced in "
          f"{build_s:.1f}s) drained in {drain_s:.2f}s "
          f"(budget {ns.budget_s:g}s)")
    if not ok or drain_s > ns.budget_s:
        print("FAIL: backlog drain exceeded budget")
        return 1
    # Unsorted: the arrival order IS the assertion — sorting would let an
    # out-of-order drain regression slip the "in-order" half of the gate.
    if accepted != list(range(1, batches + 1)):
        print("FAIL: drain was not in-order exactly-once")
        return 1
    print("OK: backlog drains within budget, in order, exactly once")
    return 0


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="tpu-pod-exporter-egress",
        description="Remote-write egress harness: chaos-receiver demo and "
                    "backlog-drain budget check.",
    )
    p.add_argument("--demo", action="store_true",
                   help="wedge a live exporter's egress with a seeded "
                        "chaos receiver, SIGKILL mid-send, assert "
                        "zero-loss exactly-once drain after restart")
    p.add_argument("--egress-dir", default="",
                   help="send-buffer dir for --demo/--drain-check "
                        "(default: a temp dir, removed on success)")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--drain-budget-s", type=float, default=30.0,
                   help="max seconds from restart to a fully-drained "
                        "backlog in --demo")
    p.add_argument("--perf-budget", type=float, default=0.05,
                   help="max fractional scrape/poll p99 regression with "
                        "egress on + receiver wedged vs egress off "
                        "(plus a 2 ms absolute noise floor)")
    p.add_argument("--perf-samples", type=int, default=30)
    p.add_argument("--drain-check", action="store_true",
                   help="synthesize an --outage-s backlog and fail if it "
                        "drains slower than --budget-s")
    p.add_argument("--outage-s", type=float, default=180.0)
    p.add_argument("--egress-interval-s", type=float, default=1.0)
    p.add_argument("--budget-s", type=float, default=20.0)
    ns = p.parse_args(argv)

    if ns.demo:
        return _demo(ns)
    if ns.drain_check:
        return _drain_check(ns)
    p.error("need --demo or --drain-check")
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
