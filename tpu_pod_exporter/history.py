"""Node-local telemetry flight recorder — bounded in-memory history.

The exporter's snapshot model deliberately keeps only the *latest* poll:
stale series are structurally impossible, but so is looking backwards. When
a pod OOMs or a duty-cycle cliff lands between Prometheus scrapes (or on a
cluster with no Prometheus at all — the gap ``aggregate.py`` exists to
fill), the evidence is gone by the next scrape. The reference exporter has
the same blindness — it overwrites two gauges every 30 s and keeps nothing
(``main.go:74-157``).

:class:`HistoryStore` turns each node into its own short-horizon TSDB: a
fixed set of per-series ring buffers (float64 value + monotonic and wall
timestamps, preallocated ``array('d')`` storage, O(1) append) that the
collector feeds once per poll *after* the snapshot swap — the scrape path
never touches the history lock. Memory is hard-bounded twice:

- per series: ``capacity`` samples × 24 bytes (three float64 arrays),
  allocated once at series creation, never grown;
- across series: at most ``max_series`` rings; creating one more evicts the
  least-recently-appended series (churned-away pods age out first) and
  counts it in ``evicted()['capacity']``. Series idle longer than
  ``retention_s`` are dropped wholesale (``evicted()['retention']``).

Worst case: ``max_series × capacity × 24`` bytes plus per-series
bookkeeping, allocated only for series actually present (~32 MB at the
256-chip shape; the exporter defaults cap at 8192 × 301 × 24 ≈ 59 MB).

Behind the raw ring sit **multi-resolution downsample tiers** (default
10 s and 60 s buckets — :data:`DEFAULT_TIER_SPEC`): each bucket folds
min/max/sum/count/first/last plus the within-bucket positive-delta sum, so
both gauge statistics and counter-reset-tolerant rates recompute exactly
from buckets. ``query_range`` transparently serves the coarsest tier that
satisfies the requested step (escalating to a coarser tier when the
requested start predates what the finer ring still holds), stretching
answerable retention from minutes to hours inside the same
``max_series`` hard bound; tier rings ride their series and evict with it.

Query surface (served by ``server.py`` as ``/api/v1/*`` JSON):

- ``series_list()`` — stored series and their label sets;
- ``query_range(metric, match, start, end, step)`` — samples by wall-clock
  range, optionally aligned to a step grid;
- ``window_stats(metric, match, window_s)`` — min/max/mean/first/last over
  a trailing window plus a counter-aware ``rate`` using the same monotonic
  fold-with-reset-tolerance semantics as the collector's ICI/DCN rates
  (negative deltas — device reset — contribute nothing).

Consumers in-tree: ``status.py --watch`` (per-chip deltas and trend arrows
instead of discarding prior samples) and ``aggregate.py`` (window-stats
fallback when a scrape round is missed, so slice continuity survives a
dropped round).

``python -m tpu_pod_exporter.history --replay trace.jsonl`` replays a
recorded backend trace through a real collector into a history store and
prints what the flight recorder would answer — the offline forensics demo
(``make history-demo``).
"""

from __future__ import annotations

import threading
import time
from array import array
from typing import TYPE_CHECKING, Callable, Mapping, Sequence

from tpu_pod_exporter.metrics import schema

if TYPE_CHECKING:  # typing only — no runtime import cost
    from tpu_pod_exporter.metrics.registry import Snapshot

# Metric families the collector feeds into history each poll. Info series
# (tpu_host_info, tpu_exporter_info) and self-metrics are excluded — their
# history is either constant or recoverable from counters — EXCEPT
# tpu_chip_info, tpu_exporter_up and tpu_exporter_slow_polls_total:
# chip_info is the guaranteed per-chip presence series (HBM may be
# unreadable), so "which chips existed at time T" must come from it;
# exporter_up is the first question of any incident timeline — and slow
# polls are the second ("was the exporter itself struggling?"), so the
# tracing counter rides along and window_stats' counter-aware rate answers
# "slow polls in the last N minutes" without a Prometheus.
HISTORY_TRACKED_METRICS: frozenset[str] = frozenset({
    "tpu_hbm_used_bytes",
    "tpu_hbm_total_bytes",
    "tpu_hbm_used_percent",
    "tpu_hbm_peak_bytes",
    "tpu_chip_info",
    "tpu_tensorcore_duty_cycle_percent",
    "tpu_ici_transferred_bytes_total",
    "tpu_ici_link_bandwidth_bytes_per_second",
    "tpu_dcn_transferred_bytes_total",
    "tpu_dcn_link_bandwidth_bytes_per_second",
    "tpu_pod_chip_count",
    "tpu_pod_hbm_used_bytes",
    "tpu_kubelet_allocatable_chips",
    "tpu_kubelet_allocated_chips",
    "tpu_exporter_up",
    "tpu_exporter_slow_polls_total",
    # The GPU device family's node surface (backend/nvml.py): same
    # forensics contract as the TPU twins — "what did GPU 0's memory do
    # over the last five minutes" must answer node-locally, and the
    # aggregator's missed-round history fallback probes these names. On
    # TPU-only exporters the families carry no samples, so tracking them
    # costs nothing (series are created per sample, not per name).
    "gpu_hbm_used_bytes",
    "gpu_hbm_total_bytes",
    "gpu_hbm_used_percent",
    "gpu_utilization_percent",
    "gpu_chip_info",
    "gpu_pod_chip_count",
    "gpu_pod_memory_used_bytes",
    "gpu_backend_up",
})

_SPEC_BY_NAME = {
    spec.name: spec
    for spec in schema.ALL_SPECS + schema.GPU_NODE_SPECS
}
_COUNTER_METRICS = frozenset(
    name for name, spec in _SPEC_BY_NAME.items() if spec.type == schema.COUNTER
)


def is_counter_metric(name: str) -> bool:
    """Counter-aware rate eligibility: schema type wins; unknown names fall
    back to the Prometheus naming convention."""
    if name in _SPEC_BY_NAME:
        return name in _COUNTER_METRICS
    return name.endswith("_total")


# Multi-resolution downsample tiers behind the raw ring: ``step:capacity``
# pairs, finest first. Defaults stretch query_range's answerable retention
# from 5 min of raw (301 × 1 s polls) to 4 h (240 × 60 s buckets) — 48×, at
# the same ``--history-max-series`` series bound. Each bucket keeps
# min/max/sum/count/first/last plus the within-bucket positive-delta sum,
# so gauge stats AND counter-reset-tolerant rates recompute exactly from
# tier buckets (asserted by tests/test_tiers.py property tests).
DEFAULT_TIER_SPEC = "10:60,60:240"

# Per finalized bucket: 11 float64 cells (4 timestamps + 7 value stats).
_TIER_BUCKET_BYTES = 11 * 8


def parse_tier_spec(spec: str) -> tuple[tuple[float, int], ...]:
    """``"10:60,60:240"`` → ``((10.0, 60), (60.0, 240))``, sorted finest
    first. Empty / ``"off"`` / ``"none"`` disables tiering entirely."""
    s = spec.strip().lower()
    if s in ("", "off", "none", "0"):
        return ()
    tiers: list[tuple[float, int]] = []
    for entry in s.split(","):
        entry = entry.strip()
        if not entry:
            continue
        step_s, _, cap_s = entry.partition(":")
        try:
            step = float(step_s)
            cap = int(cap_s) if cap_s else 0
        except ValueError as e:
            raise ValueError(f"bad tier entry {entry!r}: {e}") from e
        if step <= 0 or cap < 2:
            raise ValueError(
                f"bad tier entry {entry!r}: need step > 0 and capacity >= 2"
            )
        tiers.append((step, cap))
    tiers.sort()
    if len({step for step, _cap in tiers}) != len(tiers):
        raise ValueError(f"duplicate tier step in {spec!r}")
    return tuple(tiers)


class _TierRing:
    """One series' downsample ring for one tier: a fixed-capacity ring of
    finalized buckets plus one open accumulator bucket.

    Buckets are keyed by wall time (``t_wall // step``) so bucket edges
    line up with the wall-clock grids queries ask for. Per finalized
    bucket the ring stores first/last mono+wall timestamps and
    min/max/sum/count/first/last values, plus ``dpos`` — the sum of
    positive deltas between consecutive samples *within* the bucket. The
    cross-bucket boundary delta is recomputed at query time from
    ``vfirst[k] − vlast[k−1]``, so a window rate over whole buckets equals
    the raw-sample computation exactly (reset tolerance included) without
    the ring needing to know its neighbours at append time."""

    __slots__ = ("step", "cap", "n", "head",
                 "tmf", "tml", "twf", "twl",
                 "vmin", "vmax", "vsum", "vcnt", "vfirst", "vlast", "dpos",
                 "bucket", "a_tmf", "a_tml", "a_twf", "a_twl", "a_min",
                 "a_max", "a_sum", "a_cnt", "a_first", "a_last", "a_dpos")

    def __init__(self, step: float, cap: int) -> None:
        zeros = bytes(8 * cap)
        self.step = step
        self.cap = cap
        self.n = 0
        self.head = 0
        self.tmf = array("d", zeros)
        self.tml = array("d", zeros)
        self.twf = array("d", zeros)
        self.twl = array("d", zeros)
        self.vmin = array("d", zeros)
        self.vmax = array("d", zeros)
        self.vsum = array("d", zeros)
        self.vcnt = array("d", zeros)
        self.vfirst = array("d", zeros)
        self.vlast = array("d", zeros)
        self.dpos = array("d", zeros)
        self.bucket = -1  # open-bucket id; -1 = nothing accumulated yet
        self.a_tmf = 0.0
        self.a_tml = 0.0
        self.a_twf = 0.0
        self.a_twl = 0.0
        self.a_min = 0.0
        self.a_max = 0.0
        self.a_sum = 0.0
        self.a_cnt = 0
        self.a_first = 0.0
        self.a_last = 0.0
        self.a_dpos = 0.0

    def add(self, t_mono: float, t_wall: float, v: float, dpos: float) -> None:
        b = int(t_wall // self.step)
        if b != self.bucket:
            if self.bucket >= 0:
                self._flush()
            self.bucket = b
            self.a_tmf = t_mono
            self.a_twf = t_wall
            self.a_min = v
            self.a_max = v
            self.a_sum = v
            self.a_cnt = 1
            self.a_first = v
            # The boundary delta (previous bucket's last → this sample) is
            # deliberately NOT accumulated: queries rebuild it from the
            # stored first/last values of adjacent buckets, keeping window
            # rates exact from any bucket onward.
            self.a_dpos = 0.0
        else:
            if v < self.a_min:
                self.a_min = v
            if v > self.a_max:
                self.a_max = v
            self.a_sum += v
            self.a_cnt += 1
            self.a_dpos += dpos
        self.a_tml = t_mono
        self.a_twl = t_wall
        self.a_last = v

    def _flush(self) -> None:
        i = self.head
        self.tmf[i] = self.a_tmf
        self.tml[i] = self.a_tml
        self.twf[i] = self.a_twf
        self.twl[i] = self.a_twl
        self.vmin[i] = self.a_min
        self.vmax[i] = self.a_max
        self.vsum[i] = self.a_sum
        self.vcnt[i] = float(self.a_cnt)
        self.vfirst[i] = self.a_first
        self.vlast[i] = self.a_last
        self.dpos[i] = self.a_dpos
        self.head = (i + 1) % self.cap
        if self.n < self.cap:
            self.n += 1

    def open_bucket(self) -> tuple | None:
        """The open accumulator as one bucket tuple (None while empty) —
        the same 11-field shape :func:`tier_items` yields. The fleet store
        (``tpu_pod_exporter.store``) captures it just before a boundary
        crossing finalizes it, which is exactly the record it persists."""
        if self.bucket >= 0 and self.a_cnt > 0:
            return (self.a_tmf, self.a_tml, self.a_twf, self.a_twl,
                    self.a_min, self.a_max, self.a_sum,
                    float(self.a_cnt), self.a_first, self.a_last,
                    self.a_dpos)
        return None

    # ------------------------------------------------ disk-backed restore
    # The wall-bucketed generalization (tpu_pod_exporter.store): a ring is
    # rebuilt at boot from persisted finalized-bucket records, then keeps
    # accumulating live — push() inserts a finalized bucket directly,
    # replacing the newest retained bucket when both cover the SAME wall
    # bucket (a re-finalization record written after a restart merged new
    # samples into a restored accumulator supersedes the pre-crash record,
    # so replay is idempotent and never yields duplicate buckets), and
    # pop_to_accumulator() re-opens the newest restored bucket so the
    # first post-restart samples of the same wall bucket MERGE exactly
    # (every accumulator field is present in the stored bucket) instead of
    # forking a twin bucket.

    def _store_at(self, i: int, b: tuple) -> None:
        (self.tmf[i], self.tml[i], self.twf[i], self.twl[i],
         self.vmin[i], self.vmax[i], self.vsum[i], self.vcnt[i],
         self.vfirst[i], self.vlast[i], self.dpos[i]) = b

    def push(self, b: tuple) -> None:
        """Insert one FINALIZED bucket (oldest-first replay order); a
        bucket covering the same wall bucket as the newest retained one
        REPLACES it (see the restore notes above)."""
        bid = int(b[2] // self.step)
        if self.n:
            j = (self.head - 1) % self.cap
            if int(self.twf[j] // self.step) == bid:
                self._store_at(j, b)
                return
        self._store_at(self.head, b)
        self.head = (self.head + 1) % self.cap
        if self.n < self.cap:
            self.n += 1

    def pop_to_accumulator(self) -> None:
        """Move the NEWEST finalized bucket back into the open accumulator
        (boot-time restore tail): post-restart samples landing in the same
        wall bucket then merge into it exactly."""
        if not self.n:
            return
        i = (self.head - 1) % self.cap
        self.head = i
        self.n -= 1
        (self.a_tmf, self.a_tml, self.a_twf, self.a_twl,
         self.a_min, self.a_max, self.a_sum, cnt,
         self.a_first, self.a_last, self.a_dpos) = (
            self.tmf[i], self.tml[i], self.twf[i], self.twl[i],
            self.vmin[i], self.vmax[i], self.vsum[i], self.vcnt[i],
            self.vfirst[i], self.vlast[i], self.dpos[i])
        self.a_cnt = int(cnt)
        self.bucket = int(self.a_twf // self.step)

    # Query-side copy, called UNDER the store lock (same raw-slice
    # discipline as HistoryStore._rows_for): finalized buckets as array
    # slices plus the open accumulator as one tuple; per-bucket Python
    # tuples are built outside the lock by _tier_items.
    def copy(self) -> tuple:
        open_bucket = self.open_bucket()
        return (self.step, self.cap, self.n, self.head,
                self.tmf[:], self.tml[:], self.twf[:], self.twl[:],
                self.vmin[:], self.vmax[:], self.vsum[:], self.vcnt[:],
                self.vfirst[:], self.vlast[:], self.dpos[:], open_bucket)

    def oldest_mono(self) -> float:
        """Earliest t_mono this ring can answer for; -inf when the ring has
        not wrapped yet (it then holds everything since series creation)."""
        if self.n < self.cap:
            return float("-inf")
        return self.tmf[(self.head - self.n) % self.cap]

    def oldest_wall(self) -> float:
        if self.n < self.cap:
            return float("-inf")
        return self.twf[(self.head - self.n) % self.cap]

    def newest_wall(self) -> float:
        if self.bucket >= 0 and self.a_cnt > 0:
            return self.a_twl
        if self.n:
            return self.twl[(self.head - 1) % self.cap]
        return float("-inf")

    def first_wall(self) -> float:
        """Wall time of the oldest retained bucket's first sample (+inf when
        empty) — the occupancy/span read, not the coverage read."""
        if self.n:
            return self.twf[(self.head - self.n) % self.cap]
        if self.bucket >= 0 and self.a_cnt > 0:
            return self.a_twf
        return float("inf")


def _tier_items(copy: tuple) -> list[tuple]:
    """One copied tier ring's buckets, oldest first (open bucket last), as
    (tmf, tml, twf, twl, vmin, vmax, vsum, vcnt, vfirst, vlast, dpos)."""
    (_step, cap, n, head, tmf, tml, twf, twl,
     vmin, vmax, vsum, vcnt, vfirst, vlast, dpos, open_bucket) = copy
    start = (head - n) % cap
    items = [
        (tmf[i], tml[i], twf[i], twl[i], vmin[i], vmax[i], vsum[i],
         vcnt[i], vfirst[i], vlast[i], dpos[i])
        for i in ((start + k) % cap for k in range(n))
    ]
    if open_bucket is not None:
        items.append(open_bucket)
    return items


# Public names for the wall-bucketed tier machinery the root-side fleet
# store (tpu_pod_exporter.store) builds on: the ring itself, the copied-
# ring walker, and the two query folds extracted below. One implementation
# of bucket semantics — the store must answer exactly like a node ring.
TierRing = _TierRing
tier_items = _tier_items


def align_grid(
    points: Sequence[tuple[float, float]],
    start: float,
    end: float,
    step: float,
    lookback: float,
) -> list[list[float]]:
    """Align time-ordered ``(t_wall, value)`` points to the grid ``start,
    start+step, …, end``: each grid point carries the most recent sample at
    or before it, within ``lookback`` seconds (so a long-dead series does
    not project forward forever). Samples just BEFORE ``start`` are still
    eligible for the left-edge grid points — filtering them out would fake
    a gap at the start of an incident window. One forward pointer walk."""
    raw = [(tw, v) for (tw, v) in points if tw <= end]
    aligned: list[list[float]] = []
    i = -1
    t = start
    while t <= end + 1e-9:
        while i + 1 < len(raw) and raw[i + 1][0] <= t:
            i += 1
        if i >= 0 and t - raw[i][0] <= lookback:
            aligned.append([t, raw[i][1]])
        t += step
    return aligned


def fold_tier_window(
    buckets: Sequence[tuple], counter: bool
) -> dict[str, float | int | None]:
    """Window statistics recomputed EXACTLY from tier buckets (oldest
    first): min/max/first/last direct, mean via sum/count (weighted —
    bucket sample counts differ), and the counter rate from within-bucket
    positive-delta sums plus cross-bucket boundary deltas rebuilt from
    adjacent buckets' first/last values, so reset tolerance survives
    downsampling. The shared fold behind HistoryStore.window_stats and the
    fleet store's window queries."""
    nsamples = int(sum(b[7] for b in buckets))
    stats: dict[str, float | int | None] = {
        "min": min(b[4] for b in buckets),
        "max": max(b[5] for b in buckets),
        "mean": sum(b[6] for b in buckets) / nsamples,
        "first": buckets[0][8],
        "last": buckets[-1][9],
        "first_t": buckets[0][2],
        "last_t": buckets[-1][3],
        "samples": nsamples,
        "rate": None,
    }
    if counter and nsamples >= 2:
        dt = buckets[-1][1] - buckets[0][0]
        if dt > 0:
            gained = sum(b[10] for b in buckets)
            for prev, cur in zip(buckets, buckets[1:]):
                d = cur[8] - prev[9]  # boundary: first - prev last
                if d > 0:
                    gained += d
            stats["rate"] = gained / dt
    return stats


class _Series:
    """One series' identity plus its fixed-capacity ring of
    (t_mono, t_wall, value) float64 triples.

    Three parallel ``array('d')`` buffers, preallocated at construction —
    an append is three C-level stores plus index arithmetic, no Python
    object allocation. Ring state lives directly on the series (no nested
    ring object): the steady-state append loop in ``append_snapshot`` is
    the store's hot path at 256-chip scale (~4.4k series/poll) and a
    per-sample method call there is the dominant cost (measured)."""

    __slots__ = ("name", "labels", "cap", "n", "head", "tm", "tw", "vals",
                 "last_mono", "tiers", "pv")

    def __init__(self, name: str, labels: dict[str, str], cap: int,
                 tier_spec: tuple[tuple[float, int], ...] = ()) -> None:
        zeros = bytes(8 * cap)
        self.name = name
        self.labels = labels
        self.cap = cap
        self.n = 0
        self.head = 0  # next write slot
        self.tm = array("d", zeros)
        self.tw = array("d", zeros)
        self.vals = array("d", zeros)
        self.last_mono = 0.0
        # Downsample rings (finest first) + the previous raw value, from
        # which each sample's positive delta (the counter-rate unit) is
        # derived once and fed to every tier. NaN start: `v - nan > 0` is
        # False, so the first sample contributes dpos 0 with no branch.
        self.tiers = tuple(_TierRing(step, tcap) for step, tcap in tier_spec)
        self.pv = float("nan")

    def append(self, t_mono: float, t_wall: float, value: float) -> None:
        i = self.head
        self.tm[i] = t_mono
        self.tw[i] = t_wall
        self.vals[i] = value
        self.head = (i + 1) % self.cap
        if self.n < self.cap:
            self.n += 1
        self.last_mono = t_mono

    def tier_add(self, t_mono: float, t_wall: float, value: float) -> None:
        d = value - self.pv
        dpos = d if d > 0.0 else 0.0
        self.pv = value
        for t in self.tiers:
            t.add(t_mono, t_wall, value, dpos)

class HistoryStore:
    """Bounded multi-series ring-buffer store with a query API.

    Tier occupancy in :meth:`stats` refreshes at most every
    ``_TIER_STATS_INTERVAL_S`` (spans move one bucket per tier step, so a
    staler read is indistinguishable almost always).

    Thread contract: ``append*`` is called by the poll thread (one lock
    acquisition per poll, after the snapshot swap — never on the scrape
    path); queries come from HTTP handler threads and copy results out
    under the same lock.
    """

    def __init__(
        self,
        capacity: int = 301,
        max_series: int = 4096,
        retention_s: float = 300.0,
        clock: Callable[[], float] = time.monotonic,
        wallclock: Callable[[], float] = time.time,
        tiers: Sequence[tuple[float, int]] | str | None = None,
    ) -> None:
        if capacity < 2:
            raise ValueError("history capacity must be >= 2")
        if max_series < 1:
            raise ValueError("history max_series must be >= 1")
        self.capacity = capacity
        self.max_series = max_series
        self.retention_s = retention_s
        # Downsample tiers (None = the default 10 s/60 s pair; () or "off"
        # disables). Tier rings ride each series and are evicted with it;
        # they stretch query_range's answerable retention ~48× at the cost
        # of ~4× per-series memory (see DEFAULT_TIER_SPEC), still
        # hard-bounded by max_series.
        if tiers is None:
            self.tier_spec = parse_tier_spec(DEFAULT_TIER_SPEC)
        elif isinstance(tiers, str):
            self.tier_spec = parse_tier_spec(tiers)
        else:
            self.tier_spec = tuple(sorted(tiers))
        self._tiering = bool(self.tier_spec)
        self._clock = clock
        self._wallclock = wallclock
        self._lock = threading.Lock()
        # (metric, label values tuple) -> _Series. Eviction picks the
        # minimum last_mono by scan — O(series) per eviction, but evictions
        # only happen past max_series, which is sized above the worst
        # supported host shape.
        self._series: dict[tuple, _Series] = {}
        # Steady-state fast path, the history twin of the renderer's
        # FamilyLayout: when a tracked family's key tuple is identical to
        # the previous poll (no churn), its _Series objects are replayed
        # from this cache and appends run as one tight zip loop — no
        # per-sample dict lookups or method calls. Any eviction clears the
        # cache wholesale (an entry could otherwise keep feeding a series
        # that no longer exists in the store).
        self._layouts: dict[str, tuple[tuple, list[_Series]]] = {}
        self._samples = 0  # retained samples across all rings
        self._evicted = {"capacity": 0, "retention": 0}
        # Bumped on every eviction. The slow path snapshots it before
        # walking a family and refuses to cache the family's layout if it
        # changed mid-walk: an eviction can claim a series created earlier
        # in the same walk, and caching that ghost would let the fast path
        # feed a series no longer in the store — silently losing samples
        # while the eviction counter sits still.
        self._evict_gen = 0
        # Retention GC is a full-store scan; at one poll per second that
        # would cost more than the appends it polices. Amortized: scans run
        # at most every retention/32 (min 1 s), so an idle series lives at
        # most ~3% past its retention — invisible at query granularity.
        self._gc_interval_s = max(1.0, retention_s / 32.0)
        self._last_gc = 0.0
        # Tier occupancy stats are a full scan too (see _tier_stats_locked)
        # — same amortization discipline.
        self._tier_stats_cache: list[dict] | None = None
        self._tier_stats_at = 0.0

    # ---------------------------------------------------------------- append

    def append(
        self,
        metric: str,
        labels: Mapping[str, str],
        value: float,
        t_mono: float | None = None,
        t_wall: float | None = None,
    ) -> None:
        """Record one sample (public single-series entry point — used by
        ``status --watch``; the collector batches via append_snapshot)."""
        tm = self._clock() if t_mono is None else t_mono
        tw = self._wallclock() if t_wall is None else t_wall
        key = (metric, tuple(sorted(labels.items())))
        with self._lock:
            self._append_locked(key, metric, dict(labels), float(value), tm, tw)
            self._gc_locked(tm)

    def append_snapshot(
        self, snapshot: "Snapshot", now_mono: float, now_wall: float
    ) -> int:
        """Feed every tracked family of one collector snapshot; returns the
        number of samples appended. One lock acquisition for the whole poll.

        Steady state (identical family layout to the previous poll) runs
        the inlined zip loop over cached _Series objects; any churn falls
        back to the keyed path for that family and rebuilds its layout."""
        appended = 0
        tiering = self._tiering
        with self._lock:
            layouts = self._layouts
            for name in HISTORY_TRACKED_METRICS:
                spec = _SPEC_BY_NAME.get(name)
                if spec is None:
                    continue
                fam = snapshot.samples(name)
                if not fam:
                    continue
                keys = tuple(fam)
                cached = layouts.get(name)
                if cached is not None and cached[0] == keys:
                    new_samples = 0
                    for s, v in zip(cached[1], fam.values()):
                        i = s.head
                        s.tm[i] = now_mono
                        s.tw[i] = now_wall
                        s.vals[i] = v
                        i += 1
                        s.head = 0 if i == s.cap else i
                        if s.n != s.cap:
                            s.n += 1
                            new_samples += 1
                        s.last_mono = now_mono
                        if tiering:
                            s.tier_add(now_mono, now_wall, v)
                    self._samples += new_samples
                    appended += len(keys)
                    continue
                # Slow path: churn or first sighting — keyed appends, then
                # freeze this poll's series list as the next poll's layout.
                label_names = spec.label_names
                series_list: list[_Series] = []
                gen0 = self._evict_gen
                for lvs, value in fam.items():
                    key = (name, lvs)
                    s = self._series.get(key)
                    if s is None:
                        s = self._create_locked(
                            key, name, dict(zip(label_names, lvs))
                        )
                    if s.n != s.cap:
                        self._samples += 1
                    s.append(now_mono, now_wall, value)
                    if tiering:
                        s.tier_add(now_mono, now_wall, value)
                    series_list.append(s)
                    appended += 1
                if self._evict_gen == gen0:
                    layouts[name] = (keys, series_list)
                # else: an eviction landed mid-walk and series_list may hold
                # a ghost — leave the family uncached (next poll re-keys).
            self._gc_locked(now_mono)
        return appended

    def _append_locked(
        self, key: tuple, metric: str, labels: dict[str, str],
        value: float, tm: float, tw: float,
    ) -> None:
        s = self._series.get(key)
        if s is None:
            s = self._create_locked(key, metric, labels)
        if s.n != s.cap:
            self._samples += 1
        s.append(tm, tw, value)
        if self._tiering:
            s.tier_add(tm, tw, value)

    def _create_locked(self, key: tuple, metric: str,
                       labels: dict[str, str]) -> _Series:
        while len(self._series) >= self.max_series:
            victim_key = min(self._series, key=lambda k: self._series[k].last_mono)
            victim = self._series.pop(victim_key)
            self._samples -= victim.n
            self._evicted["capacity"] += 1
            self._evict_gen += 1
            self._layouts.clear()  # a layout may still reference the victim
        s = self._series[key] = _Series(metric, labels, self.capacity,
                                        self.tier_spec)
        return s

    def _gc_locked(self, now_mono: float) -> None:
        """Drop series idle past retention (amortized full scan)."""
        if self.retention_s <= 0:
            return
        if now_mono - self._last_gc < self._gc_interval_s:
            return
        self._last_gc = now_mono
        horizon = now_mono - self.retention_s
        stale = [k for k, s in self._series.items() if s.last_mono < horizon]
        for k in stale:
            s = self._series.pop(k)
            self._samples -= s.n
            self._evicted["retention"] += 1
        if stale:
            self._evict_gen += 1
            self._layouts.clear()

    # ------------------------------------------------- pressure shed hook

    def set_capacity(self, new_cap: int) -> None:
        """Rebuild every raw ring at ``new_cap``, keeping each series'
        NEWEST samples — the memory-pressure ladder's ``history_cut`` rung
        (tpu_pod_exporter.pressure). The downsample tiers are untouched
        (coarse tiers shed LAST: they are the cheapest bytes per second of
        answerable history), so long-window queries keep answering while
        raw-resolution retention halves. Reversible: a larger ``new_cap``
        re-grows the rings (existing samples preserved)."""
        new_cap = max(int(new_cap), 2)
        with self._lock:
            if new_cap == self.capacity:
                return
            zeros = bytes(8 * new_cap)
            for s in self._series.values():
                keep = min(s.n, new_cap)
                start = (s.head - keep) % s.cap
                tm = array("d", zeros)
                tw = array("d", zeros)
                vals = array("d", zeros)
                for k in range(keep):
                    i = (start + k) % s.cap
                    tm[k] = s.tm[i]
                    tw[k] = s.tw[i]
                    vals[k] = s.vals[i]
                self._samples -= s.n - keep
                s.tm, s.tw, s.vals = tm, tw, vals
                s.cap = new_cap
                s.n = keep
                s.head = keep % new_cap
            self.capacity = new_cap
            # The cached layouts hold the same _Series objects (still
            # valid — identity unchanged), so the steady-state append
            # fast path keeps working across the rebuild.

    # ----------------------------------------------- persistence (persist.py)

    def export_series(self) -> list[tuple[str, dict, list[tuple[float, float]]]]:
        """Every series' full ring as ``(metric, labels, [(t_wall, value),
        …])`` oldest-first — the checkpoint payload for crash-safe
        persistence. Raw ``array('d')`` slices are copied under the lock
        (same discipline as :meth:`_rows_for`); the per-sample tuples are
        built outside it."""
        with self._lock:
            rows = [
                (s.name, dict(s.labels), s.cap, s.n, s.head, s.tw[:], s.vals[:])
                for s in self._series.values()
            ]
        out = []
        for name, labels, cap, n, head, tw, vals in rows:
            start = (head - n) % cap
            samples = [
                (tw[i], vals[i])
                for i in ((start + k) % cap for k in range(n))
            ]
            out.append((name, labels, samples))
        return out

    def restore_series(
        self, metric: str, labels: Mapping[str, str],
        samples: list[tuple[float, float]],
        wall_to_mono: Callable[[float], float],
    ) -> int:
        """Bulk-append persisted samples (oldest first) at boot. Monotonic
        timestamps are reconstructed from wall time via ``wall_to_mono``
        (the restart reset the monotonic clock); appending past capacity
        simply wraps the ring, keeping the newest samples. Returns the
        number of samples appended.

        Key discipline: the restored series MUST land under the exact key
        the collector's ``append_snapshot`` will use on the first live
        poll — ``(metric, label-VALUE tuple in spec order)`` — or restored
        and live samples fork into two series with identical labels and
        the continuity the restore exists for is silently lost. Metrics
        outside the schema fall back to the sorted-items key that the
        generic :meth:`append` path uses, for the same reason."""
        spec = _SPEC_BY_NAME.get(metric)
        if spec is not None:
            key = (metric, tuple(str(labels.get(ln, ""))
                                 for ln in spec.label_names))
        else:
            key = (metric, tuple(sorted(labels.items())))
        lbl = dict(labels)
        with self._lock:
            for t_wall, value in samples:
                self._append_locked(
                    key, metric, lbl, float(value),
                    wall_to_mono(t_wall), t_wall,
                )
        return len(samples)

    # ----------------------------------------------------------------- query

    @staticmethod
    def _matches(labels: dict[str, str], match: Mapping[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in match.items())

    @staticmethod
    def _choose_ring(s: _Series, step: float, start: float,
                     wall_axis: bool, escalate: bool = True) -> int:
        """Tier selection for one series: index into ``s.tiers`` or -1 for
        the raw ring.

        Two rules, in order: (1) the COARSEST ring whose resolution still
        satisfies the requested step (raw when ``step`` is 0 or finer than
        every tier) — the transparent-downsample contract; (2) coverage
        escalation (``escalate``; off for raw-sample queries, whose
        contract is "the raw ring, whatever it still holds"): if the
        chosen ring has already evicted ``start``, prefer the finest
        coarser ring that still reaches it, else whichever ring reaches
        furthest back — answering an old incident window at 60 s
        resolution beats answering nothing. A ring that has not wrapped
        yet holds everything since series creation and always covers."""
        choice = -1
        if step > 0:
            for i, t in enumerate(s.tiers):
                if t.step <= step:
                    choice = i

        def oldest(idx: int) -> float:
            if idx < 0:
                if s.n < s.cap:
                    return float("-inf")
                j = (s.head - s.n) % s.cap
                return s.tw[j] if wall_axis else s.tm[j]
            t = s.tiers[idx]
            return t.oldest_wall() if wall_axis else t.oldest_mono()

        if not escalate or oldest(choice) <= start:
            return choice
        best, best_oldest = choice, oldest(choice)
        for i in range(choice + 1, len(s.tiers)):
            ow = oldest(i)
            if ow <= start:
                return i
            if ow < best_oldest:
                best, best_oldest = i, ow
        return best

    def _rows_for(self, metric: str, match: Mapping[str, str]) -> list[tuple]:
        """Matching series' ring contents, copied out under the lock as raw
        ``array('d')`` slices — C-speed memcpy, ~7 KB per series. The
        per-sample Python tuples are built OUTSIDE the lock by
        ``_row_items``: a match-less query on a 256-chip store materializes
        ~1.3M tuples, and doing that under the lock would let any client of
        the (unauthenticated) /api/v1 endpoints starve the poll thread's
        append and stall polling."""
        with self._lock:
            return [
                (s.labels, s.cap, s.n, s.head, s.tm[:], s.tw[:], s.vals[:])
                for s in self._series.values()
                if s.name == metric and self._matches(s.labels, match)
            ]

    def _query_rows(self, metric: str, match: Mapping[str, str],
                    step: float, start: float, wall_axis: bool,
                    escalate: bool = True) -> list[tuple]:
        """Tier-aware row copies for one query: per matching series, pick
        the ring :meth:`_choose_ring` selects and copy ONLY that ring
        (copying every tier of every series would multiply the under-lock
        memcpy ~4×, paid by raw-only queries that never read it). Each row
        is ``(labels, tier_step, payload, last_wall)`` where tier_step is
        0.0 for the raw ring and payload is the matching ring copy;
        last_wall is the series' newest raw sample wall time — the
        staleness stamp every query answer now carries."""
        with self._lock:
            rows: list[tuple] = []
            for s in self._series.values():
                if s.name != metric or not self._matches(s.labels, match):
                    continue
                last_wall = (
                    s.tw[(s.head - 1) % s.cap] if s.n else None
                )
                idx = (
                    self._choose_ring(s, step, start, wall_axis, escalate)
                    if s.tiers else -1
                )
                if idx < 0:
                    payload: tuple = (
                        s.labels, s.cap, s.n, s.head,
                        s.tm[:], s.tw[:], s.vals[:],
                    )
                    rows.append((s.labels, 0.0, payload, last_wall))
                else:
                    t = s.tiers[idx]
                    rows.append((s.labels, t.step, t.copy(), last_wall))
            return rows

    @staticmethod
    def _row_items(row: tuple) -> list[tuple[float, float, float]]:
        """One copied row's samples, oldest first, as (t_mono, t_wall, v)."""
        _labels, cap, n, head, tm, tw, vals = row
        start = (head - n) % cap
        return [
            (tm[i], tw[i], vals[i])
            for i in ((start + k) % cap for k in range(n))
        ]

    def series_list(self) -> list[dict]:
        with self._lock:
            return [
                {"metric": s.name, "labels": dict(s.labels),
                 "samples": s.n}
                for s in self._series.values()
            ]

    # Per-bucket value picks for tier-backed query_range grids. A bucket
    # tuple is (tmf, tml, twf, twl, vmin, vmax, vsum, vcnt, vfirst, vlast,
    # dpos) — see _tier_items.
    QUERY_AGGS: tuple[str, ...] = ("last", "min", "max", "mean")

    @staticmethod
    def _bucket_value(b: tuple, agg: str) -> float:
        if agg == "min":
            return b[4]
        if agg == "max":
            return b[5]
        if agg == "mean":
            return b[6] / b[7] if b[7] else b[9]
        return b[9]  # last

    def query_range(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        start: float | None = None,
        end: float | None = None,
        step: float = 0.0,
        agg: str = "last",
    ) -> list[dict]:
        """Samples of every matching series with wall time in [start, end].

        ``step == 0`` returns raw samples; ``step > 0`` aligns to the grid
        ``start, start+step, …, end``, each point carrying the most recent
        sample at or before it (within a ``max(2*step, 10 s)`` staleness
        lookback, so a long-dead series doesn't project forward forever).

        The backing ring is chosen per series (:meth:`_choose_ring`): the
        coarsest downsample tier whose resolution satisfies ``step``, with
        coverage escalation when the requested ``start`` predates what the
        finer ring still holds — one query spans hours without the caller
        knowing tiers exist. Tier-backed answers expose per-bucket ``agg``
        (last/min/max/mean; a duty-cycle cliff hunts with ``agg=min``);
        each result row carries ``tier`` (the bucket width served, 0 =
        raw) and ``last_sample_wall_ts`` (the series' newest sample — the
        staleness stamp federation merges key on).
        """
        if end is None:
            end = self._wallclock()
        if start is None:
            start = end - 300.0
        out: list[dict] = []
        for labels, tier_step, payload, last_wall in self._query_rows(
            metric, match or {}, step, start, True, escalate=step > 0
        ):
            if tier_step == 0.0:
                items = self._row_items(payload)
                points = [(tw, v) for (_tm, tw, v) in items]
            else:
                points = [
                    (b[3], self._bucket_value(b, agg))
                    for b in _tier_items(payload)
                ]
            if step > 0:
                # Lookback floor tracks the bucket width on tier-backed
                # answers: a 60 s bucket's single point must carry grid
                # points across its whole bucket, not just 10 s of it.
                lookback = max(2.0 * step, 2.0 * tier_step, 10.0)
                values = align_grid(points, start, end, step, lookback)
            else:
                values = [
                    [tw, v] for (tw, v) in points if start <= tw <= end
                ]
            if values:
                out.append({
                    "metric": metric, "labels": dict(labels),
                    "values": values, "tier": tier_step,
                    "last_sample_wall_ts": last_wall,
                })
        return out

    def window_stats(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        window_s: float = 60.0,
        now_mono: float | None = None,
    ) -> list[dict]:
        """min/max/mean/first/last over the trailing window, plus a
        counter-aware ``rate`` (sum of positive deltas / elapsed — the
        ICI/DCN monotonic-fold semantics: a device reset holds, it never
        goes negative). ``rate`` is null for gauges and for windows with
        fewer than two samples.

        Windows reaching past raw retention fold downsample-tier buckets
        instead: min/mean/max/first/last and sample counts recompute
        exactly from per-bucket stats, and the counter rate rebuilds
        cross-bucket boundary deltas from adjacent buckets' first/last
        values, so reset tolerance survives downsampling (a window edge
        mid-bucket includes that whole bucket — bucket-width granularity,
        not sample loss). Rows carry ``tier`` and ``last_sample_wall_ts``
        like :meth:`query_range`."""
        now = self._clock() if now_mono is None else now_mono
        lo = now - window_s
        counter = is_counter_metric(metric)
        out: list[dict] = []
        for labels, tier_step, payload, last_wall in self._query_rows(
            metric, match or {}, 0.0, lo, False
        ):
            stats: dict[str, float | int | None]
            if tier_step == 0.0:
                items = self._row_items(payload)
                win = [(tm, tw, v) for (tm, tw, v) in items if tm >= lo]
                if not win:
                    continue
                vals = [v for (_tm, _tw, v) in win]
                stats = {
                    "min": min(vals),
                    "max": max(vals),
                    "mean": sum(vals) / len(vals),
                    "first": vals[0],
                    "last": vals[-1],
                    "first_t": win[0][1],
                    "last_t": win[-1][1],
                    "samples": len(vals),
                    "rate": None,
                }
                if counter and len(win) >= 2:
                    dt = win[-1][0] - win[0][0]
                    if dt > 0:
                        gained = sum(
                            d for d in
                            (b - a for a, b in zip(vals, vals[1:]))
                            if d > 0
                        )
                        stats["rate"] = gained / dt
            else:
                buckets = [
                    b for b in _tier_items(payload) if b[1] >= lo
                ]  # bucket's last sample inside the window
                if not buckets:
                    continue
                stats = fold_tier_window(buckets, counter)
            out.append({
                "metric": metric, "labels": dict(labels), "stats": stats,
                "tier": tier_step, "last_sample_wall_ts": last_wall,
            })
        return out

    # ----------------------------------------------------------- introspection

    _TIER_STATS_INTERVAL_S = 10.0

    def _tier_stats_locked(self) -> list[dict]:
        """Per-tier occupancy/span, amortized: the full O(series × tiers)
        scan runs at most once per _TIER_STATS_INTERVAL_S and is cached —
        the collector reads stats() EVERY poll, and spans move one bucket
        per tier-step anyway, so a freshly scanned answer would be
        identical almost every time while holding the append lock longer."""
        now = self._clock()
        if (self._tier_stats_cache is not None
                and now - self._tier_stats_at < self._TIER_STATS_INTERVAL_S):
            return self._tier_stats_cache
        tiers: list[dict] = []
        for i, (step, cap) in enumerate(self.tier_spec):
            buckets = 0
            oldest = float("inf")
            newest = float("-inf")
            for s in self._series.values():
                t = s.tiers[i]
                buckets += t.n + (1 if t.bucket >= 0 and t.a_cnt else 0)
                fw = t.first_wall()
                if fw < oldest:
                    oldest = fw
                nw = t.newest_wall()
                if nw > newest:
                    newest = nw
            tiers.append({
                "step_s": step,
                "capacity": cap,
                "buckets": buckets,
                # Answerable span: how far back this tier can currently
                # reach — the occupancy read the Grafana row plots.
                "span_s": max(newest - oldest, 0.0) if buckets else 0.0,
            })
        self._tier_stats_cache = tiers
        self._tier_stats_at = now
        return tiers

    def stats(self) -> dict:
        with self._lock:
            nseries = len(self._series)
            # Three float64 arrays per raw ring plus 11 per tier bucket,
            # all preallocated at full capacity per series present.
            per_series = self.capacity * 24 + sum(
                cap * _TIER_BUCKET_BYTES for _step, cap in self.tier_spec
            )
            tiers = self._tier_stats_locked()
            return {
                "series": nseries,
                "samples": self._samples,
                "evicted": dict(self._evicted),
                "capacity": self.capacity,
                "max_series": self.max_series,
                "retention_s": self.retention_s,
                "memory_bytes": nseries * per_series,
                "tiers": tiers,
            }


# --------------------------------------------------------------------- demo


def main(argv: list[str] | None = None) -> int:
    """Replay a recorded backend trace through a real collector into a
    HistoryStore and print the flight recorder's answers — offline incident
    forensics with zero hardware (``make history-demo``)."""
    import argparse

    from tpu_pod_exporter.attribution.fake import FakeAttribution
    from tpu_pod_exporter.backend.recorded import RecordedBackend
    from tpu_pod_exporter.collector import Collector
    from tpu_pod_exporter.metrics import SnapshotStore

    p = argparse.ArgumentParser(
        prog="tpu-pod-exporter-history",
        description="Replay a recorded trace into the telemetry flight "
                    "recorder and print window stats.",
    )
    p.add_argument("--replay", required=True,
                   help="JSONL trace recorded with --record-to")
    p.add_argument("--polls", type=int, default=0,
                   help="polls to replay (default: one pass over the trace)")
    p.add_argument("--interval-s", type=float, default=1.0,
                   help="simulated seconds between replayed polls")
    p.add_argument("--window-s", type=float, default=0.0,
                   help="window for stats (default: the whole replay)")
    ns = p.parse_args(argv)

    backend = RecordedBackend(ns.replay, loop=True)
    polls = ns.polls or len(backend)
    window = ns.window_s or polls * ns.interval_s + 1.0

    # Simulated clocks: the replay runs at memory speed but history sees
    # evenly spaced poll timestamps, so rates/windows mean what they say.
    sim = {"t": 0.0}
    base_wall = 1_700_000_000.0
    history = HistoryStore(
        capacity=max(2, min(polls + 1, 4096)),
        retention_s=0.0,  # forensics replay: never age anything out
        clock=lambda: sim["t"],
        wallclock=lambda: base_wall + sim["t"],
    )
    collector = Collector(
        backend, FakeAttribution(), SnapshotStore(), history=history,
        clock=lambda: sim["t"], wallclock=lambda: base_wall + sim["t"],
    )
    for i in range(polls):
        sim["t"] = i * ns.interval_s
        collector.poll_once()

    st = history.stats()
    print(f"replayed {polls} polls from {ns.replay}")
    print(f"history: {st['series']} series, {st['samples']} samples, "
          f"~{st['memory_bytes'] / 1024:.0f} KiB, evicted={st['evicted']}")
    metrics = sorted({s["metric"] for s in history.series_list()})
    if not metrics:
        print("no tracked series in this trace")
        return 0
    for metric in metrics:
        print(f"\n{metric} (window={window:g}s):")
        for row in history.window_stats(metric, window_s=window,
                                        now_mono=sim["t"] + 1e-9):
            s = row["stats"]
            ident = ",".join(
                f"{k}={v}" for k, v in sorted(row["labels"].items()) if v
            )
            rate = "" if s["rate"] is None else f" rate={s['rate']:.1f}/s"
            print(f"  {{{ident}}} n={s['samples']} min={s['min']:g} "
                  f"max={s['max']:g} mean={s['mean']:g} last={s['last']:g}"
                  f"{rate}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
