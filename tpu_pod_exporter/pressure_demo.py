"""The ``make pressure-demo`` drills — resource pressure, end to end.

Three drills against REAL components (the chaos-demo/egress-demo
pattern: build the thing, hurt it deterministically, assert the policy):

1. **disk** — a live in-process :class:`~tpu_pod_exporter.app.ExporterApp`
   (fake backend, real persister + WAL + checkpoints, real egress into a
   ledgered :class:`~tpu_pod_exporter.chaos.ChaosReceiver`) on a disk
   budget its steady state cannot fit: the governor must climb the WHOLE
   ladder in order (WAL thinning → egress compaction → checkpoint halving
   → WAL off), usage must stop growing, scraping must keep serving, every
   rung must be attributable from ``/metrics`` alone, the egress
   exactly-once ledger must end intact — and when the budget is raised,
   the ladder must step back down rung by rung with hysteresis.
2. **memory** — history rings + trace ring + a fleet query cache under a
   byte budget half their filled size: sheds must land coarse-tiers-last
   (fleet cache → trace halving → raw-ring cut), the accounted bytes must
   converge under the budget, the raw rings must keep their NEWEST
   samples, and recovery must restore every knob.
3. **storm** — a :class:`~tpu_pod_exporter.server.MetricsServer` with
   admission control vs a 500-connection keep-alive storm: a polite
   scraper's p99 stays within the budget of its pre-storm baseline, the
   storm costs rejected requests (counted per cause), and open
   connections never exceed the cap.

``run_disk_drill(governor=False)`` is the NEGATIVE CONTROL: the same
workload with no budget configured must visibly break the disk invariant
(usage grows past the budget the governed run respected) — proving the
drill can fail. ``make pressure-demo`` runs all three plus the control.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
import urllib.request


def _p99(lat: list[float]) -> float:
    lat = sorted(lat)
    return lat[min(int(len(lat) * 0.99), len(lat) - 1)]


def _metric_values(body: str, name: str) -> dict[str, float]:
    """``name{labels} value`` lines → {labels-part: value} (labels-part
    "" for label-less series)."""
    out: dict[str, float] = {}
    for line in body.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest.startswith("{"):
            labels, _, val = rest[1:].partition("} ")
            try:
                out[labels] = float(val)
            except ValueError:
                continue
        elif rest.startswith(" "):
            try:
                out[""] = float(rest[1:])
            except ValueError:
                continue
    return out


# ------------------------------------------------------------------ disk


DISK_BUDGET_BYTES = 48 << 10   # deliberately below the steady working set


def run_disk_drill(state_dir: str, governor: bool) -> int:
    """The disk-full ladder against a real exporter (see module doc).
    ``governor=False`` is the negative control: same workload, no budget —
    returns 0 only when the invariant VISIBLY breaks."""
    from tpu_pod_exporter.app import ExporterApp
    from tpu_pod_exporter.chaos import ChaosReceiver
    from tpu_pod_exporter.config import ExporterConfig
    from tpu_pod_exporter.pressure import dir_usage_bytes

    what = "disk drill" if governor else "disk drill NEGATIVE CONTROL"
    own_dir = not state_dir
    root = state_dir or tempfile.mkdtemp(prefix="tpe-pressure-demo-")
    sdir = os.path.join(root, "state")
    edir = os.path.join(root, "egress")
    receiver = ChaosReceiver([], seed=3)
    receiver.start()
    cfg = ExporterConfig(
        port=0, host="127.0.0.1", interval_s=0.1,
        backend="fake", fake_chips=4, attribution="none",
        history_retention_s=5.0,
        state_dir=sdir,
        state_snapshot_interval_s=1.0,
        state_fsync_interval_s=0.0,
        egress_url=receiver.url, egress_dir=edir, egress_interval_s=0.0,
        state_max_disk_mb=(DISK_BUDGET_BYTES / (1 << 20)) if governor
        else 0.0,
        log_level="warning",
    )
    app = ExporterApp(cfg)
    rc = 1
    try:
        if governor:
            assert app.governor is not None
            # Demo pacing: production hysteresis is 30 s; the drill wants
            # the whole shed+recover cycle inside ~20 s.
            app.governor.check_interval_s = 0.2
            app.governor.hysteresis_s = 0.5
        app.start()
        base = f"http://127.0.0.1:{app.port}"
        print(f"{what}: exporter on {base}, budget "
              f"{DISK_BUDGET_BYTES // 1024} KiB over {sdir} + {edir}"
              if governor else
              f"{what}: exporter on {base}, NO budget (reference "
              f"{DISK_BUDGET_BYTES // 1024} KiB)")

        seen_levels: list[int] = []
        deadline = time.monotonic() + 25.0
        while time.monotonic() < deadline:
            time.sleep(0.4)
            if app.governor is not None:
                lvl = app.governor.stats()["disk"]["level"]
                if not seen_levels or seen_levels[-1] != lvl:
                    seen_levels.append(lvl)
                if governor and lvl >= 4:
                    break
        usage = dir_usage_bytes(sdir) + dir_usage_bytes(edir)
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            body = r.read().decode()
        states = _metric_values(body, "tpu_exporter_pressure_state")
        disk_state = states.get('resource="disk"')

        if not governor:
            # Negative control: the invariant must VISIBLY break — usage
            # over the budget the governed run respected, with the ladder
            # flat at 0 (nothing shed, nothing reclaimed).
            print(f"         usage {usage}B vs the governed run's budget "
                  f"{DISK_BUDGET_BYTES}B; published disk ladder level: "
                  f"{disk_state}")
            if usage > DISK_BUDGET_BYTES and not disk_state:
                print("negative control OK: without the governor the disk "
                      "budget invariant visibly breaks (usage over budget, "
                      "zero shedding)")
                rc = 0
            else:
                print("NEGATIVE CONTROL FAILED: the invariant did not "
                      "break without the governor — the drill proves "
                      "nothing")
            return rc

        gs = app.governor.stats()["disk"]
        print(f"         ladder levels over time: {seen_levels}; usage "
              f"{usage}B; exposition pressure_state[disk]={disk_state}")
        problems: list[str] = []
        if gs["level"] < 4:
            problems.append(f"ladder never reached wal_off (level "
                            f"{gs['level']}, rungs {gs['rungs']})")
        if sorted(set(seen_levels)) != seen_levels_monotone(seen_levels):
            problems.append(f"ladder did not climb monotonically: "
                            f"{seen_levels}")
        if disk_state != float(gs["level"]):
            problems.append(
                f"exposition disagrees with the governor: "
                f"pressure_state={disk_state} vs level {gs['level']}")
        ps = app.persister.stats()
        if ps["dropped_by_reason"]["shed"] == 0:
            problems.append("no WAL records were shed (stride/off rungs "
                            "inert?)")
        if not ps["wal_enabled"]:
            pass  # wal_off applied — expected at level 4
        else:
            problems.append("wal_off rung did not disable the WAL")
        # Serving never stopped: a scrape right now answers 200 with data.
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            if r.status != 200:
                problems.append(f"/metrics answered {r.status} under "
                                f"pressure")

        # Relief: raise the budget; the ladder must step back to 0 rung
        # by rung (hysteresis) and the WAL must resume.
        app.governor.set_disk_budget_bytes(64 << 20)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if app.governor.stats()["disk"]["level"] == 0:
                break
            time.sleep(0.3)
        gs = app.governor.stats()["disk"]
        if gs["level"] != 0:
            problems.append(f"ladder stuck at level {gs['level']} after "
                            f"the budget was raised")
        if gs["recovers"] < 4:
            problems.append(f"expected >= 4 rung-by-rung recoveries, got "
                            f"{gs['recovers']}")
        ps = app.persister.stats()
        if not (ps["wal_enabled"] and ps["wal_stride"] == 1
                and ps["snapshot_factor"] == 1.0):
            problems.append(f"persister not fully restored after "
                            f"recovery: {ps['wal_enabled']=} "
                            f"{ps['wal_stride']=} {ps['snapshot_factor']=}")
        print(f"         recovery: level {gs['level']}, "
              f"{gs['sheds']} shed(s) / {gs['recovers']} recover(s)")

        # The egress exactly-once ledger survived the whole window.
        app.stop()  # final flush before reading the ledger
        stats = app.shipper.stats()
        ledger = receiver.stats()
        seqs = sorted(ledger["accepted_seqs"])
        if ledger["duplicate_seqs"] or ledger["duplicate_samples"]:
            problems.append(f"ledger saw duplicates: "
                            f"{len(ledger['duplicate_seqs'])} batches / "
                            f"{ledger['duplicate_samples']} samples")
        if seqs != list(range(1, len(seqs) + 1)):
            problems.append(f"ledger not contiguous: {seqs[:5]}…")
        print(f"         ledger: {len(seqs)} batches delivered "
              f"exactly-once (enqueued {stats['enqueued_batches']})")
        if problems:
            for p in problems:
                print(f"FAIL: {p}")
            return 1
        print("disk drill OK: full ladder climb, bounded usage, serving "
              "throughout, exactly-once ledger, rung-by-rung recovery")
        rc = 0
        return rc
    finally:
        try:
            app.stop()
        except Exception:  # noqa: BLE001 — teardown must finish
            pass
        receiver.stop()
        if own_dir and rc == 0:
            shutil.rmtree(root, ignore_errors=True)
        elif rc != 0:
            print(f"state kept for inspection: {root}")


def seen_levels_monotone(levels: list[int]) -> list[int]:
    """Helper for the climb-order assertion: the distinct levels seen,
    in first-seen order (a monotone climb sees them sorted)."""
    out: list[int] = []
    for lvl in levels:
        if lvl not in out:
            out.append(lvl)
    return out


# ---------------------------------------------------------------- memory


def run_memory_drill() -> int:
    """Memory-budget shedding over real components: fleet cache → trace
    ring halving → raw-ring cut, in that order, converging under budget
    while the raw rings keep their newest samples."""
    from tpu_pod_exporter.fleet import _QueryCache
    from tpu_pod_exporter.history import HistoryStore
    from tpu_pod_exporter.pressure import PressureGovernor
    from tpu_pod_exporter.trace import PollTrace, TraceStore

    # Raw rings only: the drill's convergence arithmetic is exact on the
    # 24-bytes-per-slot raw arrays (the downsample tiers are precisely the
    # memory the ladder REFUSES to shed — coarse data is cheapest).
    history = HistoryStore(capacity=256, max_series=4096, retention_s=0.0,
                           tiers=())
    base_wall = 1_700_000_000.0
    for i in range(200):
        for s in range(40):
            history.append("tpu_hbm_used_bytes", {"chip_id": str(s)},
                           float(i), t_mono=float(i),
                           t_wall=base_wall + i)
    trace_store = TraceStore(max_traces=256)
    for i in range(256):
        tr = PollTrace("poll", time.monotonic, time.time)
        for phase in ("device_read", "publish"):
            tr.begin(phase)
            tr.end("ok")
        trace_store.append(tr)
    cache = _QueryCache(512)
    fat = {"status": "ok", "data": {"result": ["x" * 64] * 16}}
    for i in range(300):
        cache.put(("window_stats", f"q{i}", 0, i), dict(fat))

    gov = PressureGovernor(check_interval_s=0.05, hysteresis_s=0.2)
    gov.register_memory_component("fleet_cache", cache.bytes)
    gov.register_memory_component("trace", trace_store.memory_bytes)
    gov.register_memory_component(
        "history", lambda: int(history.stats()["memory_bytes"]))
    shed_order: list[str] = []

    def shed(name, fn):
        def _apply():
            shed_order.append(name)
            fn()
        return _apply

    gov.add_memory_rung(
        "fleet_cache", shed("fleet_cache",
                            lambda: cache.set_enabled(False)),
        lambda: cache.set_enabled(True))
    gov.add_memory_rung(
        "trace_halved",
        shed("trace_halved",
             lambda: trace_store.set_max_traces(
                 max(trace_store.max_traces // 2, 8))),
        lambda: trace_store.set_max_traces(256))
    gov.add_memory_rung(
        "history_cut",
        shed("history_cut",
             lambda: history.set_capacity(max(history.capacity // 2, 16))),
        lambda: history.set_capacity(256))

    filled = gov._memory_usage()
    hist_bytes = int(history.stats()["memory_bytes"])
    trace_bytes = trace_store.memory_bytes()
    # Between (trace/2 + hist/2) and (trace/2 + hist): every rung must
    # fire before the accounted bytes fit, and the full ladder suffices.
    budget = int(trace_bytes / 2 + hist_bytes * 0.75)
    gov.set_memory_budget_bytes(budget)
    print(f"memory drill: accounted {filled}B (history {hist_bytes}B), "
          f"budget {budget}B")
    for _ in range(12):
        gov.tick()
        if gov._memory_usage() <= budget and gov.stats()["memory"]["level"] >= 3:
            break
        time.sleep(0.02)
    problems: list[str] = []
    accounted = gov._memory_usage()
    gs = gov.stats()["memory"]
    print(f"         shed order {shed_order}; accounted {accounted}B; "
          f"level {gs['level']}")
    if shed_order != ["fleet_cache", "trace_halved", "history_cut"]:
        problems.append(f"shed order wrong: {shed_order} (coarse tiers "
                        f"must shed LAST)")
    if accounted > budget:
        problems.append(f"accounted {accounted}B still over budget "
                        f"{budget}B after the full ladder")
    if cache.bytes() != 0:
        problems.append("fleet cache not cleared")
    rows = history.query_range("tpu_hbm_used_bytes",
                               {"chip_id": "0"},
                               start=base_wall, end=base_wall + 300)
    if not rows or rows[0]["values"][-1][1] != 199.0:
        problems.append("history lost its NEWEST samples in the cut")
    # The exposition view agrees with the governor.
    from tpu_pod_exporter.metrics import SnapshotBuilder

    b = SnapshotBuilder()
    gov.emit(b)
    body = b.build(timestamp=time.time()).encode().decode()
    states = _metric_values(body, "tpu_exporter_pressure_state")
    if states.get('resource="memory"') != float(gs["level"]):
        problems.append(f"exposition pressure_state disagrees: {states}")
    # Relief: budget off; the ladder must unwind fully.
    gov.set_memory_budget_bytes(0)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        gov.tick()
        if gov.stats()["memory"]["level"] == 0:
            break
        time.sleep(0.05)
    gs = gov.stats()["memory"]
    if gs["level"] != 0:
        problems.append(f"memory ladder stuck at {gs['level']} after "
                        f"relief")
    if history.capacity != 256 or trace_store.max_traces != 256:
        problems.append("recovery did not restore capacities")
    if problems:
        for p in problems:
            print(f"FAIL: {p}")
        return 1
    print("memory drill OK: coarse-tiers-last shedding, budget "
          "convergence, newest samples kept, full recovery")
    return 0


# ----------------------------------------------------------------- storm


def run_storm_drill(conns: int, slack_frac: float, slack_s: float) -> int:
    """Scrape-storm admission control: a polite scraper's p99 stays within
    ``baseline * (1 + slack_frac) + slack_s`` while ``conns`` aggressive
    keep-alive connections hammer the same server."""
    import http.client

    from tpu_pod_exporter.attribution.fake import FakeAttribution
    from tpu_pod_exporter.backend.fake import FakeBackend
    from tpu_pod_exporter.chaos import ScrapeStorm
    from tpu_pod_exporter.collector import Collector
    from tpu_pod_exporter.metrics import SnapshotStore
    from tpu_pod_exporter.server import MetricsServer

    store = SnapshotStore()
    collector = Collector(FakeBackend(chips=64), FakeAttribution(), store)
    collector.poll_once()
    conn_cap = 16
    server = MetricsServer(
        store, host="127.0.0.1", port=0,
        max_concurrent_scrapes=4,
        # The drill isolates ADMISSION control; the token-bucket rate cap
        # (a different, earlier defense) would 429 the polite scraper and
        # the storm alike and mask what is being measured here.
        max_scrapes_per_s=0.0,
        max_open_connections=conn_cap,
        max_requests_per_client=8,
    )
    server.start()
    rc = 1
    storm = None
    try:
        # ONE long-lived keep-alive connection, established BEFORE the
        # storm — the shape of a real Prometheus scraper. Its admitted
        # connection slot is held for the duration, which is exactly how
        # admission control protects an incumbent scraper from a storm
        # (a NEW connection during a full-cap storm is indistinguishable
        # from the storm and gets the same 429).
        polite = http.client.HTTPConnection("127.0.0.1", server.port,
                                            timeout=10)

        def polite_p99(n: int) -> float:
            lat: list[float] = []
            for _ in range(n):
                t0 = time.perf_counter()
                polite.request("GET", "/metrics")
                resp = polite.getresponse()
                body = resp.read()
                if resp.status != 200 or not body:
                    raise RuntimeError(
                        f"polite scrape failed: {resp.status}")
                lat.append(time.perf_counter() - t0)
            return _p99(lat)

        baseline = polite_p99(150)
        storm = ScrapeStorm("127.0.0.1", server.port, conns=conns,
                            pause_s=0.02, reject_pause_s=1.0)
        storm.start()
        time.sleep(1.0)  # let the storm reach steady state
        try:
            during = polite_p99(150)
        except (OSError, RuntimeError) as e:
            # The incumbent scraper being rejected/disconnected IS the
            # drill failing — report it, never a traceback.
            print(f"FAIL: polite scraper failed during the storm: {e}")
            return 1
        finally:
            storm.stop()
            polite.close()
        st = storm.stats()
        peak = server.conn_stats["peak"]
        budget = baseline * (1.0 + slack_frac) + slack_s
        print(f"storm drill: {conns} conns; polite p99 "
              f"{1e3 * baseline:.2f}ms -> {1e3 * during:.2f}ms "
              f"(budget {1e3 * budget:.2f}ms); storm served "
              f"{st['served']} / rejected {st['rejected']} "
              f"(errors {st['errors']}); open-conn peak {peak} "
              f"(cap {conn_cap})")
        problems: list[str] = []
        if during > budget:
            problems.append(f"polite p99 {1e3 * during:.2f}ms blew the "
                            f"budget {1e3 * budget:.2f}ms")
        if st["rejected"] == 0:
            problems.append("storm drew zero 429s — admission control "
                            "inert")
        if peak > conn_cap:
            problems.append(f"open connections peaked at {peak} over the "
                            f"{conn_cap} cap")
        rejects = dict(server.scrape_rejects)
        if rejects.get("connections", 0) + rejects.get("client", 0) == 0:
            problems.append(f"no admission rejects counted: {rejects}")
        if problems:
            for p in problems:
                print(f"FAIL: {p}")
            return 1
        print(f"storm drill OK: rejects by cause {rejects}")
        rc = 0
        return rc
    finally:
        if storm is not None:
            storm.stop()
        server.stop()


def _write_result(path: str, doc: dict) -> None:
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
    except OSError:
        pass
