"""HTTP export surface — ``GET /metrics`` plus health endpoints.

Analog of the reference's promhttp goroutine (``main.go:67-72``), with the
scrape path made collection-free *and* render-free: the poll loop pre-encodes
the exposition text into the SnapshotStore, so a scrape is one lock, one
reference read, and one ``sendall`` of cached bytes. This is what keeps p99
scrape latency flat regardless of chip count (SURVEY.md §3.3, §7 "hard
parts").

Additional endpoints the reference lacks:
- ``/healthz`` — liveness (process up, returns 200 always).
- ``/readyz`` — readiness JSON (200 once data is being served, 503
  before) with a ``state`` field: ``starting`` / ``warm`` (serving a
  restored pre-restart snapshot, first live poll pending — see
  ``tpu_pod_exporter.persist``) / ``ready`` / ``degraded``.
- ``/api/v1/series`` / ``/api/v1/query_range`` / ``/api/v1/window_stats`` —
  JSON queries against the node-local history flight recorder
  (``tpu_pod_exporter.history``); served on the metrics port because the
  slice aggregator consumes them. Absent history (``--history-retention-s
  0``) answers 404 JSON. On the aggregator the same routes are served by
  the federated fleet query plane (``tpu_pod_exporter.fleet``) behind the
  same 2-permit fence.
- ``/debug/vars``, ``/debug/stacks`` and ``/debug/trace`` (poll traces as
  Chrome ``trace_event`` JSON) answer **loopback clients only** by default
  (thread stacks, config and traces are operator surface, not fleet
  surface); ``--debug-addr 0.0.0.0`` restores remote access.

The server is a stdlib ThreadingHTTPServer: no event-loop dependency, a few
concurrent scrapers at most (Prometheus), and request handling does no
per-request allocation beyond headers.
"""

from __future__ import annotations

import json
import logging
import math
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.trace import parse_traceparent, to_chrome_trace


def _json_sanitize(obj):
    """Replace non-finite floats with None, recursively (slow path of
    _serve_json — only runs when a response actually contains one)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _json_sanitize(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_json_sanitize(v) for v in obj]
    return obj


def debug_client_allowed(client_ip: str, debug_addr: str) -> bool:
    """Whether a /debug/* request from ``client_ip`` may be served.

    Default policy is loopback-only: thread stacks and effective config are
    operator surface. ``--debug-addr 0.0.0.0`` (or ``*``) opens the debug
    endpoints to any client that can reach the metrics port. Loopback is
    always allowed regardless of the setting — the RUNBOOK's on-node curl
    must never lock itself out."""
    if client_ip.startswith("127.") or client_ip == "::1" or client_ip.startswith("::ffff:127."):
        return True
    return debug_addr in ("0.0.0.0", "*")


def _format_stacks() -> str:
    """Every live thread's Python stack, one block per thread.

    ``sys._current_frames`` is a documented-CPython atomic snapshot (the
    dict is built under the GIL); traceback formatting walks frame objects
    that stay valid while referenced, so a wedged thread's stack renders
    even though that thread never cooperates."""
    import sys
    import traceback

    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(frames.items()):
        t = by_id.get(ident)
        name = t.name if t else "?"
        daemon = " daemon" if (t and t.daemon) else ""
        out.append(f"--- thread {ident} ({name}){daemon} ---")
        out.extend(
            line.rstrip("\n")
            for line in traceback.format_stack(frame)
        )
        out.append("")
    return "\n".join(out) + "\n"

log = logging.getLogger("tpu_pod_exporter.server")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

def prerender_429(body: bytes, content_type: str) -> bytes:
    """A 429 + Retry-After response as raw wire bytes, rendered once at
    import: under a storm the reject path runs per request, and
    BaseHTTPRequestHandler.send_response formats a Date header and three
    header lines each time — measurable CPU that a reject must not spend.
    ``Connection: close`` both caps the handler thread's lifetime and tells
    well-behaved clients to back off the keep-alive connection. Shared by
    the /metrics scrape guard and the /api/v1 query fence (exporter and
    aggregator both — extracted, not duplicated)."""
    return (
        b"HTTP/1.1 429 Too Many Requests\r\n"
        b"Content-Type: " + content_type.encode("ascii") + b"\r\n"
        b"Retry-After: 1\r\n"
        b"Content-Length: " + str(len(body)).encode("ascii") + b"\r\n"
        b"Connection: close\r\n"
        b"\r\n" + body
    )


_REJECT_RESPONSE = prerender_429(
    b"too many concurrent scrapes\n", "text/plain; charset=utf-8"
)
# The /api/v1 fence's twin: still JSON (every consumer of these endpoints
# parses JSON, including during the very storm this rejects).
_API_REJECT_RESPONSE = prerender_429(
    b'{"status": "error", "error": "too many concurrent api queries"}',
    "application/json",
)
# Admission-control rejects (scrape-storm defense, tpu_pod_exporter
# ISSUE 10): a storm must cost rejected requests, never file descriptors
# or handler-thread pile-up. Same pre-rendered-bytes discipline as the
# scrape guard — the reject path runs per storm request.
_CONN_REJECT_RESPONSE = prerender_429(
    b"connection limit reached\n", "text/plain; charset=utf-8"
)
_CLIENT_REJECT_RESPONSE = prerender_429(
    b"per-client request limit reached\n", "text/plain; charset=utf-8"
)

# Probe paths exempt from admission control: a scrape storm must never be
# able to 429 kubelet's liveness/readiness probes into restarting the pod.
_ADMISSION_EXEMPT_PATHS = ("/healthz", "/readyz")


def accepts_openmetrics(accept: str) -> bool:
    """Whether content negotiation should pick OpenMetrics over plain text.

    A real (if minimal) q-value parse rather than a substring test
    (RFC 9110 §12.4.2 subset): OpenMetrics is served only when its q is
    positive AND at least the q the client gave ``text/plain`` (directly or
    via a wildcard) — a client sending ``text/plain;q=1,
    application/openmetrics-text;q=0.1`` deliberately prefers text and must
    get it. Malformed q-values count as q=1; unlisted types inherit the
    wildcard q, if any.
    """
    qs: dict[str, float] = {}
    for entry in accept.split(","):
        parts = entry.split(";")
        mtype = parts[0].strip().lower()
        if not mtype:
            continue
        q = 1.0
        for param in parts[1:]:
            name, _, value = param.partition("=")
            if name.strip().lower() == "q":
                try:
                    q = float(value.strip())
                except ValueError:
                    q = 1.0
        qs[mtype] = max(q, qs.get(mtype, 0.0))
    wildcard = max(qs.get("*/*", 0.0), qs.get("text/*", 0.0))
    q_om = qs.get("application/openmetrics-text", 0.0)
    q_text = qs.get("text/plain", wildcard)
    return q_om > 0.0 and q_om >= q_text


class _TokenBucket:
    """Scrape-rate cap for /metrics. The concurrency semaphore bounds how
    many big bodies are in flight, but not how many per second — and a
    sequential storm of full-body scrapes is pure kernel-copy cost
    (~0.4 ms CPU per ~950 KB body at 256 chips; measured, bench.py) that
    no amount of server cleverness removes. Above the bucket rate the
    exporter answers with the pre-rendered 429 instead: monitoring losing
    a scrape beats monitoring stealing the TPU host's cores. The default
    rate (config.max_scrapes_per_s=100) is ~20× any sane setup — a few
    Prometheus replicas plus an aggregator at 1 Hz."""

    __slots__ = ("rate", "burst", "tokens", "last", "lock")

    def __init__(self, rate: float, burst: float) -> None:
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.last = time.monotonic()
        self.lock = threading.Lock()

    def take(self) -> bool:
        with self.lock:
            # monotonic() read INSIDE the lock: a stale `now` against a
            # newer `last` written by another thread would apply a negative
            # refill, silently draining tokens (code-review r5).
            now = time.monotonic()
            self.tokens = min(
                self.burst, self.tokens + (now - self.last) * self.rate
            )
            self.last = now
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            return False

    def refund(self) -> None:
        """Return a taken token (the request was refused downstream anyway —
        e.g. by the concurrency guard — so it must not count against the
        rate: a stall would otherwise drain the bucket and 429 well-behaved
        scrapers after it clears)."""
        with self.lock:
            self.tokens = min(self.burst, self.tokens + 1.0)


class _Handler(BaseHTTPRequestHandler):
    # set by server factory
    store: SnapshotStore
    debug_vars = None  # optional callable -> dict
    # Optional HistoryStore serving /api/v1/*; None = history disabled.
    history = None
    # Optional fleet.FleetQueryPlane: when set (the aggregator), /api/v1/*
    # routes are answered by the federated fan-out instead of a local
    # history store, behind the same api_sem fence.
    fleet = None
    # Optional trace.TraceStore: serves GET /debug/trace (Chrome
    # trace_event JSON) and records a node-side scrape span whenever a
    # /metrics request carries a traceparent header (the aggregator's
    # fan-out propagation). None = tracing disabled (--trace off).
    trace = None
    # Concurrency fence for /api/v1/*: queries copy ring contents (cheap,
    # but not free at 256-chip scale) and ThreadingHTTPServer spawns a
    # thread per request — without a cap, a flood of history queries could
    # keep the store lock contended against the poll thread's append.
    # Small and separate from the scrape semaphore: the aggregator's
    # missed-round fallback must not queue behind a scrape storm.
    api_sem: threading.BoundedSemaphore | None = None
    api_queue_timeout_s: float = 0.25
    # /debug/* exposure policy (see debug_client_allowed).
    debug_addr: str = "127.0.0.1"
    # /healthz fails when the newest snapshot is older than this (0 = never).
    # A poll thread wedged inside a hung device runtime stops swapping
    # snapshots; liveness must catch that so kubelet restarts the pod —
    # serving stale bytes forever would look "up" while monitoring nothing.
    health_max_age_s: float = 0.0
    # Optional () -> str|None liveness hook, checked before the staleness
    # rule: a non-None reason fails /healthz IMMEDIATELY (e.g. the poll
    # loop thread died and its one restart is spent) instead of waiting
    # health_max_age_s for the snapshot to go stale.
    live_fn = None
    # Optional () -> dict merged into the /readyz JSON body — degraded
    # readiness detail (e.g. sources whose circuit breaker has been open
    # across several probes). Detail only: it never flips the status code;
    # a degraded-but-serving exporter must keep its endpoints in rotation.
    ready_detail_fn = None
    # Concurrency guard for /metrics: at most N handlers render/send at
    # once; excess requests queue briefly, then get 429 + Retry-After. A
    # misconfigured scrape storm (BENCH: ~1k scrapes/s ate half a core)
    # must not be able to starve the workload's cores — monitoring losing
    # a scrape beats monitoring stealing the TPU host's CPU.
    scrape_sem: threading.BoundedSemaphore | None = None
    scrape_queue_timeout_s: float = 0.25
    scrape_bucket: _TokenBucket | None = None
    # Rate-cap rejects sleep this long before answering: a fast 429 just
    # makes a storming client retry faster (measured: a sequential storm
    # against an instant reject still ate >30% of a core in connection
    # churn alone), while a tarpitted one is throttled to ~10 attempts/s
    # per connection. Sleeping threads cost memory, not CPU; the slot cap
    # below keeps a massively-concurrent flood from parking unbounded
    # threads (overflow rejects immediately).
    scrape_tarpit_s: float = 0.1
    tarpit_slots: threading.BoundedSemaphore | None = None
    scrape_rejects = None  # {"concurrency": int, "rate": int}, shared per server
    scrape_rejects_lock: threading.Lock | None = None
    # Optional (duration_s: float) -> None, called for every SERVED scrape
    # (rejects excluded — a tarpit sleep is not a scrape latency). Feeds the
    # tpu_exporter_scrape_duration_seconds histogram; must stay cheap, it
    # runs on the scrape path.
    scrape_observer = None
    # Admission control (resource-pressure ISSUE 10): a hard cap on OPEN
    # connections (keep-alive scrapers parked on handler threads are the
    # FD/thread cost a storm inflicts on a thread-per-connection server)
    # plus a per-client-IP concurrent-request cap. Over-cap connections
    # are answered with the pre-rendered 429 + Retry-After and closed —
    # except the kubelet probe paths, which always answer (a storm must
    # not restart the pod). None/0 = disabled (the exporter app enables
    # them via --max-open-connections / --max-requests-per-client).
    conn_slots: threading.BoundedSemaphore | None = None
    conn_stats = None   # {"open": int, "peak": int}, shared per server
    conn_lock: threading.Lock | None = None
    max_requests_per_client: int = 0
    client_active = None  # {ip: concurrent requests}, shared per server
    client_lock: threading.Lock | None = None
    # Slow-client write defense: per-connection socket SEND timeout
    # (SO_SNDTIMEO — receive-side keep-alive idling is unaffected). A
    # scraper that stops reading mid-body would otherwise pin this handler
    # thread inside sendall() forever; with the option set, the blocked
    # send raises after this many seconds, the connection is dropped, and
    # the drop is counted (tpu_exporter_client_write_timeouts_total).
    client_write_timeout_s: float = 10.0
    write_timeouts = None  # {"total": int}, shared per server
    write_timeouts_lock: threading.Lock | None = None
    # Optional () -> dict|None: non-None means the server is WARM-serving a
    # restored pre-restart snapshot (no live poll yet); merged into the
    # /readyz body as state="warm" detail. See tpu_pod_exporter.persist.
    warm_fn = None
    protocol_version = "HTTP/1.1"

    def setup(self) -> None:
        super().setup()
        # Connection admission: a slot is held for the connection's whole
        # lifetime (keep-alive included). Over-cap connections still get
        # ONE request handled — 429 for anything but the probe paths —
        # then close; the cost of that bounded courtesy is one short-lived
        # thread, not a parked one.
        self._admitted = True
        slots = self.conn_slots
        if slots is not None:
            self._admitted = slots.acquire(blocking=False)
        if self.conn_stats is not None and self._admitted:
            with self.conn_lock:
                self.conn_stats["open"] += 1
                if self.conn_stats["open"] > self.conn_stats["peak"]:
                    self.conn_stats["peak"] = self.conn_stats["open"]
        t = self.client_write_timeout_s
        if t > 0:
            try:
                # struct timeval: two C longs on every platform this runs
                # on (linux). Failure just means no write fence — never a
                # refused connection.
                self.connection.setsockopt(
                    socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                    struct.pack("ll", int(t), int((t - int(t)) * 1e6)),
                )
            except (OSError, ValueError, struct.error):
                pass

    def finish(self) -> None:
        if getattr(self, "_admitted", True):
            if self.conn_stats is not None:
                with self.conn_lock:
                    self.conn_stats["open"] -= 1
            if self.conn_slots is not None:
                self.conn_slots.release()
        super().finish()

    def do_GET(self) -> None:  # noqa: N802 — stdlib API
        try:
            self._route_get()
        except (TimeoutError, BlockingIOError) as e:
            # SO_SNDTIMEO fired mid-response: the client stalled reading.
            # Count it, kill the (half-written) connection, swallow — the
            # stdlib would otherwise stack-trace a client-side fault.
            if self.write_timeouts is not None:
                with self.write_timeouts_lock:
                    self.write_timeouts["total"] += 1
            self.close_connection = True
            log.debug("client write timeout from %s: %s",
                      self.client_address[0], e)

    def _route_get(self) -> None:
        path, _, query = self.path.partition("?")
        exempt = path in _ADMISSION_EXEMPT_PATHS
        if not getattr(self, "_admitted", True):
            # Over the connection cap: this connection never got a slot.
            # Probe paths still answer (then close); everything else gets
            # the pre-rendered 429 — the storm pays, kubelet never does.
            self.close_connection = True
            if not exempt:
                self._count_admission_reject("connections")
                self.wfile.write(_CONN_REJECT_RESPONSE)
                return
        cap = self.max_requests_per_client
        client_key = None
        if cap > 0 and not exempt:
            client_key = self.client_address[0]
            with self.client_lock:
                cur = self.client_active.get(client_key, 0)
                if cur >= cap:
                    client_key = None
                    over = True
                else:
                    self.client_active[client_key] = cur + 1
                    over = False
            if over:
                self._count_admission_reject("client")
                self.close_connection = True
                self.wfile.write(_CLIENT_REJECT_RESPONSE)
                return
        try:
            self._dispatch_get(path, query)
        finally:
            if client_key is not None:
                with self.client_lock:
                    cur = self.client_active.get(client_key, 1) - 1
                    if cur <= 0:
                        self.client_active.pop(client_key, None)
                    else:
                        self.client_active[client_key] = cur

    def _count_admission_reject(self, cause: str) -> None:
        if self.scrape_rejects is not None:
            with self.scrape_rejects_lock:
                self.scrape_rejects[cause] = (
                    self.scrape_rejects.get(cause, 0) + 1
                )

    def _dispatch_get(self, path: str, query: str) -> None:
        if path == "/metrics":
            self._serve_metrics()
        elif path.startswith("/api/v1/"):
            self._serve_api(path, query)
        elif path.startswith("/debug/") and not debug_client_allowed(
            self.client_address[0], self.debug_addr
        ):
            # Loopback-only by default: stacks + effective config are
            # operator surface. --debug-addr 0.0.0.0 restores remote reads.
            self._serve_text(
                403, b"debug endpoints are loopback-only "
                     b"(start with --debug-addr 0.0.0.0 to expose)\n"
            )
        elif path == "/debug/vars" and self.debug_vars is not None:
            try:
                body = json.dumps(type(self).debug_vars(), indent=1).encode()
            except Exception as e:  # noqa: BLE001 — debug must not 500 loops
                body = json.dumps({"error": str(e)}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/debug/trace":
            # Poll traces as Chrome trace_event JSON (chrome://tracing /
            # Perfetto). Loopback-gated by the /debug/* guard above.
            # Lock discipline (satellite audit, all /debug/* + /api/v1
            # routes): every store-backed route copies references/values
            # under the store's lock and serializes OUTSIDE it —
            # TraceStore.last/scrapes here, _rows_for for /api/v1, the
            # debug_vars callable's stats() snapshots — so a slow client
            # draining a large JSON body can never hold a lock the poll
            # thread needs for its snapshot swap or history/trace append.
            self._serve_trace(query)
        elif path == "/debug/stacks":
            # The pprof-equivalent SURVEY §5 asks for, sized to this
            # process: a point-in-time dump of every thread's Python stack.
            # THE tool for the wedge /healthz detects — `curl
            # /debug/stacks` from the node shows exactly where a stuck
            # poll thread is blocked (a hung gRPC call, a dead NFS mount)
            # without kubectl exec, a debugger, or signals. Read-only,
            # allocation-light, served even while the poll thread is
            # wedged because handlers run on their own threads.
            self._serve_text(200, _format_stacks().encode())
        elif path == "/healthz":
            reason = None
            if self.live_fn is not None:
                try:
                    reason = type(self).live_fn()
                except Exception as e:  # noqa: BLE001 — a broken hook is itself unhealthy
                    reason = f"liveness hook failed: {e}"
            snap = self.store.current()
            if reason:
                self._serve_text(503, f"{reason}\n".encode())
            elif (
                self.health_max_age_s > 0
                and snap.timestamp > 0
                and time.time() - snap.timestamp > self.health_max_age_s
            ):
                age = time.time() - snap.timestamp
                self._serve_text(
                    503, f"poll stalled: last snapshot {age:.1f}s old\n".encode()
                )
            else:
                self._serve_text(200, b"ok\n")
        elif path == "/readyz":
            snap = self.store.current()
            ready = snap.timestamp > 0
            body: dict = {"ready": ready}
            warm = None
            if ready and self.warm_fn is not None:
                try:
                    warm = type(self).warm_fn()
                except Exception:  # noqa: BLE001 — warm detail must not break probes
                    warm = None
            if not ready:
                body["state"] = "starting"
                body["reason"] = "no poll completed yet"
            elif warm is not None:
                # Serving the restored pre-restart snapshot; no live poll
                # yet. Still 200 — data IS being served (that is the whole
                # point of warm start) — but distinctly labeled so rollouts
                # and operators can tell restored from live.
                body["state"] = "warm"
                body.update(warm)
            else:
                body["state"] = "ready"
            if self.ready_detail_fn is not None:
                try:
                    detail = type(self).ready_detail_fn() or {}
                    body.update(detail)
                    # Degraded = still serving, but an operator should
                    # look: a source breaker stuck open across probes, or
                    # the egress receiver unreachable past the same reopen
                    # threshold (batches buffering to disk, not flowing).
                    egress = detail.get("egress") or {}
                    if body["state"] == "ready" and (
                        detail.get("degraded_sources")
                        or egress.get("degraded")
                    ):
                        body["state"] = "degraded"
                except Exception:  # noqa: BLE001 — detail must not break probes
                    pass
            # JSON either way (kubelet only reads the status code; humans
            # and the RUNBOOK read the state + degraded-source detail).
            self._serve_json(200 if ready else 503, body)
        elif path == "/":
            self._serve_text(
                200,
                b"tpu-pod-exporter\n/metrics /healthz /readyz "
                b"/api/v1/series /api/v1/query_range /api/v1/window_stats\n",
            )
        else:
            self._serve_text(404, b"not found\n")

    # --------------------------------------------------------- trace export

    # /debug/trace response bound: `last` is clamped so the export stays a
    # bounded handful of MB no matter what a client asks for (each trace is
    # ~8 spans; scrape spans are capped by their own ring).
    TRACE_EXPORT_MAX_LAST = 200

    def _serve_trace(self, query: str) -> None:
        ts = self.trace
        if ts is None:
            self._serve_json(404, {
                "status": "error",
                "error": "tracing disabled (--trace off)",
            })
            return
        qs = parse_qs(query, keep_blank_values=True)
        try:
            last = int((qs.get("last") or ["20"])[-1])
        except ValueError:
            self._serve_json(400, {
                "status": "error", "error": "last must be an integer",
            })
            return
        if last < 1:
            self._serve_json(400, {
                "status": "error", "error": "last must be >= 1",
            })
            return
        last = min(last, self.TRACE_EXPORT_MAX_LAST)
        # Copy references under the store lock; build + serialize the (much
        # larger) JSON document outside it (see the /debug/* lock audit).
        traces = ts.last(last)
        scrapes = ts.scrapes(min(4 * last, 512))
        self._serve_json(200, to_chrome_trace(traces, scrapes))

    # ------------------------------------------------------- history queries

    def _serve_api(self, path: str, query: str) -> None:
        """JSON query surface: node-local history flight recorder, or the
        aggregator's federated fleet query plane when one is attached.
        Outside the scrape fences (the aggregator's missed-round fallback
        must not compete with the very scrape storm it is working around)
        but behind its own small concurrency cap — the same 2-permit fence
        and pre-rendered 429 + Retry-After on both exporter and aggregator."""
        sem = self.api_sem
        if sem is not None and not sem.acquire(timeout=self.api_queue_timeout_s):
            self.close_connection = True
            self.wfile.write(_API_REJECT_RESPONSE)
            return
        try:
            t0 = time.perf_counter()
            self._serve_api_inner(path, query)
            tstore = self.trace
            if tstore is not None:
                # Same cross-tier join as /metrics: an /api/v1 request
                # carrying a traceparent (the fleet query plane stamps one
                # per fan-out leg) records this node's serve span under the
                # remote query trace. Headerless queries record nothing.
                ctx = parse_traceparent(self.headers.get("traceparent") or "")
                if ctx is not None:
                    dur = time.perf_counter() - t0
                    tstore.record_scrape(
                        ctx[0], ctx[1], time.time() - dur, dur,
                        client=self.client_address[0],
                    )
        finally:
            if sem is not None:
                sem.release()

    @staticmethod
    def _parse_range_params(param) -> tuple[str, float, float, float, str]:
        """Validated query_range params — shared by the node-local and
        fleet routes so the 400 contract cannot drift between tiers."""
        metric = param("metric")
        if not metric:
            raise ValueError("missing required parameter: metric")
        end = float(param("end") or time.time())
        start = float(param("start") or (end - 300.0))
        step = float(param("step") or 0.0)
        agg = param("agg") or "last"
        if agg not in ("last", "min", "max", "mean"):
            raise ValueError("agg must be one of last/min/max/mean")
        # Finite + bounded before the store walks a grid: the grid
        # loop is O((end-start)/step) Python iterations, and this
        # endpoint is unauthenticated and exempt from the scrape
        # fences — start=0&step=1 (~1.7e9 points) or end=inf must
        # be a 400, not a pinned handler thread. Cap matches
        # Prometheus's 11k resolution limit.
        if not (math.isfinite(start) and math.isfinite(end)
                and math.isfinite(step)):
            raise ValueError("start/end/step must be finite")
        if step < 0:
            raise ValueError("step must be >= 0")
        if end < start:
            raise ValueError("end must be >= start")
        if step > 0 and (end - start) / step > 11000:
            raise ValueError(
                "query resolution too high: (end - start) / step "
                "must be <= 11000"
            )
        return metric, start, end, step, agg

    @staticmethod
    def _parse_window_params(param) -> tuple[str, float]:
        metric = param("metric")
        if not metric:
            raise ValueError("missing required parameter: metric")
        window = float(param("window") or 60.0)
        if window <= 0:
            raise ValueError("window must be > 0")
        return metric, window

    def _serve_api_inner(self, path: str, query: str) -> None:
        qs = parse_qs(query, keep_blank_values=True)

        def param(name: str, default: str | None = None) -> str | None:
            vals = qs.get(name)
            return vals[-1] if vals else default

        match = {
            k[len("match["):-1]: vs[-1]
            for k, vs in qs.items()
            if k.startswith("match[") and k.endswith("]") and len(k) > 7
        }
        if self.fleet is not None:
            self._serve_fleet_api(path, param, match)
            return
        if param("source"):
            # The node tier has no store: a ?source= knob that silently
            # does nothing would let an operator trust an answer that is
            # not what they asked for (same rule as the store-less
            # aggregator below).
            self._serve_json(400, {
                "status": "error",
                "error": "source= requires a store-backed root "
                         "(no fleet store attached on this tier)",
            })
            return
        h = self.history
        if h is None:
            self._serve_json(404, {
                "status": "error",
                "error": "history disabled (--history-retention-s 0)",
            })
            return
        try:
            if path == "/api/v1/series":
                self._serve_json(200, {"status": "ok", "source": "live",
                                       "data": h.series_list()})
                return
            if path == "/api/v1/query_range":
                metric, start, end, step, agg = self._parse_range_params(
                    param)
                result = h.query_range(metric, match, start, end, step,
                                       agg=agg)
                if not result:
                    self._serve_json(404, {
                        "status": "error",
                        "error": f"no samples for metric {metric!r} "
                                 f"matching {match!r} in range",
                    })
                    return
                self._serve_json(200, {
                    "status": "ok",
                    # Shared envelope contract across tiers: node-local
                    # answers are "live" by definition (the root's
                    # store-backed plane answers live|store|merged under
                    # the same key) — shapes must not drift between tiers.
                    "source": "live",
                    "data": {"resultType": "matrix", "result": result},
                })
                return
            if path == "/api/v1/window_stats":
                metric, window = self._parse_window_params(param)
                result = h.window_stats(metric, match, window_s=window)
                if not result:
                    self._serve_json(404, {
                        "status": "error",
                        "error": f"no samples for metric {metric!r} "
                                 f"matching {match!r} in window",
                    })
                    return
                self._serve_json(200, {"status": "ok", "source": "live",
                                       "data": {"result": result}})
                return
        except ValueError as e:
            self._serve_json(400, {"status": "error", "error": str(e)})
            return
        self._serve_json(404, {"status": "error", "error": "unknown API path"})

    def _serve_fleet_api(self, path: str, param, match: dict) -> None:
        """Federated /api/v1 on the aggregator: same routes, same param
        validation, but the answer is the fleet envelope — merged series
        plus per-target status — and a dead target is partial=true, never
        a non-200 round failure."""
        fleet = self.fleet
        # ?source=live|store|merged is meaningful only on a store-backed
        # plane (the root with --store-dir). Asking a store-less tier for
        # it must be an actionable 400, never a silently-ignored knob —
        # an operator reading "source":"live" back from a query they sent
        # ?source=store to would trust data that is not what they asked.
        source = param("source")
        kwargs: dict = {}
        if getattr(fleet, "handles_source", False):
            if source:
                kwargs["source"] = source
        elif source:
            self._serve_json(400, {
                "status": "error",
                "error": "source= requires a store-backed root "
                         "(no fleet store attached on this tier)",
            })
            return
        try:
            if path == "/api/v1/series":
                self._serve_json(200, fleet.series(**kwargs))
                return
            if path == "/api/v1/query_range":
                metric, start, end, step, agg = self._parse_range_params(
                    param)
                self._serve_json(200, fleet.query_range(
                    metric, match, start, end, step, agg=agg, **kwargs))
                return
            if path == "/api/v1/window_stats":
                metric, window = self._parse_window_params(param)
                self._serve_json(200, fleet.window_stats(
                    metric, match, window_s=window, **kwargs))
                return
        except ValueError as e:
            self._serve_json(400, {"status": "error", "error": str(e)})
            return
        self._serve_json(404, {"status": "error", "error": "unknown API path"})

    def _serve_json(self, code: int, obj) -> None:
        try:
            # allow_nan=False: bare NaN/Infinity literals are not JSON and
            # break every strict parser (jq, JSON.parse, encoding/json) —
            # exactly during the forensics these endpoints serve. Backends
            # CAN report NaN samples (format_value supports them), so the
            # fallback path maps non-finite values to null instead of 500ing.
            body = json.dumps(obj, allow_nan=False).encode()
        except ValueError:
            body = json.dumps(_json_sanitize(obj)).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _serve_metrics(self) -> None:
        bucket = self.scrape_bucket
        if bucket is not None and not bucket.take():
            self._reject_scrape("rate", tarpit=True)
            return
        sem = self.scrape_sem
        if sem is not None and not sem.acquire(timeout=self.scrape_queue_timeout_s):
            if bucket is not None:
                bucket.refund()  # this scrape was never served
            # No tarpit here: this path already queued for
            # scrape_queue_timeout_s, which throttles the client the same way.
            self._reject_scrape("concurrency")
            return
        try:
            t0 = time.perf_counter()
            self._serve_metrics_inner()
            dur = time.perf_counter() - t0
            observer = self.scrape_observer
            if observer is not None:
                observer(dur)
            tstore = self.trace
            if tstore is not None:
                # Cross-tier join: a scrape carrying a W3C traceparent
                # header (the aggregator stamps one per fan-out scrape)
                # records a node-side scrape span under the REMOTE trace
                # context, so the aggregator's round trace links to this
                # exporter's serve time. Headerless scrapes (Prometheus)
                # record nothing — no per-scrape ring churn.
                ctx = parse_traceparent(self.headers.get("traceparent") or "")
                if ctx is not None:
                    tstore.record_scrape(
                        ctx[0], ctx[1], time.time() - dur, dur,
                        client=self.client_address[0],
                    )
        finally:
            if sem is not None:
                sem.release()

    def _reject_scrape(self, cause: str, tarpit: bool = False) -> None:
        if tarpit and self.scrape_tarpit_s > 0:
            slots = self.tarpit_slots
            if slots is not None and slots.acquire(blocking=False):
                try:
                    time.sleep(self.scrape_tarpit_s)
                finally:
                    slots.release()
        if self.scrape_rejects is not None:
            # += on a dict value is a read-modify-write, NOT GIL-atomic;
            # under the very storm this counts, unlocked increments drop
            # (advisor r4). The reject path is already slow-path — a
            # lock costs nothing here.
            with self.scrape_rejects_lock:
                self.scrape_rejects[cause] += 1
        self.close_connection = True
        self.wfile.write(_REJECT_RESPONSE)

    def _serve_metrics_inner(self) -> None:
        snap = self.store.current()
        # Content negotiation: Prometheus ≥2.5 advertises OpenMetrics in
        # Accept; both formats are served from lazily-cached bytes, so the
        # negotiation costs a header parse, not a render.
        openmetrics = accepts_openmetrics(self.headers.get("Accept") or "")
        headers = [
            ("Content-Type", OPENMETRICS_CONTENT_TYPE if openmetrics else CONTENT_TYPE)
        ]
        if "gzip" in (self.headers.get("Accept-Encoding") or ""):
            body = (
                snap.encode_openmetrics_gzip() if openmetrics else snap.encode_gzip()
            )  # compressed once per snapshot, cached
            headers.append(("Content-Encoding", "gzip"))
        else:
            body = snap.encode_openmetrics() if openmetrics else snap.encode()
        self.send_response(200)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _serve_text(self, code: int, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args) -> None:  # quiet access logs
        log.debug("http: " + fmt, *args)


class _Server(ThreadingHTTPServer):
    # Python ≥3.11 sets SO_REUSEPORT on ThreadingHTTPServer, which lets a
    # second exporter instance bind the same port and silently steal scrapes.
    # Fail loudly on a port conflict instead.
    allow_reuse_port = False
    daemon_threads = True


class MetricsServer:
    """Owns the listener thread. Unlike the reference (hardcoded ``:8000``,
    ``log.Fatal`` on listener death, ``main.go:71``), port 0 is allowed for
    tests (ephemeral) and shutdown is clean."""

    def __init__(
        self,
        store: SnapshotStore,
        host: str = "0.0.0.0",
        port: int = 8000,
        debug_vars=None,
        health_max_age_s: float = 0.0,
        max_concurrent_scrapes: int = 4,
        scrape_queue_timeout_s: float = 0.25,
        max_scrapes_per_s: float = 0.0,
        scrape_tarpit_s: float = 0.1,
        scrape_observer=None,
        history=None,
        fleet=None,
        trace=None,
        debug_addr: str = "127.0.0.1",
        live_fn=None,
        ready_detail_fn=None,
        client_write_timeout_s: float = 10.0,
        warm_fn=None,
        max_open_connections: int = 0,
        max_requests_per_client: int = 0,
    ) -> None:
        # Every cause pre-seeded so the self-metric publishes a 0 series
        # per cause from poll 1 (stable surface). "connections"/"client"
        # are the admission-control causes (0 unless the caps are on).
        self.scrape_rejects = {
            "concurrency": 0, "rate": 0, "connections": 0, "client": 0,
        }
        self.write_timeouts = {"total": 0}
        # Open-connection accounting for the admission cap (peak is the
        # scrape-storm drill's bound witness).
        self.conn_stats = {"open": 0, "peak": 0}
        handler = type(
            "BoundHandler",
            (_Handler,),
            {
                "store": store,
                "debug_vars": staticmethod(debug_vars) if debug_vars else None,
                "history": history,
                "fleet": fleet,
                "trace": trace,
                "api_sem": (
                    threading.BoundedSemaphore(2)
                    if history is not None or fleet is not None
                    else None
                ),
                "debug_addr": debug_addr,
                "health_max_age_s": health_max_age_s,
                "live_fn": staticmethod(live_fn) if live_fn else None,
                "ready_detail_fn": (
                    staticmethod(ready_detail_fn) if ready_detail_fn else None
                ),
                "warm_fn": staticmethod(warm_fn) if warm_fn else None,
                "client_write_timeout_s": client_write_timeout_s,
                "write_timeouts": self.write_timeouts,
                "write_timeouts_lock": threading.Lock(),
                "scrape_sem": (
                    threading.BoundedSemaphore(max_concurrent_scrapes)
                    if max_concurrent_scrapes > 0
                    else None
                ),
                "scrape_queue_timeout_s": scrape_queue_timeout_s,
                # Burst 2× rate: absorbs scrape-alignment spikes (every
                # scraper firing in the same second) without letting a
                # sustained storm exceed ~rate serves/s.
                "scrape_bucket": (
                    _TokenBucket(max_scrapes_per_s, 2.0 * max_scrapes_per_s)
                    if max_scrapes_per_s > 0
                    else None
                ),
                "scrape_tarpit_s": scrape_tarpit_s,
                "tarpit_slots": threading.BoundedSemaphore(64),
                "scrape_rejects": self.scrape_rejects,
                "scrape_rejects_lock": threading.Lock(),
                "scrape_observer": (
                    staticmethod(scrape_observer) if scrape_observer else None
                ),
                "conn_slots": (
                    threading.BoundedSemaphore(max_open_connections)
                    if max_open_connections > 0
                    else None
                ),
                "conn_stats": self.conn_stats,
                "conn_lock": threading.Lock(),
                "max_requests_per_client": max_requests_per_client,
                "client_active": {},
                "client_lock": threading.Lock(),
            },
        )
        self._httpd = _Server((host, port), handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("already started")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="tpu-exporter-http", daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            # shutdown() blocks until serve_forever acknowledges — calling it
            # on a never-started server would deadlock, so gate on the thread.
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
