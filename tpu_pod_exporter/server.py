"""HTTP export surface — ``GET /metrics`` plus health endpoints.

Analog of the reference's promhttp goroutine (``main.go:67-72``), with the
scrape path made collection-free *and* render-free: the poll loop pre-encodes
the exposition text into the SnapshotStore, so a scrape is one reference
read and one kernel copy of cached bytes. This is what keeps p99 scrape
latency flat regardless of chip count (SURVEY.md §3.3, §7 "hard parts").

Serving architecture (ISSUE 13 rewrite): a single ``selectors``-based event
loop owns every socket — accept, request parse, and all response writes —
so a thousand idle keep-alive scrapers or trickle-reading clients cost file
descriptors, not threads. Work that may block (an uncached render, the
/api/v1 history/fleet queries, /debug serialization) is handed to a small
elastic worker pool whose results are written back by the loop; the common
scrape (body already cached on the snapshot) is served entirely inline.
The pre-event-loop defenses carry over as natural loop constructs:

- ``--client-write-timeout-s`` (SO_SNDTIMEO on the old thread-per-connection
  server) is now a per-connection write-progress deadline: a client that
  stalls mid-body is dropped and counted once no byte has moved for that
  long.
- The scrape-rate tarpit is a loop timer, not a sleeping thread.
- Admission control (connection cap, per-client cap) and the pre-rendered
  429 + Retry-After paths run inline on the loop before any work is spent.

Endpoints the reference lacks:
- ``/healthz`` — liveness (200 unless the poll loop is provably wedged).
- ``/readyz`` — readiness JSON (200 once data is being served, 503
  before) with a ``state`` field: ``starting`` / ``warm`` (serving a
  restored pre-restart snapshot, first live poll pending — see
  ``tpu_pod_exporter.persist``) / ``ready`` / ``degraded``.
- ``/api/v1/series`` / ``/api/v1/query_range`` / ``/api/v1/window_stats`` —
  JSON queries against the node-local history flight recorder
  (``tpu_pod_exporter.history``); served on the metrics port because the
  slice aggregator consumes them. Absent history (``--history-retention-s
  0``) answers 404 JSON. On the aggregator the same routes are served by
  the federated fleet query plane (``tpu_pod_exporter.fleet``) behind the
  same 2-permit fence.
- ``/debug/vars``, ``/debug/stacks`` and ``/debug/trace`` (poll traces as
  Chrome ``trace_event`` JSON) answer **loopback clients only** by default
  (thread stacks, config and traces are operator surface, not fleet
  surface); ``--debug-addr 0.0.0.0`` restores remote access.

Probe routes (/healthz, /readyz, /) answer inline on the loop so a scrape
storm or a wedged render can never starve kubelet; their optional hooks
(``live_fn``/``ready_detail_fn``/``warm_fn``) must therefore stay
non-blocking — every in-repo hook is a lock-free stats read.
"""

from __future__ import annotations

import heapq
import json
import logging
import math
import selectors
import socket
import threading
import time
from collections import deque
from itertools import islice
from typing import Any, Callable
from urllib.parse import parse_qs

from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.trace import parse_traceparent, to_chrome_trace


def _json_sanitize(obj: Any) -> Any:
    """Replace non-finite floats with None, recursively (slow path of
    JSON serving — only runs when a response actually contains one)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _json_sanitize(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_json_sanitize(v) for v in obj]
    return obj


def debug_client_allowed(client_ip: str, debug_addr: str) -> bool:
    """Whether a /debug/* request from ``client_ip`` may be served.

    Default policy is loopback-only: thread stacks and effective config are
    operator surface. ``--debug-addr 0.0.0.0`` (or ``*``) opens the debug
    endpoints to any client that can reach the metrics port. Loopback is
    always allowed regardless of the setting — the RUNBOOK's on-node curl
    must never lock itself out."""
    if client_ip.startswith("127.") or client_ip == "::1" or client_ip.startswith("::ffff:127."):
        return True
    return debug_addr in ("0.0.0.0", "*")


def _format_stacks() -> str:
    """Every live thread's Python stack, one block per thread.

    ``sys._current_frames`` is a documented-CPython atomic snapshot (the
    dict is built under the GIL); traceback formatting walks frame objects
    that stay valid while referenced, so a wedged thread's stack renders
    even though that thread never cooperates."""
    import sys
    import traceback

    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(frames.items()):
        t = by_id.get(ident)
        name = t.name if t else "?"
        daemon = " daemon" if (t and t.daemon) else ""
        out.append(f"--- thread {ident} ({name}){daemon} ---")
        out.extend(
            line.rstrip("\n")
            for line in traceback.format_stack(frame)
        )
        out.append("")
    return "\n".join(out) + "\n"


log = logging.getLogger("tpu_pod_exporter.server")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def prerender_429(body: bytes, content_type: str) -> bytes:
    """A 429 + Retry-After response as raw wire bytes, rendered once at
    import: under a storm the reject path runs per request, and formatting
    a status line plus four headers each time is measurable CPU that a
    reject must not spend. ``Connection: close`` both caps the connection's
    lifetime and tells well-behaved clients to back off the keep-alive
    connection. Shared by the /metrics scrape guard and the /api/v1 query
    fence (exporter and aggregator both — extracted, not duplicated)."""
    return (
        b"HTTP/1.1 429 Too Many Requests\r\n"
        b"Content-Type: " + content_type.encode("ascii") + b"\r\n"
        b"Retry-After: 1\r\n"
        b"Content-Length: " + str(len(body)).encode("ascii") + b"\r\n"
        b"Connection: close\r\n"
        b"\r\n" + body
    )


_REJECT_RESPONSE = prerender_429(
    b"too many concurrent scrapes\n", "text/plain; charset=utf-8"
)
# The /api/v1 fence's twin: still JSON (every consumer of these endpoints
# parses JSON, including during the very storm this rejects).
_API_REJECT_RESPONSE = prerender_429(
    b'{"status": "error", "error": "too many concurrent api queries"}',
    "application/json",
)
# Admission-control rejects (scrape-storm defense, tpu_pod_exporter
# ISSUE 10): a storm must cost rejected requests, never file descriptors
# or handler-thread pile-up. Same pre-rendered-bytes discipline as the
# scrape guard — the reject path runs per storm request.
_CONN_REJECT_RESPONSE = prerender_429(
    b"connection limit reached\n", "text/plain; charset=utf-8"
)
_CLIENT_REJECT_RESPONSE = prerender_429(
    b"per-client request limit reached\n", "text/plain; charset=utf-8"
)
# Stream-subscriber cap (the dashboard plane's admission half): a viewer
# storm past the cap pays a pre-rendered 429 and should retry against a
# read replica — tpu_stream_rejects_total{cause="cap"} counts it.
_STREAM_REJECT_RESPONSE = prerender_429(
    b'{"status": "error", "error": "stream subscriber cap reached; '
    b'retry against a read replica"}',
    "application/json",
)

# Probe paths exempt from admission control: a scrape storm must never be
# able to 429 kubelet's liveness/readiness probes into restarting the pod.
_ADMISSION_EXEMPT_PATHS = ("/healthz", "/readyz")

# Scatter-gather writes need sendmsg (Linux/BSD; absent on some
# platforms — the per-view send() path below is the fallback).
_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")


def accepts_openmetrics(accept: str) -> bool:
    """Whether content negotiation should pick OpenMetrics over plain text.

    A real (if minimal) q-value parse rather than a substring test
    (RFC 9110 §12.4.2 subset): OpenMetrics is served only when its q is
    positive AND at least the q the client gave ``text/plain`` (directly or
    via a wildcard) — a client sending ``text/plain;q=1,
    application/openmetrics-text;q=0.1`` deliberately prefers text and must
    get it. Malformed q-values count as q=1; unlisted types inherit the
    wildcard q, if any.
    """
    qs: dict[str, float] = {}
    for entry in accept.split(","):
        parts = entry.split(";")
        mtype = parts[0].strip().lower()
        if not mtype:
            continue
        q = 1.0
        for param in parts[1:]:
            name, _, value = param.partition("=")
            if name.strip().lower() == "q":
                try:
                    q = float(value.strip())
                except ValueError:
                    q = 1.0
        qs[mtype] = max(q, qs.get(mtype, 0.0))
    wildcard = max(qs.get("*/*", 0.0), qs.get("text/*", 0.0))
    q_om = qs.get("application/openmetrics-text", 0.0)
    q_text = qs.get("text/plain", wildcard)
    return q_om > 0.0 and q_om >= q_text


class _TokenBucket:
    """Scrape-rate cap for /metrics. The concurrency fence bounds how
    many big bodies are in flight, but not how many per second — and a
    sequential storm of full-body scrapes is pure kernel-copy cost
    (~0.4 ms CPU per ~950 KB body at 256 chips; measured, bench.py) that
    no amount of server cleverness removes. Above the bucket rate the
    exporter answers with the pre-rendered 429 instead: monitoring losing
    a scrape beats monitoring stealing the TPU host's cores. The default
    rate (config.max_scrapes_per_s=100) is ~20× any sane setup — a few
    Prometheus replicas plus an aggregator at 1 Hz."""

    __slots__ = ("rate", "burst", "tokens", "last", "lock")

    def __init__(self, rate: float, burst: float) -> None:
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.last = time.monotonic()
        self.lock = threading.Lock()

    def take(self) -> bool:
        with self.lock:
            # monotonic() read INSIDE the lock: a stale `now` against a
            # newer `last` written by another thread would apply a negative
            # refill, silently draining tokens (code-review r5).
            now = time.monotonic()
            self.tokens = min(
                self.burst, self.tokens + (now - self.last) * self.rate
            )
            self.last = now
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            return False

    def refund(self) -> None:
        """Return a taken token (the request was refused downstream anyway —
        e.g. by the concurrency guard — so it must not count against the
        rate: a stall would otherwise drain the bucket and 429 well-behaved
        scrapers after it clears)."""
        with self.lock:
            self.tokens = min(self.burst, self.tokens + 1.0)


# --------------------------------------------------------------- HTTP pieces

_MAX_HEADER_BYTES = 65536
# GET requests carry no meaningful body here; anything advertised beyond
# this is refused rather than buffered (the loop must never hold unbounded
# client bytes).
_MAX_BODY_DISCARD = 1 << 20

_STATUS_LINES = {
    200: b"HTTP/1.1 200 OK\r\n",
    400: b"HTTP/1.1 400 Bad Request\r\n",
    403: b"HTTP/1.1 403 Forbidden\r\n",
    404: b"HTTP/1.1 404 Not Found\r\n",
    413: b"HTTP/1.1 413 Content Too Large\r\n",
    429: b"HTTP/1.1 429 Too Many Requests\r\n",
    431: b"HTTP/1.1 431 Request Header Fields Too Large\r\n",
    500: b"HTTP/1.1 500 Internal Server Error\r\n",
    501: b"HTTP/1.1 501 Not Implemented\r\n",
    503: b"HTTP/1.1 503 Service Unavailable\r\n",
}


class _Request:
    """One parsed request head (the loop never buffers request bodies)."""

    __slots__ = ("method", "target", "headers", "keep_alive")

    def __init__(self, method: str, target: str, headers: dict[str, str],
                 keep_alive: bool) -> None:
        self.method = method
        self.target = target
        self.headers = headers
        self.keep_alive = keep_alive


def _parse_head(head: bytes) -> _Request | None:
    """Parse request line + headers. None = malformed (caller 400s)."""
    lines = head.split(b"\r\n")
    parts = lines[0].split()
    if len(parts) != 3:
        return None
    method_b, target_b, version = parts
    if not version.startswith(b"HTTP/1."):
        return None
    headers: dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        key, sep, value = line.partition(b":")
        if not sep:
            return None
        try:
            headers[key.strip().decode("latin-1").lower()] = (
                value.strip().decode("latin-1")
            )
        except UnicodeDecodeError:  # pragma: no cover - latin-1 total
            return None
    keep_alive = version == b"HTTP/1.1"
    conn_tokens = headers.get("connection", "").lower()
    if "close" in conn_tokens:
        keep_alive = False
    elif "keep-alive" in conn_tokens:
        keep_alive = True
    return _Request(method_b.decode("latin-1"), target_b.decode("latin-1"),
                    headers, keep_alive)


class _Response:
    """A response the loop serializes and writes. ``observe`` marks a
    served scrape (duration observed + trace span recorded at flush)."""

    __slots__ = ("status", "headers", "body", "close", "observe",
                 "trace_ctx")

    def __init__(self, status: int, headers: list[tuple[str, str]],
                 body: bytes, close: bool = False, observe: bool = False,
                 trace_ctx: tuple[str, str] | None = None) -> None:
        self.status = status
        self.headers = headers
        self.body = body
        self.close = close
        self.observe = observe
        self.trace_ctx = trace_ctx


def _text_response(code: int, body: bytes, close: bool = False) -> _Response:
    return _Response(
        code, [("Content-Type", "text/plain; charset=utf-8")], body,
        close=close,
    )


def _json_response(code: int, obj: Any) -> _Response:
    try:
        # allow_nan=False: bare NaN/Infinity literals are not JSON and
        # break every strict parser (jq, JSON.parse, encoding/json) —
        # exactly during the forensics these endpoints serve. Backends
        # CAN report NaN samples (format_value supports them), so the
        # fallback path maps non-finite values to null instead of 500ing.
        body = json.dumps(obj, allow_nan=False).encode()  # lint: disable=loop-blocking(probe payloads only: readyz/healthz/debug docs are a few hundred bytes, microseconds to encode — the metrics exposition never comes through here)
    except ValueError:
        body = json.dumps(_json_sanitize(obj)).encode()  # lint: disable=loop-blocking(same probe-sized payload as the line above, non-finite fallback)
    return _Response(code, [("Content-Type", "application/json")], body)


class _Conn:
    """Per-connection loop state: read buffer, pending write queue, and the
    bookkeeping the admission/observation paths need at request finish."""

    __slots__ = (
        "sock", "fd", "ip", "rbuf", "wbufs", "admitted", "keep_alive",
        "busy", "close_after", "closed", "client_key", "req_t0",
        "observe_scrape", "trace_ctx", "need_discard", "events",
        "response_pending", "last_write_progress", "write_deadline_armed",
        "streaming", "stream_sub",
    )

    def __init__(self, sock: socket.socket, ip: str) -> None:
        self.sock = sock
        self.fd = sock.fileno()
        self.ip = ip
        self.rbuf = bytearray()
        self.wbufs: deque[memoryview] = deque()
        self.admitted = True
        self.keep_alive = True
        self.busy = False            # a request is in flight
        self.close_after = False
        self.closed = False
        self.client_key: str | None = None
        self.req_t0 = 0.0
        self.observe_scrape = False
        self.trace_ctx: tuple[str, str] | None = None
        self.need_discard = 0        # request-body bytes left to drop
        self.events = 0              # current selector interest mask
        self.response_pending = False
        self.last_write_progress = 0.0
        self.write_deadline_armed = False
        # Dashboard stream subscription riding this connection (SSE,
        # close-delimited): the loop pushes frames instead of finishing a
        # request, and closing detaches the hub subscriber.
        self.streaming = False
        self.stream_sub: Any = None


class _WorkerPool:
    """Elastic thread pool for request work that may block (uncached
    renders, history/fleet queries, /debug serialization). Threads are
    spawned on demand up to ``max_workers`` and expire after idling — the
    steady state of a healthy exporter is zero to one worker, because the
    hot path never leaves the loop."""

    _IDLE_EXPIRE_S = 10.0

    def __init__(self, max_workers: int,
                 idle_expire_s: float | None = None) -> None:
        self._max = max(1, max_workers)
        self._idle_expire = (idle_expire_s if idle_expire_s is not None
                             else self._IDLE_EXPIRE_S)
        self._tasks: deque[Callable[[], None]] = deque()
        self._lock = threading.Lock()
        # LIFO stack of idle workers' wake events. Submit wakes the MOST
        # recently parked worker: work concentrates on few hot threads and
        # the rest genuinely idle until the reap takes them. (The old
        # Condition.notify() rotated wakeups round-robin through every
        # waiter, which both refreshed each one's idle clock AND spread a
        # trickle of tasks across the whole storm-grown pool — BENCH_r06's
        # slow_clients threads_after never returned to baseline.)
        self._waiters: list[threading.Event] = []
        self._threads = 0
        self._seq = 0
        self._stopping = False

    @property
    def threads(self) -> int:
        return self._threads

    @property
    def queued(self) -> int:
        return len(self._tasks)

    def submit(self, fn: Callable[[], None]) -> None:
        spawn = False
        wake: threading.Event | None = None
        with self._lock:
            if self._stopping:
                return
            self._tasks.append(fn)
            if self._waiters:
                wake = self._waiters.pop()  # LIFO: hottest worker first
            elif self._threads < self._max:
                self._threads += 1
                self._seq += 1
                spawn = True
                seq = self._seq
        if wake is not None:
            wake.set()
        if spawn:
            threading.Thread(
                target=self._run,
                name=f"tpu-exporter-http-worker-{seq}",
                daemon=True,
            ).start()

    def _run(self) -> None:
        ev = threading.Event()
        last_active = time.monotonic()
        while True:
            fn: Callable[[], None] | None = None
            with self._lock:
                if self._tasks:
                    fn = self._tasks.popleft()
                elif self._stopping:
                    self._threads -= 1
                    return
                else:
                    idle_for = time.monotonic() - last_active
                    if idle_for >= self._idle_expire:
                        # This worker hasn't been needed for a full grace
                        # period: the pool shrinks back toward the traffic
                        # it actually has (steady state 0-1 workers).
                        self._threads -= 1
                        return
                    ev.clear()
                    self._waiters.append(ev)
            if fn is None:
                ev.wait(timeout=self._idle_expire
                        - (time.monotonic() - last_active))
                with self._lock:
                    try:
                        self._waiters.remove(ev)
                    except ValueError:
                        pass  # a submit popped us — a task is waiting
                continue
            try:
                fn()
            except Exception:  # noqa: BLE001 — a task must not kill the pool
                log.exception("http worker task failed")
            last_active = time.monotonic()

    def shutdown(self) -> None:
        with self._lock:
            self._stopping = True
            waiters = list(self._waiters)
            self._waiters.clear()
        for ev in waiters:
            ev.set()


class _HandlerState:
    """Every knob and shared counter the request paths read.

    Exposed to tests as ``server._httpd.RequestHandlerClass`` — the
    pre-event-loop server bound these as class attributes on a
    per-instance handler subclass, and the admission/fence tests poke
    them (``client_active``, ``client_lock``, ``api_sem``) directly."""

    def __init__(self) -> None:
        self.store: SnapshotStore = None  # type: ignore[assignment]
        self.debug_vars: Callable[[], dict] | None = None
        self.history: Any = None
        self.fleet: Any = None
        self.trace: Any = None
        self.api_sem: threading.BoundedSemaphore | None = None
        self.api_queue_timeout_s: float = 0.25
        self.debug_addr: str = "127.0.0.1"
        self.health_max_age_s: float = 0.0
        self.live_fn: Callable[[], str | None] | None = None
        self.ready_detail_fn: Callable[[], dict] | None = None
        self.warm_fn: Callable[[], dict | None] | None = None
        self.scrape_sem: threading.BoundedSemaphore | None = None
        self.scrape_queue_timeout_s: float = 0.25
        self.scrape_bucket: _TokenBucket | None = None
        self.scrape_tarpit_s: float = 0.1
        self.scrape_rejects: dict[str, int] = {}
        self.scrape_rejects_lock = threading.Lock()
        self.scrape_observer: Callable[[float], None] | None = None
        self.max_open_connections: int = 0
        self.conn_stats: dict[str, int] = {}
        self.conn_lock = threading.Lock()
        self.max_requests_per_client: int = 0
        self.client_active: dict[str, int] = {}
        self.client_lock = threading.Lock()
        self.client_write_timeout_s: float = 10.0
        self.write_timeouts: dict[str, int] = {}
        self.write_timeouts_lock = threading.Lock()
        # Streaming dashboard plane (tpu_pod_exporter.stream.StreamHub);
        # None = /api/v1/stream answers 404 on this tier.
        self.stream: Any = None
        self.stream_max_buffer_bytes: int = 2 << 20


class _CompatHandle:
    """Legacy introspection shim: tests (and only tests) reach the shared
    handler state through ``server._httpd.RequestHandlerClass``, the path
    the stdlib-server implementation exposed."""

    def __init__(self, state: _HandlerState) -> None:
        self.RequestHandlerClass = state


# Loop-dispatch probe seam. analysis/witness.py's LoopWitness sets this
# under TPE_LOOP_WITNESS=1 to time every callback the loop runs inline
# (the runtime half of the loop-blocking contract; the static half never
# imports this module). None — the default — keeps dispatch at one global
# read plus a branch.
LOOP_PROBE: "Callable[[str, Callable[..., None], float], None] | None" = None


class _EventLoopServer:
    """The selector loop plus request routing. Single-threaded: every
    socket operation happens on the loop thread; workers communicate back
    exclusively through :meth:`call_soon` + the wake pipe."""

    def __init__(self, host: str, port: int, state: _HandlerState,
                 max_workers: int,
                 worker_idle_expire_s: float = 10.0) -> None:
        self.state = state
        self._sel = selectors.DefaultSelector()
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # SO_REUSEADDR (TIME_WAIT rebinds) but never SO_REUSEPORT: a second
        # exporter instance binding the same live port must fail loudly,
        # not silently steal scrapes.
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            lsock.bind((host, port))
        except OSError:
            lsock.close()
            raise
        lsock.listen(128)
        lsock.setblocking(False)
        self._lsock = lsock
        # Cached: the port must stay readable after close() (stop() then
        # a late .port read must not raise on the dead socket).
        self._port = int(lsock.getsockname()[1])
        self._conns: dict[int, _Conn] = {}
        self._timers: list[tuple[float, int, Callable[[], None]]] = []
        self._timer_seq = 0
        self._pending: deque[Callable[[], None]] = deque()
        self._pending_lock = threading.Lock()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._stopping = False
        self.pool = _WorkerPool(max_workers, worker_idle_expire_s)
        self.served = {"inline": 0, "worker": 0}
        self._sel.register(lsock, selectors.EVENT_READ, None)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)

    @property
    def port(self) -> int:
        return self._port

    # ------------------------------------------------------------ loop core

    def run(self) -> None:
        try:
            while not self._stopping:
                timeout: float | None = None
                if self._timers:
                    timeout = max(0.0, self._timers[0][0] - time.monotonic())
                for key, mask in self._sel.select(timeout):
                    if key.fileobj is self._lsock:
                        self._invoke("accept", self._accept)
                    elif key.fileobj is self._wake_r:
                        self._invoke("wake", self._drain_wake)
                    else:
                        conn: _Conn = key.data
                        if conn.closed:
                            continue
                        if mask & selectors.EVENT_WRITE:
                            self._invoke("write", self._try_write, conn)
                        if mask & selectors.EVENT_READ and not conn.closed:
                            self._invoke("read", self._on_readable, conn)
                self._run_pending()
                self._run_timers()
        finally:
            for conn in list(self._conns.values()):
                self._close_conn(conn)
            self.pool.shutdown()
            try:
                self._sel.unregister(self._lsock)
                self._sel.unregister(self._wake_r)
            except (KeyError, ValueError):
                pass
            self._sel.close()
            self._wake_r.close()
            self._wake_w.close()
            self._lsock.close()

    def wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass  # a wake is already pending (or the loop is gone)

    def _invoke(self, kind: str, fn: Callable[..., None],
                *args: Any) -> None:
        """Loop-dispatch choke point: every callback the loop runs inline
        passes through here, so the loop-stall witness can time it and the
        static analyzer can tag the ``fn`` argument with the loop role
        (CALLBACK_ROLES in analysis/concurrency.py)."""
        probe = LOOP_PROBE
        if probe is None:
            fn(*args)
            return
        t0 = time.monotonic()
        try:
            fn(*args)
        finally:
            probe(kind, fn, time.monotonic() - t0)

    def call_soon(self, fn: Callable[[], None]) -> None:
        """Thread-safe: schedule ``fn`` on the loop thread."""
        with self._pending_lock:
            self._pending.append(fn)
        self.wake()

    def call_later(self, delay_s: float, fn: Callable[[], None]) -> None:
        """Loop-thread only: run ``fn`` after ``delay_s``."""
        self._timer_seq += 1
        heapq.heappush(
            self._timers, (time.monotonic() + delay_s, self._timer_seq, fn)
        )

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _run_pending(self) -> None:
        while True:
            with self._pending_lock:
                if not self._pending:
                    return
                fn = self._pending.popleft()
            try:
                self._invoke("pending", fn)
            except Exception:  # noqa: BLE001 — one callback must not kill the loop
                log.exception("loop callback failed")

    def _run_timers(self) -> None:
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            _, _, fn = heapq.heappop(self._timers)
            try:
                self._invoke("timer", fn)
            except Exception:  # noqa: BLE001 — one timer must not kill the loop
                log.exception("loop timer failed")

    # ----------------------------------------------------- connection state

    def _set_events(self, conn: _Conn, events: int) -> None:
        if conn.closed or events == conn.events:
            return
        if events == 0:
            self._sel.unregister(conn.sock)
        elif conn.events == 0:
            self._sel.register(conn.sock, events, conn)
        else:
            self._sel.modify(conn.sock, events, conn)
        conn.events = events

    def _accept(self) -> None:
        st = self.state
        for _ in range(128):
            try:
                sock, addr = self._lsock.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, addr[0])
            cap = st.max_open_connections
            with st.conn_lock:
                if cap > 0 and st.conn_stats["open"] >= cap:
                    # Over the cap: the connection still gets ONE request
                    # handled — a probe answer, or the pre-rendered 429 —
                    # then closes. It is never counted as open.
                    conn.admitted = False
                else:
                    st.conn_stats["open"] += 1
                    if st.conn_stats["open"] > st.conn_stats["peak"]:
                        st.conn_stats["peak"] = st.conn_stats["open"]
            self._conns[conn.fd] = conn
            self._set_events(conn, selectors.EVENT_READ)

    def _close_conn(self, conn: _Conn) -> None:
        if conn.closed:
            return
        try:
            self._set_events(conn, 0)
        except (KeyError, ValueError, OSError):
            pass
        conn.closed = True
        sub = conn.stream_sub
        if sub is not None:
            conn.stream_sub = None
            hub = self.state.stream
            if hub is not None:
                try:
                    hub.detach(sub)
                except Exception:  # noqa: BLE001 — teardown must not kill the loop
                    log.exception("stream detach failed")
        self._release_client_slot(conn)
        self._conns.pop(conn.fd, None)
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn.admitted:
            with self.state.conn_lock:
                self.state.conn_stats["open"] -= 1

    def _release_client_slot(self, conn: _Conn) -> None:
        key = conn.client_key
        if key is None:
            return
        conn.client_key = None
        st = self.state
        with st.client_lock:
            cur = st.client_active.get(key, 1) - 1
            if cur <= 0:
                st.client_active.pop(key, None)
            else:
                st.client_active[key] = cur

    # --------------------------------------------------------------- reads

    def _on_readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(65536)
        except BlockingIOError:
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)
            return
        if conn.streaming:
            # A subscriber never pipelines; anything it sends after the
            # subscribe request is discarded. The read interest stays on
            # solely so a client close is noticed promptly (the recv above
            # returning b"" is how a dropped viewer frees its slot).
            return
        conn.rbuf += data
        if conn.busy:
            if len(conn.rbuf) > 4 * _MAX_HEADER_BYTES:
                # Pipelining flood while a response is in flight: stop
                # reading until the current request finishes.
                self._set_events(conn, conn.events & ~selectors.EVENT_READ)
            return
        self._process_rbuf(conn)

    def _process_rbuf(self, conn: _Conn) -> None:
        if conn.need_discard:
            take = min(conn.need_discard, len(conn.rbuf))
            del conn.rbuf[:take]
            conn.need_discard -= take
            if conn.need_discard:
                return
        idx = conn.rbuf.find(b"\r\n\r\n")
        if idx < 0:
            if len(conn.rbuf) > _MAX_HEADER_BYTES:
                self._respond(conn, _text_response(
                    431, b"request header too large\n", close=True))
            return
        head = bytes(conn.rbuf[:idx])
        del conn.rbuf[:idx + 4]
        req = _parse_head(head)
        if req is None:
            self._respond(conn, _text_response(
                400, b"malformed request\n", close=True))
            return
        if "transfer-encoding" in req.headers:
            self._respond(conn, _text_response(
                400, b"request bodies are not accepted\n", close=True))
            return
        try:
            body_len = int(req.headers.get("content-length", "0") or "0")
        except ValueError:
            self._respond(conn, _text_response(
                400, b"bad content-length\n", close=True))
            return
        if body_len > _MAX_BODY_DISCARD:
            self._respond(conn, _text_response(
                413, b"request body too large\n", close=True))
            return
        if body_len > 0:
            conn.need_discard = body_len
            take = min(conn.need_discard, len(conn.rbuf))
            del conn.rbuf[:take]
            conn.need_discard -= take
        conn.busy = True
        conn.req_t0 = time.monotonic()
        conn.keep_alive = req.keep_alive
        if req.method != "GET":
            self._respond(conn, _text_response(
                501, b"only GET is supported\n", close=True))
            return
        try:
            self._handle_request(conn, req)
        except Exception:  # noqa: BLE001 — routing bug must not kill the loop
            log.exception("request handling failed")
            if not conn.closed and not conn.response_pending:
                self._respond(conn, _text_response(
                    500, b"internal error\n", close=True))

    # -------------------------------------------------------------- writes

    def _respond(self, conn: _Conn, resp: _Response) -> None:
        if conn.closed:
            return
        body = resp.body
        close = resp.close or not conn.keep_alive
        head = [_STATUS_LINES[resp.status]]
        for k, v in resp.headers:
            head.append(f"{k}: {v}\r\n".encode("latin-1"))
        head.append(b"Content-Length: " + str(len(body)).encode("ascii")
                    + b"\r\n")
        if close:
            head.append(b"Connection: close\r\n")
        head.append(b"\r\n")
        conn.wbufs.append(memoryview(b"".join(head)))
        if body:
            conn.wbufs.append(memoryview(body))
        conn.close_after = close
        conn.observe_scrape = resp.observe
        conn.trace_ctx = resp.trace_ctx
        conn.response_pending = True
        conn.last_write_progress = time.monotonic()
        if close:
            self._stop_reading(conn)
        self._try_write(conn)

    def _send_raw(self, conn: _Conn, raw: bytes) -> None:
        """Queue pre-rendered wire bytes (the 429 family) and close after."""
        if conn.closed:
            return
        conn.wbufs.append(memoryview(raw))
        conn.close_after = True
        conn.observe_scrape = False
        conn.trace_ctx = None
        conn.response_pending = True
        conn.last_write_progress = time.monotonic()
        self._stop_reading(conn)
        self._try_write(conn)

    def _stop_reading(self, conn: _Conn) -> None:
        """This connection will close once its response flushes: stop
        reading and drop any buffered client bytes. Without this a client
        streaming header-less bytes (no terminator, never reading) would
        grow ``rbuf`` at its send rate and queue one 431 per recv — an
        unauthenticated memory lever — since the pipelining read-throttle
        only engages while ``busy`` is set."""
        conn.rbuf.clear()
        conn.need_discard = 0
        self._set_events(conn, conn.events & ~selectors.EVENT_READ)

    # Scatter-gather width for sendmsg: response head + body leave in one
    # syscall (the identity keep-alive fast path — a ~1 MB cached body was
    # previously one send per queued view, and the head/body split cost a
    # second syscall per request); bounded well under IOV_MAX.
    _SENDMSG_MAX_VIEWS = 16

    def _try_write(self, conn: _Conn) -> None:
        sock = conn.sock
        while conn.wbufs:
            try:
                if len(conn.wbufs) > 1 and _HAS_SENDMSG:
                    # Zero-copy gather of the queued memoryviews — no
                    # join, no intermediate bytes; the kernel walks the
                    # iovec straight out of the cached body. islice, not
                    # a full-deque copy: a backlogged stream subscriber
                    # can hold hundreds of queued frame views, and this
                    # runs on the loop's hot path.
                    bufs = list(islice(conn.wbufs,
                                       self._SENDMSG_MAX_VIEWS))
                    n = sock.sendmsg(bufs)
                else:
                    n = sock.send(conn.wbufs[0])
            except BlockingIOError:
                self._set_events(conn, conn.events | selectors.EVENT_WRITE)
                self._arm_write_deadline(conn)
                return
            except OSError:
                self._close_conn(conn)
                return
            if not n:
                break
            conn.last_write_progress = time.monotonic()
            # Advance the queue by n bytes (sendmsg may span views).
            while n:
                mv = conn.wbufs[0]
                if n < len(mv):
                    conn.wbufs[0] = mv[n:]
                    n = 0
                else:
                    n -= len(mv)
                    conn.wbufs.popleft()
        if conn.events & selectors.EVENT_WRITE:
            self._set_events(conn, conn.events & ~selectors.EVENT_WRITE)
        if conn.response_pending:
            conn.response_pending = False
            self._finish_request(conn)

    def _arm_write_deadline(self, conn: _Conn) -> None:
        """Slow-client write defense: the old server set SO_SNDTIMEO so a
        blocked sendall() raised after --client-write-timeout-s; on the
        loop the same contract is a progress deadline — a connection whose
        pending bytes move nothing for that long is dropped and counted
        (tpu_exporter_client_write_timeouts_total)."""
        t = self.state.client_write_timeout_s
        if t <= 0 or conn.write_deadline_armed:
            return
        conn.write_deadline_armed = True

        def check() -> None:
            if conn.closed or not conn.wbufs:
                conn.write_deadline_armed = False
                return
            idle = time.monotonic() - conn.last_write_progress
            if idle >= t:
                st = self.state
                with st.write_timeouts_lock:
                    st.write_timeouts["total"] += 1
                log.debug("client write timeout from %s", conn.ip)
                self._close_conn(conn)
            else:
                self.call_later(t - idle, check)

        self.call_later(t, check)

    def _finish_request(self, conn: _Conn) -> None:
        if conn.observe_scrape:
            dur = time.monotonic() - conn.req_t0
            observer = self.state.scrape_observer
            if observer is not None:
                try:
                    observer(dur)
                except Exception:  # noqa: BLE001 — observer must not kill the loop
                    log.exception("scrape observer failed")
            ctx = conn.trace_ctx
            tstore = self.state.trace
            if ctx is not None and tstore is not None:
                # Cross-tier join: a scrape carrying a W3C traceparent
                # header (the aggregator stamps one per fan-out scrape)
                # records a node-side scrape span under the REMOTE trace
                # context, so the aggregator's round trace links to this
                # exporter's serve time. Headerless scrapes (Prometheus)
                # record nothing — no per-scrape ring churn.
                tstore.record_scrape(
                    ctx[0], ctx[1], time.time() - dur, dur, client=conn.ip,
                )
            conn.observe_scrape = False
            conn.trace_ctx = None
        self._release_client_slot(conn)
        conn.busy = False
        if conn.close_after:
            self._close_conn(conn)
            return
        self._set_events(conn, conn.events | selectors.EVENT_READ)
        if conn.rbuf or conn.need_discard:
            # Deferred (not recursed): a client that pipelined hundreds of
            # requests into one buffer must cost loop iterations, not
            # Python stack depth.
            self.call_soon(lambda: self._resume_buffered(conn))

    def _resume_buffered(self, conn: _Conn) -> None:
        if not conn.closed and not conn.busy:
            self._process_rbuf(conn)

    # ----------------------------------------------------------- streaming

    _STREAM_HEAD = (
        b"HTTP/1.1 200 OK\r\n"
        b"Content-Type: text/event-stream\r\n"
        b"Cache-Control: no-cache\r\n"
        b"X-Accel-Buffering: no\r\n"
        b"Connection: close\r\n"
        b"\r\n"
    )

    def _begin_stream(self, conn: _Conn, sub: Any, payload: bytes) -> None:
        """Loop-thread: turn this connection into a live SSE subscription.
        ``payload`` is the hub-built snapshot (plus any frames that landed
        during serialization); everything after arrives via
        :meth:`_stream_write` posts from round/tick threads."""
        hub = self.state.stream
        if conn.closed:
            # The viewer dropped while the worker was subscribing.
            if hub is not None:
                hub.detach(sub)
            return
        conn.streaming = True
        conn.stream_sub = sub
        conn.keep_alive = False
        conn.close_after = False    # pushes keep coming until detach
        conn.response_pending = False
        # The subscription is capped by the hub, not the per-client
        # request cap — a dashboard opening 8 panels from one IP is the
        # normal case, not an attack the request cap should stop.
        self._release_client_slot(conn)
        conn.wbufs.append(memoryview(self._STREAM_HEAD))
        conn.wbufs.append(memoryview(payload))
        # Transport is ready: enable round pushes AND atomically collect
        # any frame committed since the snapshot was built (the ring
        # catch-up) — writer() posts land on this loop strictly after
        # this callback, so a frame is never dropped into the
        # pre-streaming window and never duplicated.
        if hub is not None:
            try:
                catchup = hub.activate(sub)
            except Exception:  # noqa: BLE001 — a hub bug must not kill the loop
                log.exception("stream activate failed")
                catchup = b""
            if catchup:
                conn.wbufs.append(memoryview(catchup))
        conn.last_write_progress = time.monotonic()
        self._try_write(conn)

    def _stream_write(self, conn: _Conn, payload: bytes) -> None:
        """Loop-thread: push one frame to a subscriber. A viewer that
        stopped reading accumulates pending views; past the buffer cap it
        is shed immediately (counted) rather than waiting out the write
        deadline — its memory cost is bounded either way."""
        if conn.closed or not conn.streaming or conn.close_after:
            # close_after = the stream is already ending (shed flush in
            # flight): later frames are dropped, not re-shed/re-counted.
            return
        pending = sum(len(m) for m in conn.wbufs)
        if pending + len(payload) > self.state.stream_max_buffer_bytes:
            hub = self.state.stream
            if hub is not None:
                hub.count_slow_shed()
            log.debug("stream subscriber %s shed: %d pending bytes",
                      conn.ip, pending)
            self._close_conn(conn)
            return
        conn.wbufs.append(memoryview(payload))
        self._try_write(conn)

    def _end_stream(self, conn: _Conn) -> None:
        """Server-initiated stream end (shed, hub close): FLUSH-then-
        close — the final labeled ``shed`` frame already queued must
        reach the viewer (the RUNBOOK contract); a viewer too stalled to
        take it is bounded by the write-progress deadline as ever."""
        if conn.closed:
            return
        if not conn.streaming:
            self._close_conn(conn)
            return
        conn.close_after = True
        if not conn.wbufs:
            self._close_conn(conn)
            return
        conn.response_pending = True  # drain → _finish_request → close
        self._try_write(conn)

    def _task_stream(self, conn: _Conn, query: str) -> None:
        """Worker task for GET /api/v1/stream: validate the query shape,
        then either register an SSE subscription (snapshot now, deltas
        pushed per round) or serve/park one long-poll turn."""
        from tpu_pod_exporter.stream import HubFull, QueryShape

        st = self.state
        hub = st.stream
        if hub is None:
            self.post_response(conn, _json_response(404, {
                "status": "error",
                "error": "streaming not enabled on this tier "
                         "(no stream hub attached; poll /api/v1 instead)",
            }))
            return
        qs = parse_qs(query, keep_blank_values=True)

        def param(name: str) -> str | None:
            vals = qs.get(name)
            return vals[-1] if vals else None

        match = {
            k[len("match["):-1]: vs[-1]
            for k, vs in qs.items()
            if k.startswith("match[") and k.endswith("]") and len(k) > 7
        }
        try:
            shape = QueryShape.from_params(param, match)
        except ValueError as e:
            self.post_response(conn, _json_response(400, {
                "status": "error", "error": str(e)}))
            return
        transport = param("transport") or "sse"
        if transport not in ("sse", "longpoll"):
            self.post_response(conn, _json_response(400, {
                "status": "error",
                "error": "transport must be sse or longpoll"}))
            return
        if transport == "longpoll":
            raw = param("cursor")
            try:
                cursor = int(raw) if raw is not None else None
            except ValueError:
                self.post_response(conn, _json_response(400, {
                    "status": "error", "error": "cursor must be an integer",
                }))
                return

            def answer(doc: dict) -> None:
                self.post_response(conn, _json_response(200, doc))

            try:
                doc = hub.poll_frames(shape, cursor, answer)
            except Exception as e:  # noqa: BLE001 — a broken shape answers, never hangs
                self.post_response(conn, _json_response(500, {
                    "status": "error", "error": str(e)}))
                return
            if doc is not None:
                answer(doc)
            # else: parked — the hub answers from a later round or the
            # heartbeat tick.
            return
        try:
            sub, first = hub.subscribe(
                shape,
                writer=lambda payload: self.call_soon(
                    lambda: self._stream_write(conn, payload)),
                closer=lambda: self.call_soon(
                    lambda: self._end_stream(conn)),
                auto_start=False,
            )
        except HubFull:
            self.post_raw(conn, _STREAM_REJECT_RESPONSE)
            return
        except Exception as e:  # noqa: BLE001 — a broken shape answers, never hangs
            self.post_response(conn, _json_response(500, {
                "status": "error", "error": str(e)}))
            return
        self.call_soon(lambda: self._begin_stream(conn, sub, first))

    def _arm_stream_tick(self) -> None:
        """Loop-thread: recurring 1 s maintenance tick for the stream hub
        (heartbeats, long-poll timeouts, idle-shape GC)."""
        hub = self.state.stream
        if hub is None or self._stopping:
            return

        def tick() -> None:
            if self._stopping:
                return
            h = self.state.stream
            if h is not None:
                h.tick()
            self.call_later(1.0, tick)

        self.call_later(1.0, tick)

    # ------------------------------------------------------------- routing

    def _count_reject(self, cause: str) -> None:
        st = self.state
        # += on a dict value is a read-modify-write, NOT GIL-atomic; the
        # worker reject paths share this counter with the loop (advisor r4).
        with st.scrape_rejects_lock:
            st.scrape_rejects[cause] = st.scrape_rejects.get(cause, 0) + 1

    def _handle_request(self, conn: _Conn, req: _Request) -> None:
        st = self.state
        path, _, query = req.target.partition("?")
        exempt = path in _ADMISSION_EXEMPT_PATHS
        if not conn.admitted:
            # Over the connection cap: this connection never got a slot.
            # Probe paths still answer (then close); everything else gets
            # the pre-rendered 429 — the storm pays, kubelet never does.
            if exempt:
                resp = self._probe_response(path)
                resp.close = True
                self._respond(conn, resp)
            else:
                self._count_reject("connections")
                self._send_raw(conn, _CONN_REJECT_RESPONSE)
            return
        cap = st.max_requests_per_client
        if cap > 0 and not exempt:
            ip = conn.ip
            with st.client_lock:
                cur = st.client_active.get(ip, 0)
                over = cur >= cap
                if not over:
                    st.client_active[ip] = cur + 1
            if over:
                self._count_reject("client")
                self._send_raw(conn, _CLIENT_REJECT_RESPONSE)
                return
            # Held until this request's response is flushed (or the
            # connection dies) — the loop equivalent of the old handler
            # thread occupying the slot for the handler's lifetime.
            conn.client_key = ip
        self._dispatch(conn, req, path, query)

    def _dispatch(self, conn: _Conn, req: _Request, path: str,
                  query: str) -> None:
        st = self.state
        if path == "/metrics":
            self._handle_metrics(conn, req)
        elif path == "/api/v1/stream":
            # Outside the 2-permit /api/v1 fence: a subscription is a
            # long-lived registration, not a query — holding a permit for
            # the stream's lifetime would wedge the polled API behind two
            # viewers. The hub's subscriber cap is the admission control.
            self._defer(conn, lambda: self._task_stream(conn, query))
        elif path.startswith("/api/v1/"):
            self._defer(conn, lambda: self._task_api(conn, req, path, query))
        elif path.startswith("/debug/") and not debug_client_allowed(
            conn.ip, st.debug_addr
        ):
            # Loopback-only by default: stacks + effective config are
            # operator surface. --debug-addr 0.0.0.0 restores remote reads.
            self._respond(conn, _text_response(
                403, b"debug endpoints are loopback-only "
                     b"(start with --debug-addr 0.0.0.0 to expose)\n"))
        elif path == "/debug/vars" and st.debug_vars is not None:
            self._defer(conn, lambda: self._task_debug_vars(conn))
        elif path == "/debug/trace":
            self._defer(conn, lambda: self._task_trace(conn, query))
        elif path == "/debug/stacks":
            # The pprof-equivalent SURVEY §5 asks for, sized to this
            # process: a point-in-time dump of every thread's Python stack.
            # THE tool for the wedge /healthz detects — `curl
            # /debug/stacks` from the node shows exactly where a stuck
            # poll thread is blocked (a hung gRPC call, a dead NFS mount)
            # without kubectl exec, a debugger, or signals. Served from a
            # worker thread, so it renders even while the poll thread (or
            # a render) is wedged.
            self._defer(conn, lambda: self._task_stacks(conn))
        elif path in _ADMISSION_EXEMPT_PATHS:
            self._respond(conn, self._probe_response(path))
        elif path == "/":
            self._respond(conn, _text_response(
                200,
                b"tpu-pod-exporter\n/metrics /healthz /readyz "
                b"/api/v1/series /api/v1/query_range /api/v1/window_stats\n",
            ))
        else:
            self._respond(conn, _text_response(404, b"not found\n"))

    def _defer(self, conn: _Conn, fn: Callable[[], None]) -> None:
        self.served["worker"] += 1

        def run() -> None:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a task bug must still answer
                log.exception("worker request task failed")
                # Without this the client hangs until its own timeout and
                # the keep-alive connection is wedged forever (busy never
                # clears). Scheduled AFTER any response the task itself
                # posted (call_soon is FIFO), so the guard below can tell
                # "no response ever sent" from "response already in
                # flight/flushed".
                def fail() -> None:
                    if conn.closed or conn.response_pending or not conn.busy:
                        return
                    self._respond(conn, _text_response(
                        500, b"internal error\n", close=True))
                self.call_soon(fail)

        self.pool.submit(run)

    def post_response(self, conn: _Conn, resp: _Response) -> None:
        """Worker-side: hand a finished response to the loop for writing."""
        self.call_soon(lambda: self._respond(conn, resp))

    def post_raw(self, conn: _Conn, raw: bytes) -> None:
        self.call_soon(lambda: self._send_raw(conn, raw))

    # ------------------------------------------------------------- /metrics

    def _handle_metrics(self, conn: _Conn, req: _Request) -> None:
        st = self.state
        bucket = st.scrape_bucket
        if bucket is not None and not bucket.take():
            self._count_reject("rate")
            if st.scrape_tarpit_s > 0:
                # Rate-cap rejects answer late: a fast 429 just makes a
                # storming client retry faster. On the loop the tarpit is
                # a timer — zero threads parked, however wide the storm.
                self.call_later(
                    st.scrape_tarpit_s,
                    lambda: self._send_raw(conn, _REJECT_RESPONSE),
                )
            else:
                self._send_raw(conn, _REJECT_RESPONSE)
            return
        sem = st.scrape_sem
        if sem is not None and not sem.acquire(blocking=False):
            # Contended: queue briefly on a worker with the old timeout
            # semantics (429 + token refund when the wait expires).
            self._defer(conn, lambda: self._task_metrics_queued(conn, req))
            return
        # Permit held (or no fence). Fast path: a body already rendered for
        # this (format, encoding) pair is served inline — one cached-bytes
        # lookup, no worker handoff, no blocking anywhere.
        snap = st.store.current()
        openmetrics = accepts_openmetrics(req.headers.get("accept", ""))
        gzipped = "gzip" in req.headers.get("accept-encoding", "")
        cached = getattr(snap, "cached_exposition", None)
        body = cached(openmetrics, gzipped) if cached is not None else None
        if body is not None:
            self.served["inline"] += 1
            if sem is not None:
                sem.release()
            self._respond(conn, self._metrics_response(
                req, body, openmetrics, gzipped))
            return
        # Uncached (first scrape of a fresh encoding, or a store whose
        # snapshots render lazily): the render may block — worker, with
        # the already-held permit transferred.
        self._defer(
            conn, lambda: self._task_metrics_render(conn, req, sem),
        )

    def _metrics_response(self, req: _Request, body: bytes,
                          openmetrics: bool, gzipped: bool) -> _Response:
        headers = [(
            "Content-Type",
            OPENMETRICS_CONTENT_TYPE if openmetrics else CONTENT_TYPE,
        )]
        if gzipped:
            headers.append(("Content-Encoding", "gzip"))
        ctx = None
        if self.state.trace is not None:
            ctx = parse_traceparent(req.headers.get("traceparent", ""))
        return _Response(200, headers, body, observe=True, trace_ctx=ctx)

    def _task_metrics_queued(self, conn: _Conn, req: _Request) -> None:
        st = self.state
        sem = st.scrape_sem
        assert sem is not None
        if not sem.acquire(timeout=st.scrape_queue_timeout_s):
            if st.scrape_bucket is not None:
                st.scrape_bucket.refund()  # this scrape was never served
            # No tarpit here: this path already queued for
            # scrape_queue_timeout_s, which throttles the client the same
            # way.
            self._count_reject("concurrency")
            self.post_raw(conn, _REJECT_RESPONSE)
            return
        try:
            self._render_metrics(conn, req)
        finally:
            sem.release()

    def _task_metrics_render(self, conn: _Conn, req: _Request,
                             sem: threading.BoundedSemaphore | None) -> None:
        try:
            self._render_metrics(conn, req)
        finally:
            if sem is not None:
                sem.release()

    def _render_metrics(self, conn: _Conn, req: _Request) -> None:
        snap = self.state.store.current()
        # Content negotiation: Prometheus ≥2.5 advertises OpenMetrics in
        # Accept; both formats are served from cached bytes, so the
        # negotiation costs a header parse, not a render.
        openmetrics = accepts_openmetrics(req.headers.get("accept", ""))
        gzipped = "gzip" in req.headers.get("accept-encoding", "")
        if gzipped:
            body = (
                snap.encode_openmetrics_gzip() if openmetrics
                else snap.encode_gzip()
            )
        else:
            body = snap.encode_openmetrics() if openmetrics else snap.encode()
        self.post_response(conn, self._metrics_response(
            req, body, openmetrics, gzipped))

    # --------------------------------------------------------------- probes

    def _probe_response(self, path: str) -> _Response:
        if path == "/healthz":
            return self._healthz_response()
        return self._readyz_response()

    def _healthz_response(self) -> _Response:
        st = self.state
        reason = None
        if st.live_fn is not None:
            try:
                reason = st.live_fn()
            except Exception as e:  # noqa: BLE001 — a broken hook is itself unhealthy
                reason = f"liveness hook failed: {e}"
        snap = st.store.current()
        if reason:
            return _text_response(503, f"{reason}\n".encode())
        if (
            st.health_max_age_s > 0
            and snap.timestamp > 0
            and time.time() - snap.timestamp > st.health_max_age_s
        ):
            age = time.time() - snap.timestamp
            return _text_response(
                503, f"poll stalled: last snapshot {age:.1f}s old\n".encode()
            )
        return _text_response(200, b"ok\n")

    def _readyz_response(self) -> _Response:
        st = self.state
        snap = st.store.current()
        ready = snap.timestamp > 0
        body: dict = {"ready": ready}
        warm = None
        if ready and st.warm_fn is not None:
            try:
                warm = st.warm_fn()
            except Exception:  # noqa: BLE001 — warm detail must not break probes
                warm = None
        if not ready:
            body["state"] = "starting"
            body["reason"] = "no poll completed yet"
        elif warm is not None:
            # Serving the restored pre-restart snapshot; no live poll
            # yet. Still 200 — data IS being served (that is the whole
            # point of warm start) — but distinctly labeled so rollouts
            # and operators can tell restored from live.
            body["state"] = "warm"
            body.update(warm)
        else:
            body["state"] = "ready"
        if st.ready_detail_fn is not None:
            try:
                detail = st.ready_detail_fn() or {}
                body.update(detail)
                # Degraded = still serving, but an operator should
                # look: a source breaker stuck open across probes, or
                # the egress receiver unreachable past the same reopen
                # threshold (batches buffering to disk, not flowing).
                egress = detail.get("egress") or {}
                if body["state"] == "ready" and (
                    detail.get("degraded_sources")
                    or egress.get("degraded")
                ):
                    body["state"] = "degraded"
            except Exception:  # noqa: BLE001 — detail must not break probes
                pass
        # JSON either way (kubelet only reads the status code; humans
        # and the RUNBOOK read the state + degraded-source detail).
        return _json_response(200 if ready else 503, body)

    # ------------------------------------------------------------ /debug/*

    def _task_debug_vars(self, conn: _Conn) -> None:
        st = self.state
        assert st.debug_vars is not None
        try:
            body = json.dumps(st.debug_vars(), indent=1).encode()
        except Exception as e:  # noqa: BLE001 — debug must not 500 loops
            body = json.dumps({"error": str(e)}).encode()
        self.post_response(conn, _Response(
            200, [("Content-Type", "application/json")], body))

    def _task_stacks(self, conn: _Conn) -> None:
        self.post_response(conn, _text_response(200, _format_stacks().encode()))

    # /debug/trace response bound: `last` is clamped so the export stays a
    # bounded handful of MB no matter what a client asks for (each trace is
    # ~8 spans; scrape spans are capped by their own ring).
    TRACE_EXPORT_MAX_LAST = 200

    def _task_trace(self, conn: _Conn, query: str) -> None:
        ts = self.state.trace
        if ts is None:
            self.post_response(conn, _json_response(404, {
                "status": "error",
                "error": "tracing disabled (--trace off)",
            }))
            return
        qs = parse_qs(query, keep_blank_values=True)
        try:
            last = int((qs.get("last") or ["20"])[-1])
        except ValueError:
            self.post_response(conn, _json_response(400, {
                "status": "error", "error": "last must be an integer",
            }))
            return
        if last < 1:
            self.post_response(conn, _json_response(400, {
                "status": "error", "error": "last must be >= 1",
            }))
            return
        last = min(last, self.TRACE_EXPORT_MAX_LAST)
        # Copy references under the store lock; build + serialize the (much
        # larger) JSON document on this worker — never on the loop, never
        # under the store lock (the /debug/* lock audit).
        traces = ts.last(last)
        scrapes = ts.scrapes(min(4 * last, 512))
        self.post_response(
            conn, _json_response(200, to_chrome_trace(traces, scrapes)))

    # ------------------------------------------------------------- /api/v1

    def _task_api(self, conn: _Conn, req: _Request, path: str,
                  query: str) -> None:
        """JSON query surface: node-local history flight recorder, or the
        aggregator's federated fleet query plane when one is attached.
        Outside the scrape fences (the aggregator's missed-round fallback
        must not compete with the very scrape storm it is working around)
        but behind its own small concurrency cap — the same 2-permit fence
        and pre-rendered 429 + Retry-After on both exporter and
        aggregator."""
        st = self.state
        sem = st.api_sem
        if sem is not None and not sem.acquire(timeout=st.api_queue_timeout_s):
            self.post_raw(conn, _API_REJECT_RESPONSE)
            return
        try:
            t0 = time.perf_counter()
            resp = self._api_response(path, query)
            tstore = st.trace
            if tstore is not None:
                # Same cross-tier join as /metrics: an /api/v1 request
                # carrying a traceparent (the fleet query plane stamps one
                # per fan-out leg) records this node's serve span under the
                # remote query trace. Headerless queries record nothing.
                ctx = parse_traceparent(req.headers.get("traceparent", ""))
                if ctx is not None:
                    dur = time.perf_counter() - t0
                    tstore.record_scrape(
                        ctx[0], ctx[1], time.time() - dur, dur,
                        client=conn.ip,
                    )
            self.post_response(conn, resp)
        finally:
            if sem is not None:
                sem.release()

    @staticmethod
    def _parse_range_params(
        param: Callable[[str], str | None],
    ) -> tuple[str, float, float, float, str]:
        """Validated query_range params — shared by the node-local and
        fleet routes so the 400 contract cannot drift between tiers."""
        metric = param("metric")
        if not metric:
            raise ValueError("missing required parameter: metric")
        end = float(param("end") or time.time())
        start = float(param("start") or (end - 300.0))
        step = float(param("step") or 0.0)
        agg = param("agg") or "last"
        if agg not in ("last", "min", "max", "mean"):
            raise ValueError("agg must be one of last/min/max/mean")
        # Finite + bounded before the store walks a grid: the grid
        # loop is O((end-start)/step) Python iterations, and this
        # endpoint is unauthenticated and exempt from the scrape
        # fences — start=0&step=1 (~1.7e9 points) or end=inf must
        # be a 400, not a pinned worker thread. Cap matches
        # Prometheus's 11k resolution limit.
        if not (math.isfinite(start) and math.isfinite(end)
                and math.isfinite(step)):
            raise ValueError("start/end/step must be finite")
        if step < 0:
            raise ValueError("step must be >= 0")
        if end < start:
            raise ValueError("end must be >= start")
        if step > 0 and (end - start) / step > 11000:
            raise ValueError(
                "query resolution too high: (end - start) / step "
                "must be <= 11000"
            )
        return metric, start, end, step, agg

    @staticmethod
    def _parse_window_params(
        param: Callable[[str], str | None],
    ) -> tuple[str, float]:
        metric = param("metric")
        if not metric:
            raise ValueError("missing required parameter: metric")
        window = float(param("window") or 60.0)
        if window <= 0:
            raise ValueError("window must be > 0")
        return metric, window

    def _api_response(self, path: str, query: str) -> _Response:
        st = self.state
        qs = parse_qs(query, keep_blank_values=True)

        def param(name: str) -> str | None:
            vals = qs.get(name)
            return vals[-1] if vals else None

        match = {
            k[len("match["):-1]: vs[-1]
            for k, vs in qs.items()
            if k.startswith("match[") and k.endswith("]") and len(k) > 7
        }
        if st.fleet is not None:
            return self._fleet_api_response(path, param, match)
        if param("source"):
            # The node tier has no store: a ?source= knob that silently
            # does nothing would let an operator trust an answer that is
            # not what they asked for (same rule as the store-less
            # aggregator below).
            return _json_response(400, {
                "status": "error",
                "error": "source= requires a store-backed root "
                         "(no fleet store attached on this tier)",
            })
        h = st.history
        if h is None:
            return _json_response(404, {
                "status": "error",
                "error": "history disabled (--history-retention-s 0)",
            })
        try:
            if path == "/api/v1/series":
                return _json_response(200, {"status": "ok", "source": "live",
                                            "data": h.series_list()})
            if path == "/api/v1/query_range":
                metric, start, end, step, agg = self._parse_range_params(
                    param)
                result = h.query_range(metric, match, start, end, step,
                                       agg=agg)
                if not result:
                    return _json_response(404, {
                        "status": "error",
                        "error": f"no samples for metric {metric!r} "
                                 f"matching {match!r} in range",
                    })
                return _json_response(200, {
                    "status": "ok",
                    # Shared envelope contract across tiers: node-local
                    # answers are "live" by definition (the root's
                    # store-backed plane answers live|store|merged under
                    # the same key) — shapes must not drift between tiers.
                    "source": "live",
                    "data": {"resultType": "matrix", "result": result},
                })
            if path == "/api/v1/window_stats":
                metric, window = self._parse_window_params(param)
                result = h.window_stats(metric, match, window_s=window)
                if not result:
                    return _json_response(404, {
                        "status": "error",
                        "error": f"no samples for metric {metric!r} "
                                 f"matching {match!r} in window",
                    })
                return _json_response(200, {"status": "ok", "source": "live",
                                            "data": {"result": result}})
        except ValueError as e:
            return _json_response(400, {"status": "error", "error": str(e)})
        return _json_response(404, {"status": "error",
                                    "error": "unknown API path"})

    def _fleet_api_response(self, path: str,
                            param: Callable[[str], str | None],
                            match: dict) -> _Response:
        """Federated /api/v1 on the aggregator: same routes, same param
        validation, but the answer is the fleet envelope — merged series
        plus per-target status — and a dead target is partial=true, never
        a non-200 round failure."""
        fleet = self.state.fleet
        # ?source=live|store|merged is meaningful only on a store-backed
        # plane (the root with --store-dir). Asking a store-less tier for
        # it must be an actionable 400, never a silently-ignored knob —
        # an operator reading "source":"live" back from a query they sent
        # ?source=store to would trust data that is not what they asked.
        source = param("source")
        kwargs: dict = {}
        if getattr(fleet, "handles_source", False):
            if source:
                kwargs["source"] = source
        elif source:
            return _json_response(400, {
                "status": "error",
                "error": "source= requires a store-backed root "
                         "(no fleet store attached on this tier)",
            })
        try:
            if path == "/api/v1/series":
                return _json_response(200, fleet.series(**kwargs))
            if path == "/api/v1/query_range":
                metric, start, end, step, agg = self._parse_range_params(
                    param)
                return _json_response(200, fleet.query_range(
                    metric, match, start, end, step, agg=agg, **kwargs))
            if path == "/api/v1/window_stats":
                metric, window = self._parse_window_params(param)
                return _json_response(200, fleet.window_stats(
                    metric, match, window_s=window, **kwargs))
        except ValueError as e:
            return _json_response(400, {"status": "error", "error": str(e)})
        return _json_response(404, {"status": "error",
                                    "error": "unknown API path"})


class MetricsServer:
    """Owns the event loop thread. Unlike the reference (hardcoded
    ``:8000``, ``log.Fatal`` on listener death, ``main.go:71``), port 0 is
    allowed for tests (ephemeral) and shutdown is clean."""

    def __init__(
        self,
        store: SnapshotStore,
        host: str = "0.0.0.0",
        port: int = 8000,
        debug_vars: Callable[[], dict] | None = None,
        health_max_age_s: float = 0.0,
        max_concurrent_scrapes: int = 4,
        scrape_queue_timeout_s: float = 0.25,
        max_scrapes_per_s: float = 0.0,
        scrape_tarpit_s: float = 0.1,
        scrape_observer: Callable[[float], None] | None = None,
        history: Any = None,
        fleet: Any = None,
        trace: Any = None,
        debug_addr: str = "127.0.0.1",
        live_fn: Callable[[], str | None] | None = None,
        ready_detail_fn: Callable[[], dict] | None = None,
        client_write_timeout_s: float = 10.0,
        warm_fn: Callable[[], dict | None] | None = None,
        max_open_connections: int = 0,
        max_requests_per_client: int = 0,
        max_workers: int = 8,
        worker_idle_expire_s: float = 10.0,
        stream_hub: Any = None,
        stream_max_buffer_bytes: int = 2 << 20,
    ) -> None:
        # Every cause pre-seeded so the self-metric publishes a 0 series
        # per cause from poll 1 (stable surface). "connections"/"client"
        # are the admission-control causes (0 unless the caps are on).
        self.scrape_rejects = {
            "concurrency": 0, "rate": 0, "connections": 0, "client": 0,
        }
        self.write_timeouts = {"total": 0}
        # Open-connection accounting for the admission cap (peak is the
        # scrape-storm drill's bound witness).
        self.conn_stats = {"open": 0, "peak": 0}
        state = _HandlerState()
        state.store = store
        state.debug_vars = debug_vars
        state.history = history
        state.fleet = fleet
        state.trace = trace
        state.api_sem = (
            threading.BoundedSemaphore(2)
            if history is not None or fleet is not None
            else None
        )
        state.debug_addr = debug_addr
        state.health_max_age_s = health_max_age_s
        state.live_fn = live_fn
        state.ready_detail_fn = ready_detail_fn
        state.warm_fn = warm_fn
        state.client_write_timeout_s = client_write_timeout_s
        state.write_timeouts = self.write_timeouts
        state.scrape_sem = (
            threading.BoundedSemaphore(max_concurrent_scrapes)
            if max_concurrent_scrapes > 0
            else None
        )
        state.scrape_queue_timeout_s = scrape_queue_timeout_s
        # Burst 2× rate: absorbs scrape-alignment spikes (every scraper
        # firing in the same second) without letting a sustained storm
        # exceed ~rate serves/s.
        state.scrape_bucket = (
            _TokenBucket(max_scrapes_per_s, 2.0 * max_scrapes_per_s)
            if max_scrapes_per_s > 0
            else None
        )
        state.scrape_tarpit_s = scrape_tarpit_s
        state.scrape_rejects = self.scrape_rejects
        state.scrape_observer = scrape_observer
        state.max_open_connections = max_open_connections
        state.conn_stats = self.conn_stats
        state.max_requests_per_client = max_requests_per_client
        state.stream = stream_hub
        state.stream_max_buffer_bytes = stream_max_buffer_bytes
        self._state = state
        self._loop = _EventLoopServer(host, port, state, max_workers,
                                      worker_idle_expire_s)
        self._httpd = _CompatHandle(state)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._loop.port

    def stats(self) -> dict[str, int]:
        """Loop/pool counters for /debug/vars (RUNBOOK 'server')."""
        loop = self._loop
        return {
            "open_connections": self.conn_stats["open"],
            "peak_connections": self.conn_stats["peak"],
            "write_timeouts": self.write_timeouts["total"],
            "served_inline": loop.served["inline"],
            # Counted at dispatch, not completion: includes requests the
            # task itself later 429s (the /api/v1 fence) or fails with 500.
            "worker_dispatched": loop.served["worker"],
            "worker_threads": loop.pool.threads,
            "worker_queue": loop.pool.queued,
            "stream_subscribers": (
                self._state.stream.subscribers
                if self._state.stream is not None else 0
            ),
        }

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("already started")
        self._thread = threading.Thread(
            target=self._loop.run, name="tpu-exporter-http", daemon=True,
        )
        self._thread.start()
        if self._state.stream is not None:
            # Heartbeats / long-poll timeouts / shape GC ride a loop
            # timer; call_soon is the thread-safe way onto the loop.
            self._loop.call_soon(self._loop._arm_stream_tick)

    def stop(self) -> None:
        loop = self._loop
        if self._thread is not None:
            loop._stopping = True
            loop.wake()
            self._thread.join(timeout=5.0)
            self._thread = None
        else:
            # Never started: release the port + selector resources without
            # spinning the loop (stop-before-start must not deadlock).
            loop._stopping = True
            loop.pool.shutdown()
            loop._sel.close()
            loop._wake_r.close()
            loop._wake_w.close()
            loop._lsock.close()
