"""Diagnostic records + the inline disable-comment escape hatch.

A finding is suppressed by a comment ON ITS LINE of the form::

    something_flagged()  # lint: disable=RULE(reason why this is intentional)

Several rules may be disabled on one line, comma-separated::

    x = gzip.compress(b)  # lint: disable=lock-io(lazy cache),wall-clock(stamp)

The reason is MANDATORY — an empty ``disable=RULE()`` (or a bare
``disable=RULE``) does not suppress anything: the whole point of the escape
hatch is that every grandfathered exception carries its justification in
the diff where reviewers see it.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass

ERROR = "error"
WARNING = "warning"

# `# lint: disable=rule-a(reason), rule-b(other reason)`
_DISABLE_RE = re.compile(r"#\s*lint:\s*disable=(.+)$")
# Reason is lazy-matched to a ")" that closes the entry (followed by a
# comma or end-of-line), so reasons may themselves contain parentheses.
_ENTRY_RE = re.compile(r"\s*([a-z][a-z0-9-]*)\s*\((.+?)\)\s*(?:,|$)")


@dataclass(frozen=True)
class Diagnostic:
    """One finding: a rule, where it fired, and why."""

    rule: str
    severity: str  # ERROR | WARNING
    path: str      # repo-relative, e.g. tpu_pod_exporter/collector.py
    line: int      # 1-based
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.severity}: {self.rule}: {self.message}"

    def fingerprint(self, line_text: str = "") -> str:
        """Stable baseline key: rule + path + the offending line's stripped
        text (so unrelated edits shifting line numbers don't churn the
        baseline, but changing the flagged line itself does)."""
        h = hashlib.sha1(
            f"{self.rule}\x00{self.path}\x00{line_text.strip()}".encode()
        )
        return h.hexdigest()[:16]


def to_sarif(findings: list[Diagnostic], rules: tuple = ()) -> dict:
    """SARIF 2.1.0 document from one findings list — the same list the
    text/JSON renderers consume, so CI can annotate PRs inline without a
    second lint pass. ``rules`` is the ALL_RULES tuple (passed in to keep
    this module import-light)."""
    level = {ERROR: "error", WARNING: "warning"}
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "exporter-lint",
                    "informationUri": (
                        "https://example.invalid/tpu-pod-exporter"
                        "#static-analysis"
                    ),
                    "rules": [
                        {
                            "id": r.name,
                            "shortDescription": {"text": r.summary},
                            "defaultConfiguration": {
                                "level": level.get(r.severity, "warning"),
                            },
                        }
                        for r in rules
                    ],
                },
            },
            "results": [
                {
                    "ruleId": d.rule,
                    "level": level.get(d.severity, "warning"),
                    "message": {"text": d.message},
                    "locations": [{
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": d.path,
                                "uriBaseId": "SRCROOT",
                            },
                            "region": {"startLine": max(d.line, 1)},
                        },
                    }],
                }
                for d in findings
            ],
        }],
    }


def parse_disables(line: str) -> dict[str, str]:
    """Extract ``{rule: reason}`` from one source line's disable comment.

    Returns an empty dict when the line has no (well-formed) disable —
    including ``disable=rule()`` with an empty reason, which is rejected by
    the regex on purpose (see module docstring).
    """
    m = _DISABLE_RE.search(line)
    if m is None:
        return {}
    out: dict[str, str] = {}
    for rule, reason in _ENTRY_RE.findall(m.group(1)):
        out[rule] = reason.strip()
    return out
