"""Runtime lock witness — the dynamic half of the concurrency contract.

Wraps ``threading.Lock``/``threading.RLock`` (factory patch, scoped to
locks *created by package code*: the creating frame's file must sit under
one of the configured include paths, so stdlib ``queue``/``logging``
locks stay untouched) and records, per thread:

* the **acquisition-order edges** actually exercised — acquiring B while
  holding A records edge ``A -> B``, keyed by each lock's creation site,
  which is exactly the identity the static pass in
  :mod:`.concurrency` assigns. CI replays the tier-1 suite under the
  witness and cross-checks every observed edge against the static order
  graph: an edge the model cannot explain fails the build.
* **order inversions**, lockdep-style: recording ``A -> B`` when a path
  ``B -> ... -> A`` was already witnessed is a deadlock candidate *even
  if no deadlock happened on this run* — two threads interleaving those
  two paths can deadlock. Same-instance blocking re-acquisition of a
  non-reentrant lock is recorded as a self-deadlock. RLock re-entry by
  the owning thread is NOT an edge and NOT an inversion.
* **held wall-time** per lock class, with a warn list for holds past a
  threshold (``TPE_LOCK_WITNESS_HOLD_MS``, default 250 ms) — long holds
  are reported in the dump for review, never a hard failure (CI runners
  stall arbitrarily; a wall-time gate would flake).

Installed from ``tests/conftest.py`` under ``TPE_LOCK_WITNESS=1``; the
edge dump lands at ``TPE_LOCK_WITNESS_OUT`` (default
``lock-witness.json``) and ``python -m tpu_pod_exporter.analysis
--check-witness <dump>`` performs the static/dynamic cross-check.

The witness's own bookkeeping uses a raw ``_thread`` lock allocated
before any patching, so it can never observe (or deadlock) itself.

This module also hosts :class:`LoopWitness` — the runtime half of the
loop-blocking contract (``TPE_LOOP_WITNESS=1``): it hooks the event
loop's dispatch choke point (``server.LOOP_PROBE``) and times every
callback the loop runs inline, failing the session on stalls.
"""

from __future__ import annotations

import _thread
import json
import os
import sys
import threading
import time
from typing import Any, Callable

# Captured at import time — the real factories, never the patched ones.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_MAX_LONG_HOLDS = 200
_MAX_INVERSIONS = 200


class _WitnessLock:
    """Delegating wrapper around a real lock. Supports the full
    Lock/RLock surface (context manager, acquire/release/locked);
    anything exotic falls through to the inner lock."""

    __slots__ = ("_witness", "_inner", "site", "kind")

    def __init__(self, witness: "LockWitness", inner: Any,
                 site: str, kind: str) -> None:
        self._witness = witness
        self._inner = inner
        self.site = site
        self.kind = kind

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking and self.kind == "lock":
            # About to block on a lock this thread already holds: record
            # the self-deadlock BEFORE parking forever on it.
            self._witness._note_self_deadlock_if_held(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness._on_acquired(self)
        return ok

    def release(self) -> None:
        self._witness._on_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()

    def __getattr__(self, name: str) -> Any:
        return getattr(object.__getattribute__(self, "_inner"), name)


class LockWitness:
    """Factory-patching lock witness. One instance per process; install/
    uninstall are idempotent and restore the real factories."""

    def __init__(
        self,
        include: tuple[str, ...] = (),
        root: str | None = None,
        hold_warn_ms: float | None = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        # Default scope: the tpu_pod_exporter package, minus analysis/
        # (the witness's own home must not observe itself).
        pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        self.root = os.path.abspath(root or os.path.dirname(pkg_dir))
        self.include = tuple(os.path.abspath(p) for p in include) or (pkg_dir,)
        self.exclude = (os.path.join(pkg_dir, "analysis"),)
        if hold_warn_ms is None:
            hold_warn_ms = float(
                os.environ.get("TPE_LOCK_WITNESS_HOLD_MS", "250"))
        self.hold_warn_ms = hold_warn_ms
        self._clock = clock
        self._mutex = _thread.allocate_lock()
        self._tls = threading.local()
        self._installed = False
        self._saved: tuple = (_REAL_LOCK, _REAL_RLOCK)
        # site -> {"path","line","kind","created","acquired"}
        self.lock_sites: dict[str, dict] = {}
        # (src_site, dst_site) -> {"count", "example"}
        self.edges: dict[tuple[str, str], dict] = {}
        self._adj: dict[str, set[str]] = {}
        self.inversions: list[dict] = []
        self.long_holds: list[dict] = []
        self.max_hold_ms: dict[str, float] = {}
        self.acquisitions = 0

    # ------------------------------------------------------------ patching

    def install(self) -> "LockWitness":
        if not self._installed:
            # Save whatever factories are live (possibly another witness,
            # e.g. the env-installed one while a test drives its own) and
            # wrap the RAW primitives — witnesses never stack.
            self._saved = (threading.Lock, threading.RLock)
            threading.Lock = self._factory("lock", _REAL_LOCK)  # type: ignore[misc]
            threading.RLock = self._factory("rlock", _REAL_RLOCK)  # type: ignore[misc]
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            threading.Lock, threading.RLock = self._saved  # type: ignore[misc]
            self._installed = False

    def __enter__(self) -> "LockWitness":
        return self.install()

    def __exit__(self, *exc: object) -> None:
        self.uninstall()

    def _factory(self, kind: str, real: Callable[[], Any]) -> Callable:
        def make() -> Any:
            inner = real()
            frame = sys._getframe(1)
            fn = os.path.abspath(frame.f_code.co_filename)
            if not any(fn.startswith(p + os.sep) or fn == p
                       for p in self.include):
                return inner
            if any(fn.startswith(p + os.sep) for p in self.exclude):
                return inner
            rel = os.path.relpath(fn, self.root).replace(os.sep, "/")
            site = f"{rel}:{frame.f_lineno}"
            with self._mutex:
                rec = self.lock_sites.setdefault(site, {
                    "site": site, "path": rel, "line": frame.f_lineno,
                    "kind": kind, "created": 0, "acquired": 0,
                })
                rec["created"] += 1
            return _WitnessLock(self, inner, site, kind)

        make.__name__ = f"witness_{kind}_factory"
        return make

    # ----------------------------------------------------------- recording

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _note_self_deadlock_if_held(self, lk: _WitnessLock) -> None:
        for held, _t0, _re in self._stack():
            if held is lk:
                with self._mutex:
                    if len(self.inversions) < _MAX_INVERSIONS:
                        self.inversions.append({
                            "kind": "self-deadlock",
                            "detail": (
                                f"thread {threading.current_thread().name!r} "
                                f"blocking-acquires non-reentrant lock "
                                f"{lk.site} it already holds "
                                f"(at {self._caller_site()})"
                            ),
                        })
                return

    def _on_acquired(self, lk: _WitnessLock) -> None:
        stack = self._stack()
        reenter = any(held is lk for held, _t0, _re in stack)
        if not reenter:
            held_sites = [held.site for held, _t0, _re in stack
                          if not _re and held.site != lk.site]
            if held_sites:
                self._record_edges(held_sites, lk.site)
            with self._mutex:
                self.acquisitions += 1
                rec = self.lock_sites.get(lk.site)
                if rec is not None:
                    rec["acquired"] += 1
        stack.append((lk, self._clock(), reenter))

    def _on_release(self, lk: _WitnessLock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lk:
                _held, t0, reenter = stack.pop(i)
                if not reenter:
                    held_ms = (self._clock() - t0) * 1000.0
                    with self._mutex:
                        prev = self.max_hold_ms.get(lk.site, 0.0)
                        if held_ms > prev:
                            self.max_hold_ms[lk.site] = held_ms
                        if (held_ms > self.hold_warn_ms
                                and len(self.long_holds) < _MAX_LONG_HOLDS):
                            self.long_holds.append({
                                "site": lk.site,
                                "held_ms": round(held_ms, 3),
                                "thread": threading.current_thread().name,
                            })
                return
        # Release of a lock this thread never acquired (ownership handed
        # across threads — Condition internals do this legitimately
        # during wait()); nothing to unwind.

    def _record_edges(self, held_sites: list[str], dst: str) -> None:
        thread = threading.current_thread().name
        with self._mutex:
            for src in held_sites:
                key = (src, dst)
                rec = self.edges.get(key)
                if rec is not None:
                    rec["count"] += 1
                    continue
                example = (f"thread {thread!r} at {self._caller_site()}")
                self.edges[key] = {"count": 1, "example": example}
                self._adj.setdefault(src, set()).add(dst)
                # Inversion: a path dst -> ... -> src already witnessed.
                path = self._find_path(dst, src)
                if path is not None and len(self.inversions) < _MAX_INVERSIONS:
                    self.inversions.append({
                        "kind": "order-inversion",
                        "detail": (
                            f"edge {src} -> {dst} ({example}) inverts the "
                            f"already-witnessed order "
                            f"{' -> '.join(path)}"
                        ),
                    })

    def _find_path(self, start: str, goal: str) -> list[str] | None:
        if start not in self._adj:
            return None
        prev: dict[str, str] = {}
        work = [start]
        seen = {start}
        while work:
            cur = work.pop()
            for nxt in self._adj.get(cur, ()):
                if nxt in seen:
                    continue
                prev[nxt] = cur
                if nxt == goal:
                    path = [goal]
                    while path[-1] != start:
                        path.append(prev[path[-1]])
                    path.reverse()
                    return path
                seen.add(nxt)
                work.append(nxt)
        return None

    @staticmethod
    def _caller_site() -> str:
        """First stack frame outside this module — where the acquire
        physically happened (diagnostics only; edge identity is the
        creation site)."""
        f = sys._getframe(2)
        here = os.path.abspath(__file__)
        while f is not None:
            fn = os.path.abspath(f.f_code.co_filename)
            if fn != here:
                return f"{fn}:{f.f_lineno}"
            back = f.f_back
            if back is None:
                break
            f = back
        return "<unknown>"

    # ----------------------------------------------------------- reporting

    def report(self) -> dict:
        with self._mutex:
            return {
                "meta": {
                    "acquisitions": self.acquisitions,
                    "hold_warn_ms": self.hold_warn_ms,
                    "locks": len(self.lock_sites),
                    "edges": len(self.edges),
                },
                "locks": [
                    dict(rec) for _, rec in sorted(self.lock_sites.items())
                ],
                "edges": [
                    {"from": src, "to": dst, **rec}
                    for (src, dst), rec in sorted(self.edges.items())
                ],
                "inversions": list(self.inversions),
                "long_holds": list(self.long_holds),
                "max_hold_ms": {
                    site: round(ms, 3)
                    for site, ms in sorted(self.max_hold_ms.items())
                },
            }

    def dump(self, path: str) -> dict:
        doc = self.report()
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return doc


def load_dump(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: witness dump must be a JSON object")
    return doc


# --------------------------------------------------------- loop witness


class LoopWitness:
    """Runtime loop-stall witness — the dynamic half of the loop-blocking
    contract (static half: analysis/execcontext.py).

    Hooks ``server.LOOP_PROBE``, the dispatch choke point every callback
    the event loop runs inline passes through (``_invoke``: selector
    events, ``call_soon`` posts, timers). Per callback it aggregates
    count / max / total wall time keyed by the function's STATIC identity
    (module, ``__qualname__``, first line — the same identity
    :func:`execcontext.cross_check_loop` maps onto the model), and
    records a **stall** for any inline callback exceeding the threshold
    (``TPE_LOOP_WITNESS_STALL_MS``, default 500 ms — inline work is
    microseconds-scale; half a second inline means the contract is
    broken, not that the runner is slow). Unlike the lock witness's
    long-hold warn list, stalls FAIL the session: a stalled loop is
    user-visible (every connection parks), so CI treats it like an
    inversion.

    Installed from ``tests/conftest.py`` under ``TPE_LOOP_WITNESS=1``;
    the dump lands at ``TPE_LOOP_WITNESS_OUT`` (default
    ``loop-witness.json``) and ``python -m tpu_pod_exporter.analysis
    --check-loop-witness <dump>`` cross-checks every witnessed callback
    against the static model's loop-role tags."""

    def __init__(self, stall_ms: float | None = None) -> None:
        if stall_ms is None:
            stall_ms = float(
                os.environ.get("TPE_LOOP_WITNESS_STALL_MS", "500"))
        self.stall_ms = stall_ms
        self._mutex = _thread.allocate_lock()
        self._installed = False
        self._saved: Any = None
        # (module, qualname, line) -> {"kinds", "count", "max_ms", "total_ms"}
        self.callbacks: dict[tuple[str, str, int], dict] = {}
        self.stalls: list[dict] = []

    def install(self) -> "LoopWitness":
        if not self._installed:
            # Deferred import: the analyzer side of this module must stay
            # importable without pulling the server in (exporter-lint
            # never imports checked code — only the RUNTIME witness does).
            from tpu_pod_exporter import server
            self._saved = server.LOOP_PROBE
            server.LOOP_PROBE = self._observe
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            from tpu_pod_exporter import server
            server.LOOP_PROBE = self._saved
            self._installed = False

    def __enter__(self) -> "LoopWitness":
        return self.install()

    def __exit__(self, *exc: object) -> None:
        self.uninstall()

    @staticmethod
    def _identity(fn: Any) -> tuple[str, str, int]:
        """Static identity of a dispatched callable: unwrap partials and
        bound methods down to the code object the model parsed."""
        seen = 0
        while hasattr(fn, "func") and seen < 8:  # functools.partial chain
            fn = fn.func
            seen += 1
        fn = getattr(fn, "__func__", fn)  # bound method -> function
        code = getattr(fn, "__code__", None)
        module = getattr(fn, "__module__", "") or ""
        qualname = getattr(fn, "__qualname__", repr(fn))
        line = code.co_firstlineno if code is not None else 0
        return (module, qualname, line)

    def _observe(self, kind: str, fn: Any, dur_s: float) -> None:
        module, qualname, line = self._identity(fn)
        ms = dur_s * 1000.0
        with self._mutex:
            rec = self.callbacks.setdefault((module, qualname, line), {
                "kinds": set(), "count": 0, "max_ms": 0.0, "total_ms": 0.0,
            })
            rec["kinds"].add(kind)
            rec["count"] += 1
            rec["total_ms"] += ms
            if ms > rec["max_ms"]:
                rec["max_ms"] = ms
            if ms > self.stall_ms and len(self.stalls) < _MAX_LONG_HOLDS:
                self.stalls.append({
                    "module": module, "qualname": qualname, "line": line,
                    "kind": kind, "ms": round(ms, 3),
                })

    def report(self) -> dict:
        with self._mutex:
            return {
                "meta": {
                    "kind": "loop-witness",
                    "threshold_ms": self.stall_ms,
                    "callbacks": len(self.callbacks),
                    "stalls": len(self.stalls),
                },
                "callbacks": [
                    {
                        "module": module, "qualname": qualname, "line": line,
                        "kinds": sorted(rec["kinds"]),
                        "count": rec["count"],
                        "max_ms": round(rec["max_ms"], 3),
                        "total_ms": round(rec["total_ms"], 3),
                    }
                    for (module, qualname, line), rec
                    in sorted(self.callbacks.items())
                ],
                "stalls": list(self.stalls),
            }

    def dump(self, path: str) -> dict:
        doc = self.report()
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return doc


# Process-global instance management for the conftest hook.
_active: LockWitness | None = None
_active_loop: LoopWitness | None = None


def install_from_env() -> LockWitness | None:
    """Install the witness when ``TPE_LOCK_WITNESS=1`` (idempotent).
    Returns the active witness, or None when disabled."""
    global _active
    if os.environ.get("TPE_LOCK_WITNESS", "") not in ("1", "true", "yes"):
        return None
    if _active is None:
        _active = LockWitness().install()
    return _active


def active() -> LockWitness | None:
    return _active


def install_loop_from_env() -> LoopWitness | None:
    """Install the loop witness when ``TPE_LOOP_WITNESS=1`` (idempotent).
    Unlike :func:`install_from_env` this imports the server module, so it
    must run AFTER the lock witness is live (lock wrapping happens at
    lock-creation time; probe hooking is just a module-global swap)."""
    global _active_loop
    if os.environ.get("TPE_LOOP_WITNESS", "") not in ("1", "true", "yes"):
        return None
    if _active_loop is None:
        _active_loop = LoopWitness().install()
    return _active_loop


def loop_active() -> LoopWitness | None:
    return _active_loop
