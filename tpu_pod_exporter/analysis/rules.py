"""The invariant rules, one AST checker per convention.

Each rule is a :class:`Rule` with a kebab-case name (the token used in
``# lint: disable=NAME(reason)``), a severity, a one-line contract, and a
checker. Per-file checkers receive the parsed module plus a
:class:`~tpu_pod_exporter.analysis.engine.LintContext` (the schema registry
and friends); whole-tree rules (flag coverage) run once over the context.

The rules encode THIS codebase's real conventions — they are deliberately
narrow. A rule that cannot decide statically stays silent rather than
guessing: a lint gate that cries wolf gets disabled wholesale, which is
worse than a gap.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Iterator

from tpu_pod_exporter.analysis.diagnostics import ERROR, WARNING, Diagnostic

if TYPE_CHECKING:  # pragma: no cover - import cycle guard, types only
    from tpu_pod_exporter.analysis.engine import LintContext


@dataclass(frozen=True)
class Rule:
    name: str
    severity: str
    summary: str
    # (tree, src_lines, relpath, ctx) -> findings; None for tree-wide rules.
    check_file: Callable | None = None
    # (ctx) -> findings; None for per-file rules.
    check_tree: Callable | None = None


# --------------------------------------------------------------- shared AST


def _terminal_name(node: ast.AST) -> str:
    """Last identifier of a Name/Attribute chain (``self._gzip_lock`` ->
    ``_gzip_lock``; ``os.fsync`` -> ``fsync``); "" when not name-like."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _receiver_name(node: ast.AST) -> str:
    """Terminal name of a call's receiver (``json`` in ``json.dumps``)."""
    if isinstance(node, ast.Attribute):
        return _terminal_name(node.value)
    return ""


def _walk_stop_at_defs(body: list[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class
    definitions — code inside a nested ``def`` does not run where it is
    written (e.g. a callback defined under a lock runs after release)."""
    defs = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
    for stmt in body:
        stack: list[ast.AST] = [stmt]
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, defs):
                continue
            stack.extend(ast.iter_child_nodes(node))


def _functions(tree: ast.Module) -> Iterator[ast.FunctionDef | ast.AsyncFunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ------------------------------------------------------------------ lock-io

# Receivers whose ``.write(...)`` means bytes leaving the process (files,
# sockets, the WAL) rather than a dict/list mutation.
_WRITEY_RECEIVERS = {
    "f", "fh", "fp", "file", "wfile", "rfile", "sock", "socket",
    "stdout", "stderr", "wal", "_wal", "conn", "connection",
}
_LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log", "recovery",
}


def _lock_io_offence(call: ast.Call) -> str | None:
    """Why this call is I/O/serialization/logging, or None if it is fine."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id == "open":
            return "open() (file I/O)"
        if fn.id == "print":
            return "print() (stream I/O)"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    recv = _receiver_name(fn)
    if attr in ("dumps", "dump") and recv in ("json", "pickle", "marshal"):
        return f"{recv}.{attr}() (serialization)"
    if attr in ("fsync", "fdatasync"):
        return f"{attr}() (disk flush)"
    if attr == "compress" and recv in ("gzip", "zlib", "bz2", "lzma"):
        return f"{recv}.compress() (compression)"
    if attr == "sendall":
        return "socket sendall() (network I/O)"
    if attr == "sleep" and recv == "time":
        return "time.sleep() (blocking)"
    if attr in _LOG_METHODS and "log" in recv.lower():
        return f"{recv}.{attr}() (logging)"
    if attr == "write" and recv in _WRITEY_RECEIVERS:
        return f"{recv}.write() (stream I/O)"
    return None


def _check_lock_io(tree: ast.Module, src_lines: list[str], relpath: str, ctx: "LintContext") -> list[Diagnostic]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        lock_expr = None
        for item in node.items:
            if "lock" in _terminal_name(item.context_expr).lower():
                lock_expr = item.context_expr
                break
        if lock_expr is None:
            continue
        held = _terminal_name(lock_expr)
        for inner in _walk_stop_at_defs(node.body):
            if isinstance(inner, ast.Call):
                why = _lock_io_offence(inner)
                if why is not None:
                    out.append(Diagnostic(
                        "lock-io", ERROR, relpath, inner.lineno,
                        f"{why} inside `with {held}:` — copy under the lock, "
                        f"serialize/log/flush outside it (PR 1/3/4 "
                        f"copy-then-serialize discipline)",
                    ))
    return out


# -------------------------------------------------------------- metric-name

# A string literal shaped like one of our metric families. The package name
# itself matches the pattern; it (and module paths) are not metrics.
# gpu_ is the GPU device family's node namespace (backend/nvml.py) — it
# resolves against metrics/schema.py exactly like tpu_; the pod_gpu/
# docker_gpu alternatives (the reference's legacy alias names) sort before
# gpu_ so they match whole.
_METRIC_SHAPED = re.compile(r"(?:tpu|pod_gpu|docker_gpu|gpu)_[a-z0-9_]+")
# Non-metric identifiers that happen to match the shape: the package name,
# and gpu_-prefixed config/kwarg names (flags, result-dict keys).
_METRIC_STRING_ALLOWED = {
    "tpu_pod_exporter", "gpu_slices", "gpu_resource_name",
}
# Module-ish strings that happen to match the metric shape.
_METRIC_STRING_ALLOWED_SUFFIXES = ("_pb2", "_pb2_grpc")

# Definition sites: the schema itself and the metrics framework (which
# derives child families for histograms) may construct specs.
_SPEC_DEFINITION_FILES = (
    "tpu_pod_exporter/metrics/schema.py",
    "tpu_pod_exporter/metrics/registry.py",
)


def _check_metric_name(tree: ast.Module, src_lines: list[str], relpath: str, ctx: "LintContext") -> list[Diagnostic]:
    reg = ctx.registry
    out = []
    is_definition_site = relpath in _SPEC_DEFINITION_FILES

    def _check_name_literal(node: ast.Constant) -> None:
        val = node.value
        if (
            _METRIC_SHAPED.fullmatch(val)
            and val not in reg.metric_names
            and val not in _METRIC_STRING_ALLOWED
            and not val.endswith(_METRIC_STRING_ALLOWED_SUFFIXES)
        ):
            out.append(Diagnostic(
                "metric-name", ERROR, relpath, node.lineno,
                f"metric name {val!r} is not registered in "
                f"metrics/schema.py (ALL_SPECS / conditional spec lists) — "
                f"add a MetricSpec there or fix the name",
            ))

    def _check_schema_attr(node: ast.Attribute) -> None:
        # schema.X — X must be a name schema.py actually defines.
        if (
            isinstance(node.value, ast.Name)
            and node.value.id == "schema"
            and node.attr not in reg.schema_names
        ):
            out.append(Diagnostic(
                "metric-name", ERROR, relpath, node.lineno,
                f"schema.{node.attr} does not exist in metrics/schema.py",
            ))

    # Docstrings mention metric names legitimately; skip Expr-statement
    # constants wholesale (they are never a publish argument).
    docstring_lines = {
        s.value.lineno
        for s in ast.walk(tree)
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant)
    }

    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            _check_schema_attr(node)
            continue
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.lineno not in docstring_lines
            and not is_definition_site
        ):
            _check_name_literal(node)
            continue
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not is_definition_site and isinstance(fn, ast.Name) and fn.id in (
            "MetricSpec", "HistogramSpec",
        ):
            out.append(Diagnostic(
                "metric-name", ERROR, relpath, node.lineno,
                f"inline {fn.id}(...) outside metrics/schema.py — every "
                f"family must live in the schema so the exposition surface "
                f"stays reviewable in one place",
            ))
    return out


# --------------------------------------------------------------- wall-clock

# Modules on the monotonic poll path: durations and schedules there must
# come from time.monotonic (or the injected ``clock``); wall time is only
# for stamping (the injected ``wallclock``) at explicitly-marked sites.
_MONOTONIC_MODULES = (
    "tpu_pod_exporter/collector.py",
    "tpu_pod_exporter/supervisor.py",
    "tpu_pod_exporter/history.py",
    "tpu_pod_exporter/trace.py",
)

_WALL_CALLS = {
    ("time", "time"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
    ("datetime", "today"),
    ("date", "today"),
}


def _check_wall_clock(tree: ast.Module, src_lines: list[str], relpath: str, ctx: "LintContext") -> list[Diagnostic]:
    if relpath not in _MONOTONIC_MODULES:
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        if (_receiver_name(fn), fn.attr) in _WALL_CALLS:
            out.append(Diagnostic(
                "wall-clock", ERROR, relpath, node.lineno,
                f"{_receiver_name(fn)}.{fn.attr}() on the monotonic poll "
                f"path — use the injected clock/wallclock, or mark a "
                f"deliberate wall-stamp site with a disable comment",
            ))
    return out


# ------------------------------------------------------------- join-timeout


def _check_join_timeout(tree: ast.Module, src_lines: list[str], relpath: str, ctx: "LintContext") -> list[Diagnostic]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute) or fn.attr != "join":
            continue
        # str.join / os.path.join always take exactly one (non-None)
        # argument, so a zero-arg join can only be Thread/Queue.join —
        # a blocking wait with no deadline.
        blocking = not node.args and not node.keywords
        for kw in node.keywords:
            if kw.arg == "timeout" and (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            ):
                blocking = True
        if len(node.args) == 1 and (
            isinstance(node.args[0], ast.Constant) and node.args[0].value is None
        ):
            blocking = True
        if blocking:
            out.append(Diagnostic(
                "join-timeout", ERROR, relpath, node.lineno,
                "blocking .join() without a timeout — an abandoned/fenced "
                "worker may never return; pass an explicit timeout "
                "(supervisor.py fences, never joins-on-blocking)",
            ))
    return out


# --------------------------------------------------------- thread-discipline


def _check_thread_discipline(tree: ast.Module, src_lines: list[str], relpath: str, ctx: "LintContext") -> list[Diagnostic]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        is_thread = (
            isinstance(fn, ast.Attribute)
            and fn.attr == "Thread"
            and _receiver_name(fn) == "threading"
        ) or (isinstance(fn, ast.Name) and fn.id == "Thread")
        if not is_thread:
            continue
        kwargs = {kw.arg for kw in node.keywords if kw.arg}
        missing = []
        if "name" not in kwargs:
            missing.append("name= (tpu-* convention; /debug/stacks and the "
                           "slow-poll profiler identify threads by name)")
        if "daemon" not in kwargs:
            missing.append("daemon=True (a non-daemon thread blocks "
                           "interpreter exit during SIGTERM drain)")
        if missing:
            out.append(Diagnostic(
                "thread-discipline", ERROR, relpath, node.lineno,
                "threading.Thread(...) missing " + " and ".join(missing),
            ))
    return out


# -------------------------------------------------------------- bare-except


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    for node in _walk_stop_at_defs(handler.body):
        if isinstance(node, ast.Raise) and node.exc is None:
            return True
    return False


def _names_base_exception(type_node: ast.expr | None) -> bool:
    if type_node is None:
        return False
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
    return any(_terminal_name(n) == "BaseException" for n in nodes)


def _check_bare_except(tree: ast.Module, src_lines: list[str], relpath: str, ctx: "LintContext") -> list[Diagnostic]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(Diagnostic(
                "bare-except", ERROR, relpath, node.lineno,
                "bare `except:` swallows KeyboardInterrupt/SystemExit — "
                "catch Exception (or narrower)",
            ))
        elif _names_base_exception(node.type) and not _handler_reraises(node):
            out.append(Diagnostic(
                "bare-except", ERROR, relpath, node.lineno,
                "except BaseException without re-raise — only the "
                "sanctioned poll-restart path may swallow these; re-raise "
                "or record the exception with a disable comment",
            ))
    return out


# --------------------------------------------------------------- debug-gate


def _compares_debug_path(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> int:
    """Line of the first /debug/ route comparison in this function, or 0.

    Only *routing* shapes count — ``x == "/debug/..."`` comparisons and
    ``.startswith("/debug/")`` calls — so log messages that merely mention
    a debug URL never trip the rule."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            operands = [node.left, *node.comparators]
            for op in operands:
                if (
                    isinstance(op, ast.Constant)
                    and isinstance(op.value, str)
                    and op.value.startswith("/debug/")
                ):
                    return node.lineno
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "startswith"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("/debug/")
        ):
            return node.lineno
    return 0


def _check_debug_gate(tree: ast.Module, src_lines: list[str], relpath: str, ctx: "LintContext") -> list[Diagnostic]:
    if relpath.startswith("tpu_pod_exporter/analysis/"):
        return []  # this rule's own "/debug/" pattern literals are data
    out = []
    for fn in _functions(tree):
        if fn.name == "debug_client_allowed":
            continue
        line = _compares_debug_path(fn)
        if not line:
            continue
        gated = any(
            _terminal_name(n) == "debug_client_allowed"
            for n in ast.walk(fn)
            if isinstance(n, (ast.Name, ast.Attribute))
        )
        if not gated:
            out.append(Diagnostic(
                "debug-gate", ERROR, relpath, line,
                f"{fn.name}() routes a /debug/* path without calling "
                f"debug_client_allowed() — debug endpoints are "
                f"loopback-only by default (server.py policy)",
            ))
    return out


# ------------------------------------------------------------ unused-import


def _check_unused_import(tree: ast.Module, src_lines: list[str], relpath: str, ctx: "LintContext") -> list[Diagnostic]:
    if relpath.endswith("__init__.py"):
        return []  # re-export surface: unused-looking imports are the API
    bound: list[tuple[str, int]] = []  # (bound name, line)
    for stmt in tree.body:  # module level only: lazy in-function imports are a
        # deliberate pattern here (gzip, numpy) and always locally used
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                name = alias.asname or alias.name.split(".")[0]
                bound.append((name, stmt.lineno))
        elif isinstance(stmt, ast.ImportFrom):
            if stmt.module == "__future__":
                continue
            for alias in stmt.names:
                if alias.name == "*":
                    continue
                bound.append((alias.asname or alias.name, stmt.lineno))
    if not bound:
        return []
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)  # __all__ entries / string annotations
    return [
        Diagnostic(
            "unused-import", WARNING, relpath, line,
            f"imported name {name!r} is never used in this module",
        )
        for name, line in bound
        if name not in used
    ]


# ------------------------------------------------- flag-read / flag-doc


def _check_flag_read(ctx: "LintContext") -> list[Diagnostic]:
    read_attrs: set[str] = set()
    for relpath, tree in ctx.package_trees.items():
        if relpath.endswith("config.py"):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                read_attrs.add(node.attr)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                # getattr(cfg, "name") / field-name strings count as reads.
                read_attrs.add(node.value)
    return [
        Diagnostic(
            "flag-read", WARNING, ctx.config_relpath, line,
            f"config flag {name!r} is never read anywhere in the package — "
            f"dead knobs mislead operators; wire it up or delete it",
        )
        for name, line in ctx.config_fields
        if name not in read_attrs
    ]


def _check_flag_doc(ctx: "LintContext") -> list[Diagnostic]:
    if not ctx.docs_text:
        return []  # no README/RUNBOOK beside the package (installed wheel)
    out = []
    for name, line in ctx.config_fields:
        flag = "--" + name.replace("_", "-")
        env = "TPE_" + name.upper()
        if flag not in ctx.docs_text and env not in ctx.docs_text:
            out.append(Diagnostic(
                "flag-doc", WARNING, ctx.config_relpath, line,
                f"config flag {flag} (env {env}) is documented in neither "
                f"README.md nor deploy/RUNBOOK.md — add it to the flags "
                f"reference",
            ))
    return out


# ------------------------------------------------ concurrency contracts

# The three whole-tree concurrency rules live in analysis/concurrency.py
# (lock discovery, call graph, held-set propagation — too much machinery
# for this file). Imported lazily so `concurrency` can borrow
# _lock_io_offence from here without a cycle.


def _check_lock_order(ctx: "LintContext") -> list[Diagnostic]:
    from tpu_pod_exporter.analysis import concurrency
    return concurrency.check_lock_order(ctx)


def _check_lock_ownership(ctx: "LintContext") -> list[Diagnostic]:
    from tpu_pod_exporter.analysis import concurrency
    return concurrency.check_lock_ownership(ctx)


def _check_lock_io_chain(ctx: "LintContext") -> list[Diagnostic]:
    from tpu_pod_exporter.analysis import concurrency
    return concurrency.check_lock_io_chain(ctx)


# The three execution-context rule families (loop-blocking,
# durability-ordering, fork-safety) build a derived pass over the same
# concurrency model; same lazy-import discipline.


def _check_loop_blocking(ctx: "LintContext") -> list[Diagnostic]:
    from tpu_pod_exporter.analysis import execcontext
    return execcontext.check_loop_blocking(ctx)


def _check_durability_ordering(ctx: "LintContext") -> list[Diagnostic]:
    from tpu_pod_exporter.analysis import execcontext
    return execcontext.check_durability_ordering(ctx)


def _check_fork_safety(ctx: "LintContext") -> list[Diagnostic]:
    from tpu_pod_exporter.analysis import execcontext
    return execcontext.check_fork_safety(ctx)


# ------------------------------------------------------------------- registry

ALL_RULES: tuple[Rule, ...] = (
    Rule(
        "lock-io", ERROR,
        "No I/O, serialization, compression, or logging inside a "
        "`with <lock>:` block (copy under the lock, work outside it).",
        check_file=_check_lock_io,
    ),
    Rule(
        "metric-name", ERROR,
        "Every metric name reaching registry/publish helpers must be "
        "registered in metrics/schema.py; no inline MetricSpec elsewhere.",
        check_file=_check_metric_name,
    ),
    Rule(
        "wall-clock", ERROR,
        "No time.time()/datetime.now() in monotonic poll-path modules "
        "(collector, supervisor, history, trace) outside marked wall-stamp "
        "sites.",
        check_file=_check_wall_clock,
    ),
    Rule(
        "join-timeout", ERROR,
        "No blocking Thread/Queue .join() without a timeout.",
        check_file=_check_join_timeout,
    ),
    Rule(
        "thread-discipline", ERROR,
        "Every threading.Thread must be named (tpu-* convention) and "
        "daemonized.",
        check_file=_check_thread_discipline,
    ),
    Rule(
        "bare-except", ERROR,
        "No bare `except:`; `except BaseException` must re-raise unless "
        "explicitly sanctioned (poll-restart / worker-relay paths).",
        check_file=_check_bare_except,
    ),
    Rule(
        "debug-gate", ERROR,
        "Any function routing a /debug/* path must call "
        "debug_client_allowed() (loopback-only policy).",
        check_file=_check_debug_gate,
    ),
    Rule(
        "unused-import", WARNING,
        "Module-level imports must be used (ruff F401 equivalent, enforced "
        "even where ruff is unavailable).",
        check_file=_check_unused_import,
    ),
    Rule(
        "flag-read", WARNING,
        "Every flag defined in config.py must be read somewhere in the "
        "package.",
        check_tree=_check_flag_read,
    ),
    Rule(
        "flag-doc", WARNING,
        "Every flag defined in config.py must be documented in README.md "
        "or deploy/RUNBOOK.md.",
        check_tree=_check_flag_doc,
    ),
    Rule(
        "lock-order", ERROR,
        "The whole-tree lock-acquisition order graph must be acyclic, "
        "and no non-reentrant lock may be re-acquired while held "
        "(deadlock candidates; analysis/concurrency.py).",
        check_tree=_check_lock_order,
    ),
    Rule(
        "lock-ownership", ERROR,
        "Declared thread-ownership contracts (one cursor-mover per "
        "buffer, one history appender, flag-checked-under-lock) hold "
        "over the thread-rooted call graph.",
        check_tree=_check_lock_ownership,
    ),
    Rule(
        "lock-io-chain", ERROR,
        "No call chain reachable under a held lock may perform I/O, "
        "serialization, compression, or logging (lock-io, "
        "interprocedural).",
        check_tree=_check_lock_io_chain,
    ),
    Rule(
        "loop-blocking", ERROR,
        "No function running inline on the event loop (role "
        "tpu-exporter-http, propagated through call_soon/call_later/"
        "_invoke) may block: file I/O, time.sleep, compression, "
        "serialization, or locks whose holders block "
        "(analysis/execcontext.py).",
        check_tree=_check_loop_blocking,
    ),
    Rule(
        "durability-ordering", ERROR,
        "State files go through persist.atomic_write; cursor movers are "
        "fsync-reachable before return; each WalBuffer cursor has "
        "exactly one declared mover role (analysis/execcontext.py).",
        check_tree=_check_durability_ordering,
    ),
    Rule(
        "fork-safety", ERROR,
        "No os.fork/multiprocessing outside a sanctioned pre-fork entry; "
        "no import-time thread/fd creation; the pre-fork resource "
        "inventory is committed as deploy/fork-inventory.json "
        "(analysis/execcontext.py).",
        check_tree=_check_fork_safety,
    ),
)

RULES_BY_NAME = {r.name: r for r in ALL_RULES}
