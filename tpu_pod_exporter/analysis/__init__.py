"""Static analysis — the exporter's invariants as machine-checked rules.

The codebase's correctness rests on conventions that no general-purpose
linter knows about: the copy-then-serialize lock discipline (every review
round of PRs 1-4 caught serialization under a lock by eye), the frozen
``metrics/schema.py`` surface (a metric name not in ``ALL_SPECS`` silently
forks the exposition contract), the monotonic-clock rule on the poll path,
the ``tpu-sup-*``/named-daemon thread conventions, and the loopback gate on
``/debug/*``. ``exporter-lint`` encodes each as a named AST rule with
file:line diagnostics, a severity, an inline
``# lint: disable=RULE(reason)`` escape hatch, and a committed baseline for
grandfathered findings — so ``make lint`` is green from day one and every
NEW violation fails CI naming the rule, file, and line.

Usage::

    python -m tpu_pod_exporter.analysis            # lint the package
    python -m tpu_pod_exporter.analysis --demo     # seed + catch a violation
    exporter-lint --format json                    # CI artifact shape

See README.md "Static analysis" for the rule reference and
``deploy/RUNBOOK.md`` for the operator workflow (updating the baseline,
recording an intentional exception).
"""

from __future__ import annotations

from tpu_pod_exporter.analysis.diagnostics import (
    Diagnostic,
    parse_disables,
)
from tpu_pod_exporter.analysis.engine import (
    LintContext,
    lint_package,
    lint_source,
)
from tpu_pod_exporter.analysis.rules import ALL_RULES, Rule

__all__ = [
    "ALL_RULES",
    "Diagnostic",
    "LintContext",
    "Rule",
    "lint_package",
    "lint_source",
    "parse_disables",
]
