"""Whole-tree concurrency contract analysis (lockdep/TSan-style, static).

Three passes over the package AST (never imports the code it checks, same
as the rest of ``exporter-lint``):

1. **Lock discovery** — every ``threading.Lock/RLock/Condition`` creation
   site in the package becomes a named lock identity: instance attributes
   (``persist.WalBuffer._lock``), class attributes
   (``supervisor._Worker._seq_lock``), module globals (``nativelib._lock``)
   and function locals. The creation site (file + statement line range) is
   the join key the runtime witness (:mod:`.witness`) maps its observed
   locks back onto.

2. **Call graph + held-set propagation** — a conservative package call
   graph rooted at thread entry points (``threading.Thread(target=...)``,
   ``ThreadPoolExecutor.submit`` sites, and the declared callback
   registrars in :data:`CALLBACK_ROLES`). Receiver types come from
   constructor assignments, parameter annotations and a
   unique-method-name fallback; anything unresolvable stays unresolved —
   a lint that guesses cries wolf. A fixpoint propagates (a) the set of
   locks that may be held on entry to each function and (b) the set of
   thread roles that may execute it.

3. **Contract checks** —

   * ``lock-order``: the derived acquisition-order graph (edge A -> B =
     some path acquires B while holding A). Cycles are deadlock
     candidates; re-acquiring a non-reentrant lock already held is a
     self-deadlock.
   * ``lock-io-chain``: the PR-5 ``lock-io`` rule deepened from single
     statements to whole call chains — a call made while a lock is held
     whose callee *transitively* performs I/O/serialization/logging.
   * ``lock-ownership``: the declared thread-ownership table
     (:data:`OWNERSHIP`) — "one cursor-mover per buffer" and friends as
     machine-checked rules instead of CHANGES.md prose. Entries may also
     declare a flag that must only ever be read under the instance lock
     (the ``_QueryCache.put`` re-check-under-lock discipline).

The static model is also the reference the runtime witness is checked
against in CI (:func:`cross_check`): an acquisition-order edge observed
while running the test suite that the static graph cannot explain fails
the build, so the model cannot silently rot. Declared-but-dynamic edges
(callback indirection the AST cannot follow) live in
:data:`MODELED_EDGES`, each with its justification.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from fnmatch import fnmatchcase
from typing import TYPE_CHECKING, Iterator

from tpu_pod_exporter.analysis.diagnostics import ERROR, Diagnostic

if TYPE_CHECKING:  # pragma: no cover - import cycle guard, types only
    from tpu_pod_exporter.analysis.engine import LintContext

_PKG = "tpu_pod_exporter"

# threading factory -> lock kind. Condition wraps a lock and acquires it
# via ``with cv:`` — the Condition object IS the modeled lock.
_LOCK_FACTORIES = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

# Method names too generic for the unique-definition fallback: a receiver
# of unknown type calling one of these could just as well be a dict, file,
# queue or socket — resolving them by name would fabricate call edges.
_COMMON_METHODS = frozenset({
    "get", "put", "set", "add", "pop", "read", "write", "close", "open",
    "run", "start", "stop", "send", "recv", "join", "clear", "copy",
    "keys", "values", "items", "update", "append", "extend", "insert",
    "remove", "acquire", "release", "wait", "notify", "notify_all",
    "submit", "result", "cancel", "flush", "seek", "tell", "readline",
    "fileno", "locked", "empty", "full", "qsize", "info", "debug",
    "warning", "error", "exception", "main", "tick", "emit", "format",
    "match", "search", "sub", "split", "strip", "encode", "decode",
    "sort", "index", "count", "exists", "name", "is_set", "popleft",
    "popitem", "setdefault", "discard", "replace", "lower", "upper",
})


# --------------------------------------------------------------- declarations


@dataclass(frozen=True)
class CallbackRole:
    """A registration method whose callable argument later runs on a
    specific thread — flow the AST cannot follow, declared instead."""

    method: str               # qualname of the registrar
    arg_indices: tuple[int, ...]
    roles: tuple[str, ...]    # thread role(s) the callable runs on
    reason: str


CALLBACK_ROLES: tuple[CallbackRole, ...] = (
    CallbackRole(
        "pressure.PressureGovernor.add_disk_rung", (1, 2),
        ("tpu-exporter-pressure",),
        "ladder rung apply/recover callables run on the governor check "
        "thread (pressure.PressureGovernor._run -> tick)",
    ),
    CallbackRole(
        "pressure.PressureGovernor.add_memory_rung", (1, 2),
        ("tpu-exporter-pressure",),
        "memory-ladder rungs run on the governor check thread",
    ),
    CallbackRole(
        "pressure.PressureGovernor.register_memory_component", (1,),
        ("tpu-exporter-pressure",),
        "component byte accounting is read each governor tick",
    ),
    CallbackRole(
        "server._WorkerPool.submit", (0,),
        ("tpu-exporter-http-worker-*",),
        "submitted task closures execute on an elastic pool worker "
        "(server._WorkerPool._run)",
    ),
    CallbackRole(
        "server._EventLoopServer.call_soon", (0,),
        ("tpu-exporter-http",),
        "posted callbacks run inline on the selectors-loop thread "
        "(server._EventLoopServer._run_pending) — the loop-blocking "
        "rule's role seed for worker->loop handoffs",
    ),
    CallbackRole(
        "server._EventLoopServer.call_later", (1,),
        ("tpu-exporter-http",),
        "timer callbacks fire inline on the loop thread "
        "(server._EventLoopServer._run_timers)",
    ),
    CallbackRole(
        "server._EventLoopServer._invoke", (1,),
        ("tpu-exporter-http",),
        "the loop-dispatch choke point: everything handed to it runs "
        "inline on the loop thread (the loop-stall witness times the "
        "same seam at runtime)",
    ),
    CallbackRole(
        "supervisor.SourceSupervisor._submit", (0,),
        ("tpu-sup-*",),
        "supervised phase callables execute on the per-source fenced "
        "worker (supervisor._Worker._run)",
    ),
)


@dataclass(frozen=True)
class OwnershipRule:
    """Which thread roles may execute a function. ``allowed`` entries are
    fnmatch patterns over thread-role names; ``("*",)`` means any thread,
    used when the entry exists only for its ``guarded_flag`` check."""

    func: str                      # qualname, e.g. "persist.WalBuffer._advance"
    allowed: tuple[str, ...]
    reason: str
    # Attribute that must ONLY be read inside a ``with self.<lock>:`` block
    # within this function (the flag-checked-under-lock discipline).
    guarded_flag: str | None = None


# The thread-ownership table: CHANGES.md prose contracts as checkable
# rules. Roles are thread names (thread-discipline guarantees every spawn
# is named); "pool:*" roles come from ThreadPoolExecutor submit sites.
OWNERSHIP: tuple[OwnershipRule, ...] = (
    # WalBuffer has exactly ONE cursor-mover per instance. The egress
    # buffer's mover is the sender thread; the alert notification
    # buffer's mover is the alert sender thread (alerting.AlertNotifier,
    # same seat one subsystem over); the fleet store's tier buffers
    # are moved by the root round (appender) thread — which is the poll
    # thread driving SliceAggregator.poll_once. A governor-thread move
    # racing the appender was PR 11's bug class; the governor may only
    # flip flags (set_thin / _disk_pressure) that the owning thread acts
    # on at its next pass.
    OwnershipRule(
        "persist.WalBuffer._advance",
        ("tpu-egress-sender", "tpu-alert-sender", "tpu-exporter-poll"),
        "single cursor-mover per buffer: the egress sender owns the "
        "egress buffer cursor, the alert sender the alert notification "
        "cursor, the root round thread the store tier cursors; a "
        "governor/HTTP-thread advance racing the owner could regress "
        "the on-disk cursor and resurrect shed records at boot",
    ),
    OwnershipRule(
        "persist.WalBuffer.trim_to_bytes",
        ("tpu-egress-sender", "tpu-alert-sender", "tpu-exporter-poll"),
        "cap trims are cursor moves (see WalBuffer._advance)",
    ),
    OwnershipRule(
        "persist.WalBuffer.ack",
        ("tpu-egress-sender", "tpu-alert-sender", "tpu-exporter-poll"),
        "acks are cursor moves (see WalBuffer._advance)",
    ),
    OwnershipRule(
        "persist.WalBuffer.drop_oldest",
        ("tpu-egress-sender", "tpu-alert-sender", "tpu-exporter-poll"),
        "age/byte-cap drops are cursor moves (see WalBuffer._advance)",
    ),
    OwnershipRule(
        "egress.RemoteWriteShipper._enforce_caps",
        ("tpu-egress-sender",),
        "backlog caps shed via the cursor; only the one thread that "
        "moves the ack cursor may run them (egress.py single-consumer "
        "discipline)",
    ),
    OwnershipRule(
        "history.HistoryStore.append_snapshot",
        ("tpu-exporter-poll",),
        "node history has one appender: the poll thread. HTTP readers "
        "copy under the lock; a second appender would interleave ring "
        "writes between tiers",
    ),
    OwnershipRule(
        "store.FleetStore.append_snapshot",
        ("tpu-exporter-poll",),
        "the root store's appender is the round thread (store.py thread "
        "contract); queries copy out under the lock",
    ),
    OwnershipRule(
        "store.FleetStore.append_samples",
        ("tpu-exporter-poll",),
        "see FleetStore.append_snapshot",
    ),
    OwnershipRule(
        "fleet._QueryCache.put",
        ("*",),
        "any thread may put, but the enabled flag must be re-checked "
        "inside the lock: a put racing the memory ladder's "
        "set_enabled(False)+clear must not resurrect a stale entry in a "
        "disabled cache (PR 10 regression class)",
        guarded_flag="_enabled",
    ),
)


@dataclass(frozen=True)
class ModeledEdge:
    """A declared lock-order edge the AST cannot derive (callback or
    data-driven indirection) but the runtime witness may observe."""

    src: str
    dst: str
    reason: str


MODELED_EDGES: tuple[ModeledEdge, ...] = ()


# -------------------------------------------------------------------- model


@dataclass(frozen=True)
class LockInfo:
    key: str        # "persist.WalBuffer._lock", "nativelib._lock", ...
    kind: str       # lock | rlock | condition
    path: str       # repo-relative
    line: int       # creation statement first line
    end_line: int


@dataclass
class _Acquire:
    key: str
    line: int
    held: frozenset[str]    # locks locally held at this acquire


@dataclass
class _CallSite:
    node: ast.Call
    line: int
    held: frozenset[str]
    callees: tuple[str, ...] = ()


@dataclass
class _FuncInfo:
    qualname: str
    relpath: str
    mod: str
    cls: str | None
    node: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda
    acquires: list[_Acquire] = field(default_factory=list)
    calls: list[_CallSite] = field(default_factory=list)
    # (line, why) direct I/O offences — used transitively for chains.
    io: list[tuple[int, str]] = field(default_factory=list)
    local_types: dict[str, tuple[str, str]] = field(default_factory=dict)
    local_funcs: dict[str, str] = field(default_factory=dict)
    local_locks: dict[str, str] = field(default_factory=dict)


@dataclass
class _ClassInfo:
    mod: str
    name: str
    node: ast.ClassDef
    relpath: str
    base_exprs: list[ast.expr] = field(default_factory=list)
    bases: list[tuple[str, str]] = field(default_factory=list)
    methods: dict[str, str] = field(default_factory=dict)   # name -> qualname
    locks: dict[str, str] = field(default_factory=dict)     # attr -> lock key
    attr_types: dict[str, tuple[str, str]] = field(default_factory=dict)


@dataclass
class _ModuleInfo:
    mod: str
    relpath: str
    tree: ast.Module
    # local name -> ("module", mod) | ("member", (mod, name))
    imports: dict[str, tuple[str, object]] = field(default_factory=dict)
    functions: dict[str, str] = field(default_factory=dict)  # name -> qualname
    classes: dict[str, str] = field(default_factory=dict)
    locks: dict[str, str] = field(default_factory=dict)      # global -> key


@dataclass(frozen=True)
class OrderEdge:
    src: str
    dst: str
    func: str      # function whose acquire created the edge
    path: str
    line: int


@dataclass
class ThreadRoot:
    role: str
    func: str      # entry function qualname
    path: str
    line: int
    via: str       # "thread" | "pool" | "callback"


class ConcurrencyModel:
    """The analyzed whole-package concurrency state."""

    def __init__(self) -> None:
        self.locks: dict[str, LockInfo] = {}
        self.functions: dict[str, _FuncInfo] = {}
        self.classes: dict[tuple[str, str], _ClassInfo] = {}
        self.modules: dict[str, _ModuleInfo] = {}
        self.roots: list[ThreadRoot] = []
        self.edges: dict[tuple[str, str], OrderEdge] = {}
        self.entry_held: dict[str, set[str]] = {}
        # func -> role -> (caller qualname | None, path, line)
        self.roles: dict[str, dict[str, tuple[str | None, str, int]]] = {}
        self.findings: list[Diagnostic] = []
        self.unresolved_acquires: list[tuple[str, str, int]] = []
        self.subclasses: dict[tuple[str, str], list[tuple[str, str]]] = {}

    # ------------------------------------------------------------- queries

    def lock_at(self, path: str, line: int) -> LockInfo | None:
        """Creation-site lookup for the witness cross-check: the witness
        records the frame of the ``threading.Lock()`` call, which sits
        inside the assignment statement the static pass recorded."""
        for lk in self.locks.values():
            if lk.path == path and lk.line <= line <= lk.end_line:
                return lk
        return None

    def role_chain(self, func: str, role: str) -> list[tuple[str, str, int]]:
        """Call chain (qualname, path, line) from the thread root of
        ``role`` down to ``func``, for diagnostics."""
        chain: list[tuple[str, str, int]] = []
        cur: str | None = func
        seen = set()
        while cur is not None and cur not in seen:
            seen.add(cur)
            prov = self.roles.get(cur, {}).get(role)
            if prov is None:
                break
            caller, path, line = prov
            chain.append((cur, path, line))
            cur = caller
        chain.reverse()
        return chain

    # ------------------------------------------------------------- exports

    def graph_json(self) -> dict:
        """The reviewable lock-graph artifact. Node/edge identity is the
        stable lock NAME, and via/site carry qualnames + paths WITHOUT
        line numbers, so the committed artifact churns only when the
        concurrency structure actually changes — never on unrelated line
        drift. Diagnostics (the lint findings) keep exact file:line."""
        roles_by_func: dict[str, list[str]] = {
            fq: sorted(r) for fq, r in self.roles.items() if r
        }
        return {
            "comment": (
                "Rendered by `python -m tpu_pod_exporter.analysis "
                "--lock-graph`. Reviewed artifact: an edge A->B means "
                "some path acquires B while holding A; cycles would be "
                "deadlock candidates and fail exporter-lint."
            ),
            "locks": [
                {"key": k.key, "kind": k.kind, "path": k.path}
                for k in sorted(self.locks.values(), key=lambda k: k.key)
            ],
            "edges": [
                {
                    "from": e.src, "to": e.dst,
                    "via": f"{e.func} ({e.path})",
                }
                for _, e in sorted(self.edges.items())
            ],
            "modeled_edges": [
                {"from": m.src, "to": m.dst, "reason": m.reason}
                for m in MODELED_EDGES
            ],
            "thread_roots": [
                dict(t) for t in sorted({
                    (("role", r.role), ("entry", r.func),
                     ("via", r.via), ("site", r.path))
                    for r in self.roots
                })
            ],
            "ownership": [
                {
                    "func": o.func, "allowed": list(o.allowed),
                    "reason": o.reason,
                    **({"guarded_flag": o.guarded_flag}
                       if o.guarded_flag else {}),
                }
                for o in OWNERSHIP
            ],
            "function_roles": {
                fq: roles_by_func[fq] for fq in sorted(roles_by_func)
            },
        }

    def graph_dot(self) -> str:
        lines = [
            "// Lock-acquisition order graph "
            "(exporter-lint --lock-graph-dot)",
            "digraph lock_order {",
            "  rankdir=LR;",
            '  node [shape=box, fontname="monospace", fontsize=10];',
        ]
        used = {e.src for e in self.edges.values()}
        used |= {e.dst for e in self.edges.values()}
        for key in sorted(used):
            kind = self.locks[key].kind if key in self.locks else "?"
            lines.append(f'  "{key}" [label="{key}\\n({kind})"];')
        # Labels carry the path only: the .dot is committed and
        # freshness-gated alongside the JSON, so line numbers would make
        # it churn on unrelated line drift (exact file:line lives in the
        # lint diagnostics, not the reviewed artifact).
        for (src, dst), e in sorted(self.edges.items()):
            lines.append(
                f'  "{src}" -> "{dst}" [label="{e.path}"];'
            )
        for m in MODELED_EDGES:
            lines.append(
                f'  "{m.src}" -> "{m.dst}" '
                f'[style=dashed, label="declared"];'
            )
        lines.append("}")
        return "\n".join(lines) + "\n"


# --------------------------------------------------------------- AST helpers


def _mod_name(relpath: str) -> str:
    """tpu_pod_exporter/metrics/registry.py -> "metrics.registry"."""
    parts = relpath.split("/")
    if parts and parts[0] == _PKG:
        parts = parts[1:]
    if parts and parts[-1] == "__init__.py":
        parts = parts[:-1]
    elif parts:
        parts[-1] = parts[-1][:-3]
    return ".".join(parts) if parts else _PKG


def _terminal(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _lock_factory_kind(call: ast.Call) -> str | None:
    """"lock"/"rlock"/"condition" when ``call`` constructs a threading
    primitive (``threading.Lock()`` or a bare imported ``Lock()``)."""
    fn = call.func
    name = _terminal(fn)
    if name not in _LOCK_FACTORIES:
        return None
    if isinstance(fn, ast.Attribute):
        if not (isinstance(fn.value, ast.Name)
                and fn.value.id == "threading"):
            return None
    return _LOCK_FACTORIES[name]


def _annotation_class(node: ast.expr | None) -> str | None:
    """Best-effort class name out of an annotation: ``WalBuffer``,
    ``"WalBuffer"``, ``Optional[WalBuffer]``, ``WalBuffer | None``."""
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value.split("|")[0].strip()
        text = text.split("[")[-1].rstrip("]").strip()
        return text.split(".")[-1] or None
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        return _annotation_class(node.slice)
    if isinstance(node, ast.BinOp):        # X | None
        return _annotation_class(node.left)
    return None


_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _iter_no_defs(node: ast.AST) -> Iterator[ast.AST]:
    """``node`` plus descendants, never descending into defs/lambdas —
    their bodies run elsewhere and are analyzed as their own
    functions."""
    stack: list[ast.AST] = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _DEFS):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _stmt_parts(
    stmt: ast.stmt,
) -> tuple[list[ast.expr], list[list[ast.stmt]]]:
    """One level of a statement: its expression operands and its nested
    statement lists (if/for/while/try bodies, match cases)."""
    exprs: list[ast.expr] = []
    lists: list[list[ast.stmt]] = []
    for _, value in ast.iter_fields(stmt):
        if isinstance(value, list):
            if not value:
                continue
            head = value[0]
            if isinstance(head, ast.stmt):
                lists.append(value)
            elif isinstance(head, ast.ExceptHandler):
                for h in value:
                    lists.append(h.body)
            elif isinstance(head, ast.expr):
                exprs.extend(value)
            elif hasattr(ast, "match_case") and isinstance(
                    head, ast.match_case):
                for mc in value:
                    lists.append(mc.body)
                    if mc.guard is not None:
                        exprs.append(mc.guard)
        elif isinstance(value, ast.expr):
            exprs.append(value)
    return exprs, lists


def _io_offence(call: ast.Call) -> str | None:
    # Shared predicate with the statement-level lock-io rule; imported
    # lazily to keep the rules <-> concurrency import acyclic.
    from tpu_pod_exporter.analysis.rules import _lock_io_offence
    return _lock_io_offence(call)


# ----------------------------------------------------------------- builder


class _Builder:
    def __init__(
        self,
        package_trees: dict[str, ast.Module],
        ownership: tuple[OwnershipRule, ...] | None = None,
    ) -> None:
        self.m = ConcurrencyModel()
        self.trees = package_trees
        self.ownership = OWNERSHIP if ownership is None else ownership
        # method name -> [(mod, Class)] for the unique-definition fallback
        self._method_index: dict[str, list[tuple[str, str]]] = {}

    def build(self) -> ConcurrencyModel:
        for relpath, tree in sorted(self.trees.items()):
            self._index_module(relpath, tree)
        self._resolve_bases()
        for fq in list(self.m.functions):
            self._summarize(self.m.functions[fq])
        self._resolve_calls()
        self._discover_roots()
        self._propagate()
        self._derive_edges()
        self._check_order_cycles()
        self._check_io_chains()
        self._check_ownership()
        self.m.findings.sort(key=lambda d: (d.path, d.line, d.rule))
        return self.m

    # ------------------------------------------------------ pass 1: index

    def _index_module(self, relpath: str, tree: ast.Module) -> None:
        mod = _mod_name(relpath)
        mi = _ModuleInfo(mod=mod, relpath=relpath, tree=tree)
        self.m.modules[mod] = mi
        for stmt in tree.body:
            self._index_top_stmt(mi, stmt)
            # Imports inside ``if TYPE_CHECKING:`` / try blocks still bind
            # names the annotations and calls refer to.
            if isinstance(stmt, (ast.If, ast.Try)):
                for _, sub in ast.iter_fields(stmt):
                    if isinstance(sub, list):
                        for s in sub:
                            if isinstance(s, (ast.Import, ast.ImportFrom)):
                                self._index_top_stmt(mi, s)
                            elif isinstance(s, ast.ExceptHandler):
                                for hs in s.body:
                                    if isinstance(hs, (ast.Import,
                                                       ast.ImportFrom)):
                                        self._index_top_stmt(mi, hs)

    def _index_top_stmt(self, mi: _ModuleInfo, stmt: ast.stmt) -> None:
        mod = mi.mod
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                if alias.name.startswith(_PKG + "."):
                    sub = alias.name[len(_PKG) + 1:]
                    local = alias.asname or alias.name.split(".")[0]
                    mi.imports[local] = ("module", sub)
        elif isinstance(stmt, ast.ImportFrom):
            src = stmt.module or ""
            if src == _PKG:
                for alias in stmt.names:
                    mi.imports[alias.asname or alias.name] = \
                        ("module", alias.name)
            elif src.startswith(_PKG + "."):
                sub = src[len(_PKG) + 1:]
                for alias in stmt.names:
                    mi.imports[alias.asname or alias.name] = \
                        ("member", (sub, alias.name))
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fq = f"{mod}.{stmt.name}"
            mi.functions[stmt.name] = fq
            self.m.functions[fq] = _FuncInfo(
                qualname=fq, relpath=mi.relpath, mod=mod, cls=None,
                node=stmt)
        elif isinstance(stmt, ast.ClassDef):
            self._index_class(mi, stmt)
        elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            if isinstance(value, ast.Call):
                kind = _lock_factory_kind(value)
                if kind is not None:
                    for t in targets:
                        if isinstance(t, ast.Name):
                            key = f"{mod}.{t.id}"
                            self.m.locks[key] = LockInfo(
                                key, kind, mi.relpath, stmt.lineno,
                                getattr(stmt, "end_lineno", stmt.lineno)
                                or stmt.lineno)
                            mi.locks[t.id] = key

    def _index_class(self, mi: _ModuleInfo, node: ast.ClassDef) -> None:
        mod = mi.mod
        ci = _ClassInfo(mod=mod, name=node.name, node=node,
                        relpath=mi.relpath)
        self.m.classes[(mod, node.name)] = ci
        mi.classes[node.name] = node.name
        ci.base_exprs = list(node.bases)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fq = f"{mod}.{node.name}.{stmt.name}"
                ci.methods[stmt.name] = fq
                self.m.functions[fq] = _FuncInfo(
                    qualname=fq, relpath=mi.relpath, mod=mod,
                    cls=node.name, node=stmt)
                self._method_index.setdefault(stmt.name, []).append(
                    (mod, node.name))
                self._index_attr_sites(ci, stmt)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                value = stmt.value
                if isinstance(value, ast.Call):
                    kind = _lock_factory_kind(value)
                    if kind is not None:
                        for t in targets:
                            if isinstance(t, ast.Name):
                                key = f"{mod}.{node.name}.{t.id}"
                                self.m.locks[key] = LockInfo(
                                    key, kind, mi.relpath, stmt.lineno,
                                    getattr(stmt, "end_lineno",
                                            stmt.lineno) or stmt.lineno)
                                ci.locks[t.id] = key
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name):
                    cls_name = _annotation_class(stmt.annotation)
                    # Dataclass-style lock fields:
                    # ``lock: threading.Lock = field(default_factory=
                    # threading.Lock)`` — created at instantiation time
                    # (inside dataclasses.py, invisible to the runtime
                    # witness) but a real lock for the order graph.
                    if cls_name in _LOCK_FACTORIES:
                        key = f"{mod}.{node.name}.{stmt.target.id}"
                        self.m.locks[key] = LockInfo(
                            key, _LOCK_FACTORIES[cls_name], mi.relpath,
                            stmt.lineno,
                            getattr(stmt, "end_lineno", stmt.lineno)
                            or stmt.lineno)
                        ci.locks[stmt.target.id] = key
                    elif cls_name:
                        ci.attr_types.setdefault(
                            stmt.target.id, ("?", cls_name))

    def _index_attr_sites(
        self, ci: _ClassInfo,
        fn: ast.FunctionDef | ast.AsyncFunctionDef,
    ) -> None:
        """``self.X = <lock factory>()`` / ``self.X = C(...)`` / annotated
        params assigned to attributes — lock identities and attr types."""
        param_types: dict[str, str] = {}
        args = fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            cls_name = _annotation_class(a.annotation)
            if cls_name:
                param_types[a.arg] = cls_name
        for stmt in ast.walk(fn):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            value = stmt.value
            for t in targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                attr = t.attr
                if isinstance(stmt, ast.AnnAssign):
                    cls_name = _annotation_class(stmt.annotation)
                    if cls_name:
                        ci.attr_types.setdefault(attr, ("?", cls_name))
                if isinstance(value, ast.Call):
                    kind = _lock_factory_kind(value)
                    if kind is not None:
                        if attr not in ci.locks:
                            key = f"{ci.mod}.{ci.name}.{attr}"
                            self.m.locks[key] = LockInfo(
                                key, kind, ci.relpath, stmt.lineno,
                                getattr(stmt, "end_lineno", stmt.lineno)
                                or stmt.lineno)
                            ci.locks[attr] = key
                    else:
                        ctor = self._class_of_call(ci.mod, value)
                        if ctor is not None:
                            ci.attr_types[attr] = ctor
                elif (isinstance(value, ast.Name)
                        and value.id in param_types):
                    resolved = self._resolve_class_name(
                        ci.mod, param_types[value.id])
                    if resolved is not None:
                        ci.attr_types[attr] = resolved

    # --------------------------------------------------- name resolution

    def _class_of_call(
        self, mod: str, call: ast.Call
    ) -> tuple[str, str] | None:
        """(mod, Class) when ``call`` constructs a package class."""
        fn = call.func
        if isinstance(fn, ast.Name):
            return self._resolve_class_name(mod, fn.id)
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            target = self._resolve_name(mod, fn.value.id)
            if target is not None and target[0] == "module":
                other = self.m.modules.get(str(target[1]))
                if other is not None and fn.attr in other.classes:
                    return (other.mod, fn.attr)
        return None

    def _resolve_name(
        self, mod: str, name: str
    ) -> tuple[str, object] | None:
        mi = self.m.modules.get(mod)
        if mi is None:
            return None
        if name in mi.classes:
            return ("class", (mod, name))
        if name in mi.functions:
            return ("func", mi.functions[name])
        imp = mi.imports.get(name)
        if imp is None:
            return None
        tag, payload = imp
        if tag == "module":
            return ("module", payload)
        src_mod, member = payload  # type: ignore[misc]
        other = self.m.modules.get(src_mod)
        if other is None:
            return None
        if member in other.classes:
            return ("class", (src_mod, member))
        if member in other.functions:
            return ("func", other.functions[member])
        if member in other.locks:
            return ("lock", other.locks[member])
        return None

    def _resolve_class_name(
        self, mod: str, name: str
    ) -> tuple[str, str] | None:
        r = self._resolve_name(mod, name)
        if r is not None and r[0] == "class":
            return r[1]  # type: ignore[return-value]
        # Unique class name across the package (covers TYPE_CHECKING-only
        # imports and string annotations).
        hits = [k for k in self.m.classes if k[1] == name]
        return hits[0] if len(hits) == 1 else None

    def _resolve_bases(self) -> None:
        for ci in self.m.classes.values():
            for b in ci.base_exprs:
                resolved: tuple[str, str] | None = None
                if isinstance(b, ast.Name):
                    resolved = self._resolve_class_name(ci.mod, b.id)
                elif isinstance(b, ast.Attribute):
                    fake = ast.Call(func=b, args=[], keywords=[])
                    resolved = self._class_of_call(ci.mod, fake)
                if resolved is not None:
                    ci.bases.append(resolved)
                    self.m.subclasses.setdefault(resolved, []).append(
                        (ci.mod, ci.name))

    def _mro(self, key: tuple[str, str]) -> list[_ClassInfo]:
        out: list[_ClassInfo] = []
        seen: set[tuple[str, str]] = set()
        stack = [key]
        while stack:
            k = stack.pop(0)
            if k in seen:
                continue
            seen.add(k)
            ci = self.m.classes.get(k)
            if ci is None:
                continue
            out.append(ci)
            stack.extend(ci.bases)
        return out

    def _descendants(self, key: tuple[str, str]) -> list[tuple[str, str]]:
        out: list[tuple[str, str]] = []
        stack = list(self.m.subclasses.get(key, ()))
        seen: set[tuple[str, str]] = set()
        while stack:
            k = stack.pop()
            if k in seen:
                continue
            seen.add(k)
            out.append(k)
            stack.extend(self.m.subclasses.get(k, ()))
        return out

    # --------------------------------------- pass 2: function summaries

    def _summarize(self, fi: _FuncInfo) -> None:
        node = fi.node
        if isinstance(node, ast.Lambda):
            body: list[ast.stmt] = [ast.Expr(value=node.body)]
        else:
            body = node.body
            for a in (node.args.posonlyargs + node.args.args
                      + node.args.kwonlyargs):
                cls_name = _annotation_class(a.annotation)
                if cls_name:
                    resolved = self._resolve_class_name(fi.mod, cls_name)
                    if resolved is not None:
                        fi.local_types[a.arg] = resolved
        self._register_nested(fi, body)
        self._walk_events(fi, body, frozenset())

    def _register_nested(self, fi: _FuncInfo, body: list[ast.stmt]) -> None:
        """Nested defs become their own functions, callable via local
        name (closures handed to Thread targets / submit)."""
        stack: list[ast.AST] = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fq = f"{fi.qualname}.<{n.name}>"
                child = _FuncInfo(
                    qualname=fq, relpath=fi.relpath, mod=fi.mod,
                    cls=fi.cls, node=n)
                # Closures read the enclosing frame's bindings.
                child.local_types = dict(fi.local_types)
                child.local_locks = dict(fi.local_locks)
                child.local_funcs = fi.local_funcs
                self.m.functions[fq] = child
                fi.local_funcs[n.name] = fq
                self._register_nested(child, n.body)
                self._walk_events(child, n.body, frozenset())
                continue
            if isinstance(n, ast.Lambda):
                # Lambdas are functions too: a ``lambda: self._respond(..)``
                # handed through an UNRESOLVED registrar (hub.subscribe's
                # writer=) still contains call_soon registrations whose
                # callbacks the loop runs — skipping the body here would
                # leave those callbacks role-less, and the loop-stall
                # witness would observe functions the static model cannot
                # explain. Same identity scheme as _callable_arg_targets.
                fq = f"{fi.qualname}.<lambda@{n.lineno}>"
                if fq not in self.m.functions:
                    child = _FuncInfo(
                        qualname=fq, relpath=fi.relpath, mod=fi.mod,
                        cls=fi.cls, node=n)
                    child.local_types = dict(fi.local_types)
                    child.local_locks = dict(fi.local_locks)
                    child.local_funcs = fi.local_funcs
                    self.m.functions[fq] = child
                    body_stmt: list[ast.stmt] = [ast.Expr(value=n.body)]
                    self._register_nested(child, body_stmt)
                    self._walk_events(child, body_stmt, frozenset())
                continue
            if isinstance(n, ast.ClassDef):
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _walk_events(
        self, fi: _FuncInfo, body: list[ast.stmt], held: frozenset[str]
    ) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in stmt.items:
                    self._scan_expr(fi, item.context_expr, held)
                    key = self._lock_expr_key(fi, item.context_expr)
                    if key is not None:
                        fi.acquires.append(_Acquire(
                            key, item.context_expr.lineno, new_held))
                        new_held = new_held | {key}
                    else:
                        name = _terminal(item.context_expr)
                        if ("lock" in name.lower()
                                or name.lstrip("_") in ("cv", "cond")):
                            self.m.unresolved_acquires.append(
                                (fi.qualname, fi.relpath,
                                 item.context_expr.lineno))
                self._walk_events(fi, stmt.body, new_held)
                continue
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                self._index_local_assign(fi, stmt)
            exprs, stmt_lists = _stmt_parts(stmt)
            for e in exprs:
                self._scan_expr(fi, e, held)
            for sub in stmt_lists:
                self._walk_events(fi, sub, held)

    def _index_local_assign(
        self, fi: _FuncInfo, stmt: ast.Assign | ast.AnnAssign
    ) -> None:
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        value = stmt.value
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names:
            return
        if isinstance(value, ast.Call):
            kind = _lock_factory_kind(value)
            if kind is not None:
                for name in names:
                    key = f"{fi.qualname}.<{name}>"
                    if key not in self.m.locks:
                        self.m.locks[key] = LockInfo(
                            key, kind, fi.relpath, stmt.lineno,
                            getattr(stmt, "end_lineno", stmt.lineno)
                            or stmt.lineno)
                    fi.local_locks[name] = key
                return
            ctor = self._class_of_call(fi.mod, value)
            if ctor is not None:
                for name in names:
                    fi.local_types[name] = ctor
        elif (isinstance(value, ast.Attribute)
                and isinstance(value.value, ast.Name)
                and value.value.id in ("self", "cls")
                and fi.cls is not None):
            # ``st = self.state`` — the typed-attribute alias idiom the
            # server request paths use.
            t = self._attr_type((fi.mod, fi.cls), value.attr)
            if t is not None:
                for name in names:
                    fi.local_types[name] = t
        if isinstance(stmt, ast.AnnAssign):
            cls_name = _annotation_class(stmt.annotation)
            if cls_name:
                resolved = self._resolve_class_name(fi.mod, cls_name)
                if resolved:
                    for name in names:
                        fi.local_types.setdefault(name, resolved)

    def _scan_expr(
        self, fi: _FuncInfo, expr: ast.expr, held: frozenset[str]
    ) -> None:
        for n in _iter_no_defs(expr):
            if isinstance(n, ast.Call):
                fi.calls.append(_CallSite(node=n, line=n.lineno, held=held))
                why = _io_offence(n)
                if why is not None:
                    fi.io.append((n.lineno, why))

    def _lock_expr_key(self, fi: _FuncInfo, expr: ast.expr) -> str | None:
        """Resolve a ``with`` context expression to a lock key."""
        if isinstance(expr, ast.Name):
            if expr.id in fi.local_locks:
                return fi.local_locks[expr.id]
            mi = self.m.modules.get(fi.mod)
            if mi is not None and expr.id in mi.locks:
                return mi.locks[expr.id]
            r = self._resolve_name(fi.mod, expr.id)
            if r is not None and r[0] == "lock":
                return str(r[1])
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        base = expr.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            if fi.cls is None:
                return None
            for ci in self._mro((fi.mod, fi.cls)):
                if expr.attr in ci.locks:
                    return ci.locks[expr.attr]
            return None
        if isinstance(base, ast.Name):
            t = fi.local_types.get(base.id)
            if t is not None:
                for ci in self._mro(t):
                    if expr.attr in ci.locks:
                        return ci.locks[expr.attr]
                return None
            r = self._resolve_name(fi.mod, base.id)
            if r is None:
                return None
            if r[0] == "class":
                for ci in self._mro(r[1]):  # type: ignore[arg-type]
                    if expr.attr in ci.locks:
                        return ci.locks[expr.attr]
            elif r[0] == "module":
                other = self.m.modules.get(str(r[1]))
                if other is not None and expr.attr in other.locks:
                    return other.locks[expr.attr]
            return None
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and fi.cls is not None):
            # self.X.Y — lock attribute of a typed instance attribute.
            t = self._attr_type((fi.mod, fi.cls), base.attr)
            if t is not None:
                for tci in self._mro(t):
                    if expr.attr in tci.locks:
                        return tci.locks[expr.attr]
        return None

    def _attr_type(
        self, cls_key: tuple[str, str], attr: str
    ) -> tuple[str, str] | None:
        for ci in self._mro(cls_key):
            t = ci.attr_types.get(attr)
            if t is None:
                continue
            if t[0] != "?":
                return t
            resolved = self._resolve_class_name(ci.mod, t[1])
            if resolved is not None:
                return resolved
        return None

    # ------------------------------------------- pass 3: call resolution

    def _resolve_calls(self) -> None:
        for fi in list(self.m.functions.values()):
            for cs in fi.calls:
                cs.callees = tuple(self._callees_of(fi, cs.node))

    def _method_on(
        self, cls_key: tuple[str, str], name: str, virtual: bool = True
    ) -> list[str]:
        out = []
        for ci in self._mro(cls_key):
            if name in ci.methods:
                out.append(ci.methods[name])
                break
        if virtual:
            # Dynamic dispatch: a subclass override may run instead (the
            # leaf/root aggregator hooks) — include them for completeness.
            for sub in self._descendants(cls_key):
                sci = self.m.classes.get(sub)
                if sci is not None and name in sci.methods:
                    out.append(sci.methods[name])
        return out

    def _ctor_of(self, cls_key: tuple[str, str]) -> list[str]:
        return self._method_on(cls_key, "__init__", virtual=False)

    def _receiver_type(
        self, fi: _FuncInfo, base: ast.expr
    ) -> tuple[str, str] | None:
        if isinstance(base, ast.Name):
            if base.id in ("self", "cls") and fi.cls is not None:
                return (fi.mod, fi.cls)
            if base.id in fi.local_types:
                return fi.local_types[base.id]
            r = self._resolve_name(fi.mod, base.id)
            if r is not None and r[0] == "class":
                return r[1]  # type: ignore[return-value]
            return None
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id in ("self", "cls")
                and fi.cls is not None):
            return self._attr_type((fi.mod, fi.cls), base.attr)
        if isinstance(base, ast.Call):
            return self._class_of_call(fi.mod, base)
        return None

    def _callees_of(self, fi: _FuncInfo, call: ast.Call) -> list[str]:
        fn = call.func
        if isinstance(fn, ast.Name):
            name = fn.id
            if name in fi.local_funcs:
                return [fi.local_funcs[name]]
            if name == "len" and call.args:
                t = self._receiver_type(fi, call.args[0])
                if t is not None:
                    return self._method_on(t, "__len__", virtual=False)
            r = self._resolve_name(fi.mod, name)
            if r is None:
                return []
            if r[0] == "func":
                return [str(r[1])]
            if r[0] == "class":
                return self._ctor_of(r[1])  # type: ignore[arg-type]
            return []
        if not isinstance(fn, ast.Attribute):
            return []
        mname = fn.attr
        base = fn.value
        if isinstance(base, ast.Name):
            r = self._resolve_name(fi.mod, base.id)
            if (r is not None and r[0] == "module"
                    and base.id not in fi.local_types):
                other = self.m.modules.get(str(r[1]))
                if other is None:
                    return []
                if mname in other.functions:
                    return [other.functions[mname]]
                if mname in other.classes:
                    return self._ctor_of((other.mod, mname))
                return []
        t = self._receiver_type(fi, base)
        if t is not None:
            return self._method_on(t, mname)
        # Unique-definition fallback for distinctive method names.
        if mname in _COMMON_METHODS or mname.startswith("__"):
            return []
        defs = self._method_index.get(mname, ())
        if len(defs) == 1:
            return self._method_on(defs[0], mname)
        return []

    # ------------------------------------------------- pass 4: roots

    def _discover_roots(self) -> None:
        for fi in list(self.m.functions.values()):
            for cs in list(fi.calls):
                self._root_from_call(fi, cs.node)

    def _callable_arg_targets(
        self, fi: _FuncInfo, arg: ast.expr
    ) -> list[str]:
        """Resolve a callable expression handed to Thread/submit/a
        registrar to the function(s) it would invoke."""
        if isinstance(arg, ast.Lambda):
            fq = f"{fi.qualname}.<lambda@{arg.lineno}>"
            if fq not in self.m.functions:
                child = _FuncInfo(
                    qualname=fq, relpath=fi.relpath, mod=fi.mod,
                    cls=fi.cls, node=arg)
                child.local_types = dict(fi.local_types)
                child.local_locks = dict(fi.local_locks)
                child.local_funcs = fi.local_funcs
                self.m.functions[fq] = child
                self.m.entry_held.setdefault(fq, set())
                self.m.roles.setdefault(fq, {})
                self._walk_events(
                    child, [ast.Expr(value=arg.body)], frozenset())
                for cs in child.calls:
                    cs.callees = tuple(self._callees_of(child, cs.node))
            return [fq]
        if isinstance(arg, ast.Name):
            if arg.id in fi.local_funcs:
                return [fi.local_funcs[arg.id]]
            r = self._resolve_name(fi.mod, arg.id)
            if r is not None and r[0] == "func":
                return [str(r[1])]
            return []
        if isinstance(arg, ast.Attribute):
            t = self._receiver_type(fi, arg.value)
            if t is not None:
                return self._method_on(t, arg.attr)
            if (arg.attr not in _COMMON_METHODS
                    and not arg.attr.startswith("__")
                    and len(self._method_index.get(arg.attr, ())) == 1):
                return self._method_on(
                    self._method_index[arg.attr][0], arg.attr)
        return []

    @staticmethod
    def _thread_name_literal(call: ast.Call) -> str:
        for kw in call.keywords:
            if kw.arg != "name":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return v.value
            if isinstance(v, ast.JoinedStr):
                parts = []
                for piece in v.values:
                    if isinstance(piece, ast.Constant):
                        parts.append(str(piece.value))
                    else:
                        parts.append("*")
                return "".join(parts)
        return "<unnamed>"

    def _root_from_call(self, fi: _FuncInfo, call: ast.Call) -> None:
        fn = call.func
        is_thread = (_terminal(fn) == "Thread" and (
            not isinstance(fn, ast.Attribute)
            or _terminal(fn.value) == "threading"))
        if is_thread:
            target = None
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None:
                return
            role = self._thread_name_literal(call)
            for fq in self._callable_arg_targets(fi, target):
                self.m.roots.append(ThreadRoot(
                    role=role, func=fq, path=fi.relpath,
                    line=call.lineno, via="thread"))
            return
        # ThreadPoolExecutor fan-out: submit(fn, ...) / map(fn, ...) on a
        # receiver that is NOT a package class (package pools declare
        # their worker role via CALLBACK_ROLES instead).
        if (isinstance(fn, ast.Attribute) and fn.attr in ("submit", "map")
                and call.args):
            t = self._receiver_type(fi, fn.value)
            recv = _terminal(fn.value).lstrip("_").lower()
            if t is None and ("pool" in recv or "executor" in recv):
                owner = fi.cls or fi.qualname.rsplit(".", 1)[-1]
                role = f"pool:{fi.mod}.{owner}"
                for fq in self._callable_arg_targets(fi, call.args[0]):
                    self.m.roots.append(ThreadRoot(
                        role=role, func=fq, path=fi.relpath,
                        line=call.lineno, via="pool"))
        callees = self._callees_of(fi, call)
        for cb in CALLBACK_ROLES:
            if cb.method not in callees:
                continue
            for idx in cb.arg_indices:
                if idx >= len(call.args):
                    continue
                for fq in self._callable_arg_targets(fi, call.args[idx]):
                    for role in cb.roles:
                        self.m.roots.append(ThreadRoot(
                            role=role, func=fq, path=fi.relpath,
                            line=call.lineno, via="callback"))

    # --------------------------------------------- pass 5: propagation

    def _propagate(self) -> None:
        m = self.m
        for fq in m.functions:
            m.entry_held.setdefault(fq, set())
            m.roles.setdefault(fq, {})
        for root in m.roots:
            if root.func in m.roles and root.role not in m.roles[root.func]:
                m.roles[root.func][root.role] = (
                    None, root.path, root.line)
        work = list(m.functions)
        in_work = set(work)
        while work:
            fq = work.pop()
            in_work.discard(fq)
            fi = m.functions[fq]
            entry = m.entry_held[fq]
            roles = m.roles[fq]
            for cs in fi.calls:
                for callee in cs.callees:
                    if callee not in m.functions:
                        continue
                    changed = False
                    target_held = m.entry_held[callee]
                    add = (entry | cs.held) - target_held
                    if add:
                        target_held |= add
                        changed = True
                    target_roles = m.roles[callee]
                    for role in roles:
                        if role not in target_roles:
                            target_roles[role] = (fq, fi.relpath, cs.line)
                            changed = True
                    if changed and callee not in in_work:
                        work.append(callee)
                        in_work.add(callee)

    # ------------------------------------------------ pass 6: contracts

    def _derive_edges(self) -> None:
        m = self.m
        for fq, fi in m.functions.items():
            entry = frozenset(m.entry_held[fq])
            for acq in fi.acquires:
                held = entry | acq.held
                for src in held:
                    if src == acq.key:
                        info = m.locks.get(acq.key)
                        if info is not None and info.kind != "rlock":
                            m.findings.append(Diagnostic(
                                "lock-order", ERROR, fi.relpath, acq.line,
                                f"re-acquisition of non-reentrant lock "
                                f"{acq.key} while already held on some "
                                f"path through {fq}() — self-deadlock "
                                f"(split the critical section, or use "
                                f"RLock with a written justification)",
                            ))
                        continue
                    edge = (src, acq.key)
                    if edge not in m.edges:
                        m.edges[edge] = OrderEdge(
                            src=src, dst=acq.key, func=fq,
                            path=fi.relpath, line=acq.line)

    def _check_order_cycles(self) -> None:
        m = self.m
        adj: dict[str, list[str]] = {}
        for (src, dst) in m.edges:
            adj.setdefault(src, []).append(dst)
        for me in MODELED_EDGES:
            adj.setdefault(me.src, []).append(me.dst)
        sccs = _tarjan_sccs(adj)
        for comp in sccs:
            comp_set = set(comp)
            cycle = self._cycle_path(comp_set, adj)
            anchor = None
            for i in range(len(cycle) - 1):
                e = m.edges.get((cycle[i], cycle[i + 1]))
                if e is not None:
                    anchor = e
                    break
            path = anchor.path if anchor else f"{_PKG}/__init__.py"
            line = anchor.line if anchor else 1
            via = " -> ".join(cycle)
            m.findings.append(Diagnostic(
                "lock-order", ERROR, path, line,
                f"lock-order cycle (deadlock candidate): {via}. Two "
                f"paths acquire these locks in opposite orders; impose "
                f"a single global order (acquire the earlier lock first "
                f"everywhere) or collapse the critical sections",
            ))

    @staticmethod
    def _cycle_path(comp: set[str], adj: dict[str, list[str]]) -> list[str]:
        start = sorted(comp)[0]
        path = [start]
        seen = {start}
        cur = start
        while True:
            nxt = None
            for w in sorted(adj.get(cur, ())):
                if w in comp:
                    if w == start:
                        return path + [start]
                    if w not in seen:
                        nxt = w
                        break
            if nxt is None:
                return path + [start]
            path.append(nxt)
            seen.add(nxt)
            cur = nxt

    def _check_io_chains(self) -> None:
        m = self.m
        # fq -> (why, via-callee | None, chain depth)
        reaches: dict[str, tuple[str, str | None, int]] = {}
        for fq, fi in m.functions.items():
            if fi.io:
                reaches[fq] = (fi.io[0][1], None, 0)
        changed = True
        while changed:
            changed = False
            for fq, fi in m.functions.items():
                if fq in reaches:
                    continue
                for cs in fi.calls:
                    hit = next(
                        (c for c in cs.callees if c in reaches), None)
                    if hit is not None:
                        reaches[fq] = (
                            reaches[hit][0], hit, reaches[hit][2] + 1)
                        changed = True
                        break
        reported: set[tuple[str, int]] = set()
        for fq, fi in m.functions.items():
            for cs in fi.calls:
                if not cs.held:
                    continue
                for callee in cs.callees:
                    if callee not in reaches:
                        continue
                    key = (fi.relpath, cs.line)
                    if key in reported:
                        break
                    reported.add(key)
                    why, _, _depth = reaches[callee]
                    chain = self._io_chain(callee, reaches)
                    held = ", ".join(sorted(cs.held))
                    m.findings.append(Diagnostic(
                        "lock-io-chain", ERROR, fi.relpath, cs.line,
                        f"call under lock ({held}) transitively performs "
                        f"{why} via {' -> '.join(chain)} — the lock-io "
                        f"rule, interprocedural: move the call outside "
                        f"the critical section or make the callee "
                        f"lock-free",
                    ))
                    break

    @staticmethod
    def _io_chain(
        start: str, reaches: dict[str, tuple[str, str | None, int]]
    ) -> list[str]:
        chain = [start]
        cur: str | None = start
        while cur is not None and cur in reaches:
            nxt = reaches[cur][1]
            if nxt is None:
                break
            chain.append(nxt)
            cur = nxt
        return chain

    def _check_ownership(self) -> None:
        m = self.m
        for rule in self.ownership:
            fi = m.functions.get(rule.func)
            if fi is None:
                m.findings.append(Diagnostic(
                    "lock-ownership", ERROR,
                    f"{_PKG}/analysis/concurrency.py", 1,
                    f"ownership table names {rule.func}() but no such "
                    f"function exists — the table rotted; update "
                    f"OWNERSHIP in analysis/concurrency.py",
                ))
                continue
            if rule.allowed != ("*",):
                for role, prov in sorted(
                        m.roles.get(rule.func, {}).items()):
                    if any(fnmatchcase(role, pat)
                           for pat in rule.allowed):
                        continue
                    chain = m.role_chain(rule.func, role)
                    via = " -> ".join(fq for fq, _, _ in chain) \
                        or rule.func
                    _caller, path, line = prov
                    m.findings.append(Diagnostic(
                        "lock-ownership", ERROR, path, line,
                        f"{rule.func}() may run on thread '{role}' "
                        f"(via {via}) but is owned by "
                        f"{'/'.join(rule.allowed)} — {rule.reason}",
                    ))
            if rule.guarded_flag is not None:
                self._check_guarded_flag(fi, rule)

    def _check_guarded_flag(
        self, fi: _FuncInfo, rule: OwnershipRule
    ) -> None:
        """Every ``self.<flag>`` READ in the function must sit inside a
        ``with self.<lock>:`` block of the same class."""
        if isinstance(fi.node, ast.Lambda):
            return
        flag = rule.guarded_flag
        offenders: list[int] = []

        def check_exprs(exprs: list[ast.expr], guarded: bool) -> None:
            if guarded:
                return
            for e in exprs:
                for n in _iter_no_defs(e):
                    if (isinstance(n, ast.Attribute) and n.attr == flag
                            and isinstance(n.value, ast.Name)
                            and n.value.id == "self"
                            and isinstance(n.ctx, ast.Load)):
                        offenders.append(n.lineno)

        def scan(body: list[ast.stmt], guarded: bool) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    inner = guarded
                    for item in stmt.items:
                        check_exprs([item.context_expr], guarded)
                        if self._lock_expr_key(fi, item.context_expr):
                            inner = True
                    scan(stmt.body, inner)
                    continue
                exprs, stmt_lists = _stmt_parts(stmt)
                check_exprs(exprs, guarded)
                for sub in stmt_lists:
                    scan(sub, guarded)

        scan(fi.node.body, False)
        for line in sorted(set(offenders)):
            self.m.findings.append(Diagnostic(
                "lock-ownership", ERROR, fi.relpath, line,
                f"{rule.func}() reads self.{flag} outside the instance "
                f"lock — the flag must be (re)checked INSIDE the lock: "
                f"{rule.reason}",
            ))


def _tarjan_sccs(adj: dict[str, list[str]]) -> list[list[str]]:
    """Strongly-connected components with >1 node, iteratively."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []

    nodes = set(adj)
    for outs in adj.values():
        nodes.update(outs)

    for v in sorted(nodes):
        if v in index:
            continue
        call_stack: list[tuple[str, Iterator[str]]] = [
            (v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while call_stack:
            node, it = call_stack[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    call_stack.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            call_stack.pop()
            if call_stack:
                parent = call_stack[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
    return sccs


# ------------------------------------------------------------------- facade


def build_model(
    package_trees: dict[str, ast.Module],
    ownership: tuple[OwnershipRule, ...] | None = None,
) -> ConcurrencyModel:
    return _Builder(package_trees, ownership=ownership).build()


def get_model(ctx: "LintContext") -> ConcurrencyModel:
    """Memoized concurrency model for a lint context (the three
    concurrency rules share one whole-tree pass)."""
    cached = getattr(ctx, "_concurrency_model", None)
    if cached is None:
        cached = build_model(ctx.package_trees)
        ctx._concurrency_model = cached  # type: ignore[attr-defined]
    return cached


def check_lock_order(ctx: "LintContext") -> list[Diagnostic]:
    return [d for d in get_model(ctx).findings if d.rule == "lock-order"]


def check_lock_ownership(ctx: "LintContext") -> list[Diagnostic]:
    return [d for d in get_model(ctx).findings
            if d.rule == "lock-ownership"]


def check_lock_io_chain(ctx: "LintContext") -> list[Diagnostic]:
    return [d for d in get_model(ctx).findings
            if d.rule == "lock-io-chain"]


# -------------------------------------------------------------- cross-check


def cross_check(model: ConcurrencyModel, dump: dict) -> list[str]:
    """Witness dump vs static model. Returns human-readable problems
    (empty = the static graph explains everything the tests witnessed).

    Two failure classes:
      * a witnessed lock whose creation site the static pass never
        discovered (the discovery pass rotted);
      * a witnessed acquisition-order edge absent from the static order
        graph and from MODELED_EDGES (the call-graph model rotted).
    """
    problems: list[str] = []
    site_to_key: dict[str, str] = {}
    for wl in dump.get("locks", []):
        path, line = wl.get("path", ""), int(wl.get("line", 0))
        site = wl.get("site", f"{path}:{line}")
        info = model.lock_at(path, line)
        if info is None:
            problems.append(
                f"witnessed lock at {site} has no static identity — "
                f"the discovery pass in analysis/concurrency.py missed "
                f"it")
            continue
        site_to_key[site] = info.key
    static_edges = set(model.edges)
    static_edges.update((m.src, m.dst) for m in MODELED_EDGES)
    for we in dump.get("edges", []):
        src = site_to_key.get(we.get("from", ""))
        dst = site_to_key.get(we.get("to", ""))
        if src is None or dst is None:
            continue  # unknown locks already reported above
        if src == dst:
            # Sibling-instance nesting of one lock class: the static
            # graph keys locks by creation site, so instance-level
            # ordering is invisible to it. Genuine same-instance
            # re-acquisition is flagged by the witness as an inversion.
            continue
        if (src, dst) not in static_edges:
            problems.append(
                f"witnessed order edge {src} -> {dst} "
                f"(observed {we.get('example', 'at runtime')}) is "
                f"absent from the static order graph — the call-graph "
                f"model can no longer explain runtime behavior; extend "
                f"the analysis or declare it in MODELED_EDGES with a "
                f"reason")
    for inv in dump.get("inversions", []):
        problems.append(
            f"witnessed lock-order inversion: "
            f"{inv.get('detail', inv)}")
    return problems
