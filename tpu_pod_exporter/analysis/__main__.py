"""``exporter-lint`` CLI — the invariant gate behind ``make lint``.

Exit status: 0 when the tree is clean against the committed baseline,
1 when any new finding exists (each printed as ``file:line: severity:
rule: message``), 2 on operational errors (missing schema, bad root).

``--demo`` seeds a deliberate lock-scoped ``json.dumps`` and an
unregistered metric name into a temp copy of ``collector.py`` and shows
the linter catching both — the lint analog of ``make chaos-demo``
(exits 0 only if BOTH seeded violations are caught).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpu_pod_exporter.analysis.diagnostics import ERROR
from tpu_pod_exporter.analysis.engine import (
    apply_baseline,
    baseline_document,
    lint_package,
    load_baseline,
)
from tpu_pod_exporter.analysis.rules import ALL_RULES

BASELINE_NAME = ".exporter-lint-baseline.json"


def _default_root() -> str:
    # analysis/__main__.py -> analysis -> tpu_pod_exporter -> repo root.
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _run_demo(root: str) -> int:
    """Copy collector.py aside, seed two violations, show the diagnostics."""
    import shutil
    import tempfile

    with tempfile.TemporaryDirectory(prefix="exporter-lint-demo-") as tmp:
        pkg = os.path.join(tmp, "tpu_pod_exporter")
        shutil.copytree(
            os.path.join(root, "tpu_pod_exporter"), pkg,
            ignore=shutil.ignore_patterns("__pycache__"),
        )
        target = os.path.join(pkg, "collector.py")
        with open(target, "a") as f:
            f.write(
                "\n\n"
                "def _lint_demo_seeded(snapshot, counters):\n"
                "    # Seeded by `exporter-lint --demo`: BOTH lines below\n"
                "    # violate an invariant rule on purpose.\n"
                "    import json\n"
                "    import threading\n"
                "    demo_lock = threading.Lock()\n"
                "    with demo_lock:\n"
                "        body = json.dumps({'seeded': True})\n"
                "    counters.inc('tpu_exporter_demo_bogus_total', ())\n"
                "    return body\n"
            )
        print("seeded into a temp copy of tpu_pod_exporter/collector.py:")
        print("  - json.dumps(...) inside `with demo_lock:`   (rule lock-io)")
        print("  - metric name 'tpu_exporter_demo_bogus_total' not in "
              "schema.ALL_SPECS   (rule metric-name)")
        print()
        findings = [
            d for d in lint_package(tmp)
            if d.path == "tpu_pod_exporter/collector.py"
        ]
        caught = set()
        for d in findings:
            print(d.format())
            caught.add(d.rule)
        ok = {"lock-io", "metric-name"} <= caught
        print()
        print("demo:", "PASS — both seeded violations caught"
              if ok else "FAIL — a seeded violation was NOT caught")
        return 0 if ok else 1


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="exporter-lint",
        description="AST-enforced invariant lint for tpu-pod-exporter.",
    )
    p.add_argument("--root", default=_default_root(),
                   help="repo root containing tpu_pod_exporter/ (default: "
                        "auto-detected from this file's location)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON path (default: <root>/{BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="write all current findings to the baseline and exit 0")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule reference and exit")
    p.add_argument("--demo", action="store_true",
                   help="seed a violation into a temp copy and show the "
                        "diagnostic (make lint-demo)")
    ns = p.parse_args(argv)

    if ns.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name:18s} {rule.severity:8s} {rule.summary}")
        return 0

    root = os.path.abspath(ns.root)
    if not os.path.isdir(os.path.join(root, "tpu_pod_exporter")):
        print(f"exporter-lint: no tpu_pod_exporter/ under {root}",
              file=sys.stderr)
        return 2

    if ns.demo:
        return _run_demo(root)

    findings = lint_package(root)
    baseline_path = ns.baseline or os.path.join(root, BASELINE_NAME)

    if ns.update_baseline:
        doc = baseline_document(findings, root)
        with open(baseline_path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    suppressed = 0
    if not ns.no_baseline:
        findings, suppressed = apply_baseline(
            findings, load_baseline(baseline_path), root
        )

    if ns.format == "json":
        print(json.dumps({
            "findings": [
                {
                    "rule": d.rule, "severity": d.severity, "path": d.path,
                    "line": d.line, "message": d.message,
                }
                for d in findings
            ],
            "baseline_suppressed": suppressed,
        }, indent=1))
    else:
        for d in findings:
            print(d.format())
        errors = sum(1 for d in findings if d.severity == ERROR)
        warnings = len(findings) - errors
        tail = f" ({suppressed} grandfathered in baseline)" if suppressed else ""
        if findings:
            print(f"exporter-lint: {errors} error(s), {warnings} warning(s)"
                  f"{tail}")
        else:
            print(f"exporter-lint: clean{tail}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
