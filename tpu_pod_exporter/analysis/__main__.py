"""``exporter-lint`` CLI — the invariant gate behind ``make lint``.

Exit status: 0 when the tree is clean against the committed baseline,
1 when any new finding exists (each printed as ``file:line: severity:
rule: message``), 2 on operational errors (missing schema, bad root).

``--demo`` seeds deliberate violations into a temp copy of the package —
a lock-scoped ``json.dumps``, an unregistered metric name, a lock-order
inversion pair, a wrong-thread WAL cursor move, an inline ``time.sleep``
on the event loop, a raw ``open("w")`` on a cursor path plus a second
cursor-mover thread, and a stray ``os.fork`` — and exits 0 only if ALL
seven rule families catch their seed (the lint analog of ``make
chaos-demo``).

``--lock-graph``/``--lock-graph-dot`` render the concurrency model's
acquisition-order graph (the committed ``deploy/lock-graph.json``
artifact); ``--fork-inventory`` renders the pre-fork resource inventory
(the committed ``deploy/fork-inventory.json`` artifact);
``--check-witness``/``--check-loop-witness`` cross-check runtime witness
dumps (``tests/conftest.py`` under ``TPE_LOCK_WITNESS=1`` /
``TPE_LOOP_WITNESS=1``) against the static model.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpu_pod_exporter.analysis.diagnostics import ERROR, to_sarif
from tpu_pod_exporter.analysis.engine import (
    apply_baseline,
    baseline_document,
    build_context,
    lint_package,
    load_baseline,
)
from tpu_pod_exporter.analysis.rules import ALL_RULES

BASELINE_NAME = ".exporter-lint-baseline.json"

# (rule that must fire, what was seeded) — the --demo contract.
_DEMO_EXPECTED = (
    ("lock-io", "json.dumps(...) inside `with demo_lock:`"),
    ("metric-name", "unregistered name 'tpu_exporter_demo_bogus_total'"),
    ("lock-order", "two functions acquiring _demo_lock_a/_demo_lock_b "
                   "in opposite orders"),
    ("lock-ownership", "a 'tpu-demo-wrong-thread' thread calling "
                       "WalBuffer.ack() (cursor move off the owner "
                       "thread)"),
    ("loop-blocking", "a call_soon()-posted callback doing time.sleep() "
                      "inline on the event loop"),
    ("durability-ordering", "raw open(.., 'w') on a cursor.json path, "
                            "plus a second cursor-mover thread on a "
                            "WalBuffer"),
    ("fork-safety", "an os.fork() outside any sanctioned pre-fork "
                    "entry point"),
)


def _default_root() -> str:
    # analysis/__main__.py -> analysis -> tpu_pod_exporter -> repo root.
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _run_demo(root: str) -> int:
    """Copy the package aside, seed one violation per rule family, and
    require the linter to catch every one of them."""
    import shutil
    import tempfile

    with tempfile.TemporaryDirectory(prefix="exporter-lint-demo-") as tmp:
        pkg = os.path.join(tmp, "tpu_pod_exporter")
        shutil.copytree(
            os.path.join(root, "tpu_pod_exporter"), pkg,
            ignore=shutil.ignore_patterns("__pycache__"),
        )
        with open(os.path.join(pkg, "collector.py"), "a") as f:
            f.write(
                "\n\n"
                "def _lint_demo_seeded(snapshot, counters):\n"
                "    # Seeded by `exporter-lint --demo`: BOTH lines below\n"
                "    # violate an invariant rule on purpose.\n"
                "    import json\n"
                "    demo_lock = threading.Lock()\n"
                "    with demo_lock:\n"
                "        body = json.dumps({'seeded': True})\n"
                "    counters.inc('tpu_exporter_demo_bogus_total', ())\n"
                "    return body\n"
                "\n\n"
                "# Seeded lock-order inversion: two paths, opposite order.\n"
                "_demo_lock_a = threading.Lock()\n"
                "_demo_lock_b = threading.Lock()\n"
                "\n\n"
                "def _lint_demo_order_one():\n"
                "    with _demo_lock_a:\n"
                "        with _demo_lock_b:\n"
                "            pass\n"
                "\n\n"
                "def _lint_demo_order_two():\n"
                "    with _demo_lock_b:\n"
                "        with _demo_lock_a:\n"
                "            pass\n"
            )
        with open(os.path.join(pkg, "persist.py"), "a") as f:
            f.write(
                "\n\n"
                "class _LintDemoWrongThreadMover:\n"
                "    # Seeded by `exporter-lint --demo`: a thread outside\n"
                "    # the declared WalBuffer cursor-owner set moving the\n"
                "    # cursor (the PR 11 governor-race bug class).\n"
                "    def __init__(self) -> None:\n"
                "        self._buf = WalBuffer('/tmp/lint-demo-wal')\n"
                "        self._thread = threading.Thread(\n"
                "            target=self._move,\n"
                "            name='tpu-demo-wrong-thread', daemon=True,\n"
                "        )\n"
                "\n"
                "    def _move(self) -> None:\n"
                "        self._buf.ack()\n"
                "\n\n"
                "def _lint_demo_raw_cursor_write(root: str) -> None:\n"
                "    # Seeded by `exporter-lint --demo`: a raw open('w')\n"
                "    # on a durability state path — bypasses the atomic\n"
                "    # write-temp/fsync/rename discipline.\n"
                "    with open(root + '/cursor.json', 'w') as f:\n"
                "        f.write('{}')\n"
                "\n\n"
                "def _lint_demo_fork() -> None:\n"
                "    # Seeded by `exporter-lint --demo`: fork outside any\n"
                "    # sanctioned pre-fork entry point.\n"
                "    os.fork()\n"
                "\n\n"
                "class _LintDemoDualMover:\n"
                "    # Seeded by `exporter-lint --demo`: TWO threads moving\n"
                "    # one WalBuffer cursor. mover-a is the declared owner\n"
                "    # (demo CursorMoverRule in analysis/execcontext.py);\n"
                "    # mover-b is the second-mover violation.\n"
                "    def __init__(self) -> None:\n"
                "        self._wal = WalBuffer('/tmp/lint-demo-dual-wal')\n"
                "        self._ta = threading.Thread(\n"
                "            target=self._move_a,\n"
                "            name='tpu-demo-mover-a', daemon=True,\n"
                "        )\n"
                "        self._tb = threading.Thread(\n"
                "            target=self._move_b,\n"
                "            name='tpu-demo-mover-b', daemon=True,\n"
                "        )\n"
                "\n"
                "    def _move_a(self) -> None:\n"
                "        self._wal.ack()\n"
                "\n"
                "    def _move_b(self) -> None:\n"
                "        self._wal.trim_to_bytes(0)\n"
            )
        with open(os.path.join(pkg, "server.py"), "a") as f:
            f.write(
                "\n\n"
                "def _lint_demo_loop_blocking() -> None:\n"
                "    # Seeded by `exporter-lint --demo`: time.sleep inline\n"
                "    # on the event loop (posted via call_soon below) —\n"
                "    # one stalled callback parks every connection.\n"
                "    time.sleep(0.5)\n"
                "\n\n"
                "def _lint_demo_register(loop) -> None:\n"
                "    loop.call_soon(_lint_demo_loop_blocking)\n"
            )
        print("seeded into a temp copy of the package:")
        for rule, what in _DEMO_EXPECTED:
            print(f"  - {what}   (rule {rule})")
        print()
        findings = [
            d for d in lint_package(tmp)
            if d.path in ("tpu_pod_exporter/collector.py",
                          "tpu_pod_exporter/persist.py",
                          "tpu_pod_exporter/server.py")
        ]
        caught = set()
        for d in findings:
            print(d.format())
            caught.add(d.rule)
        missing = [r for r, _ in _DEMO_EXPECTED if r not in caught]
        print()
        if missing:
            print(f"demo: FAIL — seeded violation(s) NOT caught: "
                  f"{', '.join(missing)}")
            return 1
        print("demo: PASS — all seeded violations caught "
              f"({', '.join(r for r, _ in _DEMO_EXPECTED)})")
        return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="exporter-lint",
        description="AST-enforced invariant lint for tpu-pod-exporter.",
    )
    p.add_argument("--root", default=_default_root(),
                   help="repo root containing tpu_pod_exporter/ (default: "
                        "auto-detected from this file's location)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON path (default: <root>/{BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="write all current findings to the baseline and exit 0")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text",
                   help="sarif emits SARIF 2.1.0 for inline PR "
                        "annotations; json is the CI artifact shape")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule reference and exit")
    p.add_argument("--demo", action="store_true",
                   help="seed one violation per rule family into a temp "
                        "copy and require the linter to catch all of "
                        "them (make lint-demo)")
    p.add_argument("--lock-graph", metavar="PATH", default=None,
                   help="write the lock-acquisition order graph (JSON, "
                        "the reviewed deploy/lock-graph.json artifact) "
                        "and exit")
    p.add_argument("--lock-graph-dot", metavar="PATH", default=None,
                   help="write the order graph as Graphviz DOT and exit")
    p.add_argument("--check-witness", metavar="DUMP", default=None,
                   help="cross-check a runtime lock-witness edge dump "
                        "(tier-1 under TPE_LOCK_WITNESS=1) against the "
                        "static model; non-zero on any unexplained edge")
    p.add_argument("--fork-inventory", metavar="PATH", default=None,
                   help="write the pre-fork resource inventory (threads/"
                        "locks/kernel objects; the reviewed "
                        "deploy/fork-inventory.json artifact) and exit")
    p.add_argument("--check-loop-witness", metavar="DUMP", default=None,
                   help="cross-check a runtime loop-witness dump (tier-1 "
                        "under TPE_LOOP_WITNESS=1) against the static "
                        "loop-role model; non-zero on any stall or any "
                        "loop-executed callback the model cannot explain")
    ns = p.parse_args(argv)

    if ns.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name:18s} {rule.severity:8s} {rule.summary}")
        return 0

    root = os.path.abspath(ns.root)
    if not os.path.isdir(os.path.join(root, "tpu_pod_exporter")):
        print(f"exporter-lint: no tpu_pod_exporter/ under {root}",
              file=sys.stderr)
        return 2

    if ns.demo:
        return _run_demo(root)

    if (ns.lock_graph or ns.lock_graph_dot or ns.check_witness
            or ns.fork_inventory or ns.check_loop_witness):
        from tpu_pod_exporter.analysis import concurrency
        model = concurrency.get_model(build_context(root))
        if ns.lock_graph:
            doc = model.graph_json()
            with open(ns.lock_graph, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            print(f"wrote {len(doc['locks'])} lock(s), "
                  f"{len(doc['edges'])} edge(s) to {ns.lock_graph}")
        if ns.lock_graph_dot:
            with open(ns.lock_graph_dot, "w", encoding="utf-8") as f:
                f.write(model.graph_dot())
            print(f"wrote DOT graph to {ns.lock_graph_dot}")
        if ns.check_witness:
            from tpu_pod_exporter.analysis import witness as witness_mod
            try:
                dump = witness_mod.load_dump(ns.check_witness)
            except (OSError, ValueError) as e:
                print(f"exporter-lint: cannot read witness dump: {e}",
                      file=sys.stderr)
                return 2
            problems = concurrency.cross_check(model, dump)
            meta = dump.get("meta", {})
            print(f"witness dump: {meta.get('locks', '?')} lock(s), "
                  f"{meta.get('acquisitions', '?')} acquisition(s), "
                  f"{meta.get('edges', '?')} order edge(s)")
            for prob in problems:
                print(f"CROSS-CHECK: {prob}")
            if problems:
                print(f"exporter-lint: witness cross-check FAILED "
                      f"({len(problems)} problem(s))")
                return 1
            print("exporter-lint: witness cross-check OK — every "
                  "witnessed edge is explained by the static model")
        if ns.fork_inventory:
            from tpu_pod_exporter.analysis import execcontext
            doc = execcontext.fork_inventory(model)
            with open(ns.fork_inventory, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            print(f"wrote {len(doc['threads'])} thread(s), "
                  f"{len(doc['locks'])} lock(s), "
                  f"{len(doc['kernel_objects'])} kernel object(s) to "
                  f"{ns.fork_inventory}")
        if ns.check_loop_witness:
            from tpu_pod_exporter.analysis import execcontext
            from tpu_pod_exporter.analysis import witness as witness_mod
            try:
                dump = witness_mod.load_dump(ns.check_loop_witness)
            except (OSError, ValueError) as e:
                print(f"exporter-lint: cannot read loop-witness dump: {e}",
                      file=sys.stderr)
                return 2
            problems = execcontext.cross_check_loop(model, dump)
            meta = dump.get("meta", {})
            print(f"loop-witness dump: {meta.get('callbacks', '?')} "
                  f"callback(s), {meta.get('stalls', '?')} stall(s) over "
                  f"{meta.get('threshold_ms', '?')} ms")
            for prob in problems:
                print(f"CROSS-CHECK: {prob}")
            if problems:
                print(f"exporter-lint: loop-witness cross-check FAILED "
                      f"({len(problems)} problem(s))")
                return 1
            print("exporter-lint: loop-witness cross-check OK — zero "
                  "stalls; every loop-executed callback is "
                  "loop-role-tagged in the static model")
        return 0

    findings = lint_package(root)
    baseline_path = ns.baseline or os.path.join(root, BASELINE_NAME)

    if ns.update_baseline:
        doc = baseline_document(findings, root)
        with open(baseline_path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    suppressed = 0
    if not ns.no_baseline:
        findings, suppressed = apply_baseline(
            findings, load_baseline(baseline_path), root
        )

    if ns.format == "sarif":
        print(json.dumps(to_sarif(findings, ALL_RULES), indent=1))
    elif ns.format == "json":
        print(json.dumps({
            "findings": [
                {
                    "rule": d.rule, "severity": d.severity, "path": d.path,
                    "line": d.line, "message": d.message,
                }
                for d in findings
            ],
            "baseline_suppressed": suppressed,
        }, indent=1))
    else:
        for d in findings:
            print(d.format())
        errors = sum(1 for d in findings if d.severity == ERROR)
        warnings = len(findings) - errors
        tail = f" ({suppressed} grandfathered in baseline)" if suppressed else ""
        if findings:
            print(f"exporter-lint: {errors} error(s), {warnings} warning(s)"
                  f"{tail}")
        else:
            print(f"exporter-lint: clean{tail}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
