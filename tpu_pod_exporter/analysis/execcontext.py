"""Execution-context contracts — where code is ALLOWED to run and in what
order it must touch the disk, as whole-tree checkable rules.

Three rule families built on the concurrency model (analysis/concurrency.py:
lock discovery, conservative call graph, thread-role fixpoint):

``loop-blocking``
    The selectors loop (server._EventLoopServer.run, thread role
    ``tpu-exporter-http``) may never block: every callback it dispatches
    inline — the scrape fast path, loop timers, streaming writes,
    ``call_soon``/``call_later`` posts — is tagged with the loop role by
    the role fixpoint, and any blocking operation (file I/O, ``time.sleep``,
    compression, serialization above the splice seam, blocking subprocess
    or network calls, or acquiring a lock whose OTHER holders may block)
    reachable under that tag is a finding. Work routed through
    ``_WorkerPool.submit`` or the ``StreamPump`` is laundered naturally:
    submitted closures carry the worker role, not the loop role.

``durability-ordering``
    The WAL contract shared by persist/egress/store/alerting as dataflow
    rules: (a) state files (``*-status.json``, ``cursor.json``, ``seq``,
    breaker/shard-map documents) must be written through the atomic
    write-temp -> fsync -> rename helper (``persist.atomic_write``) — a
    raw ``open(path, "w")`` on a state path is a finding; (b) cursor
    movers (``ack``/``_advance``/``trim_to_bytes``/``drop_oldest``) on a
    cursor-owning class must be fsync-reachable before return; (c) each
    ``WalBuffer`` instance has exactly ONE declared mover role
    (``CURSOR_MOVERS`` below) — a new subsystem wiring a second mover
    thread fails lint, not review.

``fork-safety``
    Forward-looking audit for the multi-core (pre-fork ``SO_REUSEPORT``)
    serving plane: direct ``os.fork``/multiprocessing use and import-time
    thread/fd creation are findings today; the full inventory of
    thread-spawn, lock, mmap, and retained-fd creation sites that would be
    live at a pre-fork point is exported as the committed
    ``deploy/fork-inventory.json`` artifact (``make fork-inventory``,
    freshness-gated in CI like the lock graph).

The runtime half lives in analysis/witness.py (``LoopWitness``, gated on
``TPE_LOOP_WITNESS=1``): it times every loop-dispatched callback through
``server.LOOP_PROBE`` and :func:`cross_check_loop` verifies that each
witnessed callback is loop-role-tagged in the static model — neither side
can rot. Like the rest of exporter-lint, this module never imports the
code it checks.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from fnmatch import fnmatchcase
from typing import TYPE_CHECKING

from tpu_pod_exporter.analysis.concurrency import (
    ConcurrencyModel,
    _FuncInfo,
    _terminal,
    get_model,
)
from tpu_pod_exporter.analysis.diagnostics import ERROR, Diagnostic

if TYPE_CHECKING:
    from tpu_pod_exporter.analysis.engine import LintContext

_PKG = "tpu_pod_exporter"

# Thread roles that ARE the event loop. The selectors loop runs on the
# thread MetricsServer.start names "tpu-exporter-http"; call_soon /
# call_later / _invoke callbacks inherit the role via CALLBACK_ROLES.
LOOP_ROLES: tuple[str, ...] = ("tpu-exporter-http",)

# Basenames that are durability STATE: files whose loss or torn write
# changes replay/restart behavior. Writes must go through
# persist.atomic_write (write temp, fsync, rename, fsync dir).
STATE_FILE_PATTERNS: tuple[str, ...] = (
    "*-status.json",   # pressure/egress/store/alert sidecars
    "cursor.json",     # WalBuffer ack cursor
    "seq",             # bare sequence stamp files
    "breaker-*.json",  # aggregator breaker state (persist.BreakerStateFile)
    "shard-map*.json",  # shard-map documents (persist.ShardMapFile)
)

# Named constants that hold state-file basenames (STATUS_NAME = "...")
# are resolved tree-wide by name, so `open(join(dir, STATUS_NAME), "w")`
# is caught even though the literal lives in another module.


@dataclass(frozen=True)
class LoopAllowance:
    """A declared inline-blocking exemption: ``func`` (exact qualname) may
    perform the named blocking operation on the loop, with the reason
    reviewed here instead of at every call site. Prefer inline
    ``# lint: disable=loop-blocking(reason)`` for one-off sites; use an
    allowance when a helper is legitimately called from many loop paths."""

    func: str
    reason: str


LOOP_ALLOWED: tuple[LoopAllowance, ...] = ()


@dataclass(frozen=True)
class CursorMoverRule:
    """The ONE thread role allowed to move a WalBuffer cursor. ``buffer``
    is an fnmatch pattern over buffer identities (``mod.Class.attr`` for
    ``self.attr = WalBuffer(...)`` construction sites, ``mod.Class.*`` for
    buffers a class keeps in containers). ``demo`` rules exist only for
    the seeded ``make lint-demo`` tree — they are exempt from the
    declaration-rot check because the real tree has no such buffer."""

    buffer: str
    role: str
    reason: str
    demo: bool = False


CURSOR_MOVERS: tuple[CursorMoverRule, ...] = (
    CursorMoverRule(
        "egress.RemoteWriteShipper.buffer", "tpu-egress-sender",
        "the egress sender thread is the single consumer: it acks after "
        "2xx, drops on caps, trims on backlog — a second mover could "
        "regress the on-disk cursor and resurrect shed batches at boot",
    ),
    CursorMoverRule(
        "alerting.AlertNotifier.buffer", "tpu-alert-sender",
        "the alert sender owns the notification cursor (same "
        "single-consumer seat as the egress shipper, one subsystem over)",
    ),
    CursorMoverRule(
        "store.FleetStore.*", "tpu-exporter-poll",
        "the root round (appender) thread is the tier buffers' only "
        "cursor-mover: append + retention trim + thin-shed all happen on "
        "its pass; the governor only flips flags the appender acts on",
    ),
    CursorMoverRule(
        "persist._LintDemoDualMover._wal", "tpu-demo-mover-a",
        "make lint-demo seed: the demo's dual-mover class declares "
        "mover-a so its second thread (mover-b) exercises the "
        "second-mover finding end to end",
        demo=True,
    ),
)

# Methods that move a WAL cursor. `_advance` is the primitive; the public
# three delegate to it.
_MOVER_NAMES = ("ack", "_advance", "trim_to_bytes", "drop_oldest")


# ----------------------------------------------------------- blocking set


_COMPRESS_MODULES = ("gzip", "zlib", "bz2", "lzma")
_SERIALIZE_MODULES = ("json", "pickle", "marshal")
_OS_BLOCKING = (
    "makedirs", "mkdir", "replace", "rename", "unlink", "remove",
    "rmdir", "listdir", "scandir", "truncate", "fsync", "fdatasync",
)
_SUBPROCESS_BLOCKING = (
    "run", "check_output", "check_call", "call", "communicate", "wait",
)
_PATH_IO = ("write_text", "write_bytes", "read_text", "read_bytes")
# File-handle-ish receiver names for `.write()` / `.read()` — mirrors the
# lock-io rule's heuristic.
_FILEY_RECEIVERS = ("f", "fh", "fp", "file", "out", "outf", "stream")


def _blocking_offence(call: ast.Call) -> str | None:
    """Why this call can block the event loop, or None.

    Deliberately NOT in the set: ``send``/``recv`` (every socket the loop
    touches is non-blocking by construction — ``sendall`` IS flagged,
    its retry loop blocks regardless), ``selector.select`` (the idle
    wait), and logging (exception paths only on the loop; the lock-io
    family polices logging under locks)."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id == "open":
            return "open() (file I/O)"
        if fn.id == "print":
            return "print() (stream I/O)"
        if fn.id == "urlopen":
            return "urlopen() (network I/O)"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    recv = _terminal(fn.value)
    if attr == "sleep" and recv == "time":
        return "time.sleep() (blocking)"
    if attr in ("dumps", "dump") and recv in _SERIALIZE_MODULES:
        return f"{recv}.{attr}() (serialization)"
    if attr in ("compress", "decompress") and recv in _COMPRESS_MODULES:
        return f"{recv}.{attr}() (compression)"
    if attr == "sendall":
        return "socket sendall() (blocking network I/O)"
    if attr in ("create_connection", "getaddrinfo") and recv == "socket":
        return f"socket.{attr}() (network I/O)"
    if attr == "urlopen":
        return "urlopen() (network I/O)"
    if attr in _OS_BLOCKING and recv in ("os", "path", "shutil"):
        return f"{recv}.{attr}() (file-system I/O)"
    if attr in _SUBPROCESS_BLOCKING and (
            recv == "subprocess" or "proc" in recv.lower()):
        return f"{recv}.{attr}() (subprocess)"
    if attr in _PATH_IO:
        return f".{attr}() (file I/O)"
    if attr == "join" and "thread" in recv.lower():
        return f"{recv}.join() (thread join)"
    if attr in ("write", "read") and recv in _FILEY_RECEIVERS:
        return f"{recv}.{attr}() (stream I/O)"
    return None


# ------------------------------------------------------------- exec model


@dataclass
class _BufferSite:
    identity: str         # "egress.RemoteWriteShipper.buffer" | "store.FleetStore.*"
    path: str
    line: int


@dataclass
class ExecContextModel:
    """Derived execution-context state, memoized per lint context."""

    model: ConcurrencyModel
    # fq -> (line, why) direct blocking operations
    direct_blocking: dict[str, list[tuple[int, str]]] = field(
        default_factory=dict)
    # fq -> (why, via-callee | None) transitive blocking reach
    reaches_blocking: dict[str, tuple[str, str | None]] = field(
        default_factory=dict)
    # lock key -> (holder fq, why) — some holder may block while holding
    blocking_holders: dict[str, tuple[str, str]] = field(
        default_factory=dict)
    loop_funcs: set[str] = field(default_factory=set)
    buffers: dict[str, _BufferSite] = field(default_factory=dict)
    # buffer identity -> [(mover fq, call line, path, roles)]
    mover_sites: dict[str, list[tuple[str, int, str, tuple[str, ...]]]] = \
        field(default_factory=dict)

    def loop_role_of(self, fq: str) -> str | None:
        for role in self.model.roles.get(fq, {}):
            if role in LOOP_ROLES:
                return role
        return None

    def blocking_chain(self, start: str) -> list[str]:
        chain = [start]
        cur: str | None = start
        while cur is not None and cur in self.reaches_blocking:
            nxt = self.reaches_blocking[cur][1]
            if nxt is None:
                break
            chain.append(nxt)
            cur = nxt
        return chain


def build_exec_model(model: ConcurrencyModel) -> ExecContextModel:
    em = ExecContextModel(model=model)
    _scan_direct_blocking(em)
    _propagate_blocking(em)
    _find_blocking_holders(em)
    em.loop_funcs = {
        fq for fq, roles in model.roles.items()
        if any(r in LOOP_ROLES for r in roles)
    }
    _discover_buffers(em)
    _collect_mover_sites(em)
    return em


def get_exec_model(ctx: "LintContext") -> ExecContextModel:
    """Memoized on the context: the three execution-context rules share
    one derived pass over the (also memoized) concurrency model."""
    cached = getattr(ctx, "_execcontext_model", None)
    if cached is None:
        cached = build_exec_model(get_model(ctx))
        ctx._execcontext_model = cached  # type: ignore[attr-defined]
    return cached


def _scan_direct_blocking(em: ExecContextModel) -> None:
    for fq, fi in em.model.functions.items():
        hits: list[tuple[int, str]] = []
        for cs in fi.calls:
            why = _blocking_offence(cs.node)
            if why is not None:
                hits.append((cs.line, why))
        if hits:
            em.direct_blocking[fq] = hits


def _propagate_blocking(em: ExecContextModel) -> None:
    reaches = em.reaches_blocking
    for fq, hits in em.direct_blocking.items():
        reaches[fq] = (hits[0][1], None)
    changed = True
    while changed:
        changed = False
        for fq, fi in em.model.functions.items():
            if fq in reaches:
                continue
            for cs in fi.calls:
                hit = next((c for c in cs.callees if c in reaches), None)
                if hit is not None:
                    reaches[fq] = (reaches[hit][0], hit)
                    changed = True
                    break


def _find_blocking_holders(em: ExecContextModel) -> None:
    """Locks under which SOME holder performs (or transitively reaches)
    blocking work. Acquiring such a lock on the loop can park the loop
    for the holder's blocking operation."""
    m = em.model
    for fq, fi in m.functions.items():
        entry = frozenset(m.entry_held.get(fq, ()))
        for cs in fi.calls:
            held = entry | cs.held
            if not held:
                continue
            why = _blocking_offence(cs.node)
            if why is None:
                hit = next(
                    (c for c in cs.callees if c in em.reaches_blocking),
                    None)
                if hit is None:
                    continue
                why = em.reaches_blocking[hit][0]
            for key in held:
                em.blocking_holders.setdefault(key, (fq, why))


def _discover_buffers(em: ExecContextModel) -> None:
    """WalBuffer construction sites. ``self.X = WalBuffer(...)`` yields
    identity ``mod.Class.X``; construction into a local/container inside a
    class method yields the class bucket ``mod.Class.*``."""
    for fq, fi in em.model.functions.items():
        if isinstance(fi.node, ast.Lambda):
            continue
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            if not (isinstance(node.value, ast.Call)
                    and _terminal(node.value.func) == "WalBuffer"):
                continue
            ident: str | None = None
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self" and fi.cls is not None):
                    ident = f"{fi.mod}.{fi.cls}.{tgt.attr}"
                    break
            if ident is None and fi.cls is not None:
                ident = f"{fi.mod}.{fi.cls}.*"
            if ident is None:
                ident = f"{fi.mod}.{fq.rsplit('.', 1)[-1]}.*"
            em.buffers.setdefault(
                ident, _BufferSite(ident, fi.relpath, node.lineno))


def _collect_mover_sites(em: ExecContextModel) -> None:
    m = em.model
    # class (mod, cls) -> identities owned by it
    by_class: dict[tuple[str, str], list[str]] = {}
    for ident in em.buffers:
        parts = ident.split(".")
        mod, cls = ".".join(parts[:-2]), parts[-2]
        by_class.setdefault((mod, cls), []).append(ident)
    for fq, fi in m.functions.items():
        for cs in fi.calls:
            fn = cs.node.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in _MOVER_NAMES):
                continue
            recv = fn.value
            # `self._advance(...)` inside the buffer class itself is the
            # internal delegation chain, not an external mover.
            if isinstance(recv, ast.Name) and recv.id == "self":
                continue
            ident = None
            if (isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self" and fi.cls is not None):
                cand = f"{fi.mod}.{fi.cls}.{recv.attr}"
                if cand in em.buffers:
                    ident = cand
            if ident is None and fi.cls is not None:
                owned = by_class.get((fi.mod, fi.cls), [])
                if len(owned) == 1:
                    ident = owned[0]
            if ident is None:
                continue
            roles = tuple(sorted(m.roles.get(fq, {})))
            em.mover_sites.setdefault(ident, []).append(
                (fq, cs.line, fi.relpath, roles))


# ----------------------------------------------------- rule: loop-blocking


def check_loop_blocking(ctx: "LintContext") -> list[Diagnostic]:
    em = get_exec_model(ctx)
    m = em.model
    out: list[Diagnostic] = []
    allowed = {a.func for a in LOOP_ALLOWED}
    for a in LOOP_ALLOWED:
        if a.func not in m.functions:
            out.append(Diagnostic(
                "loop-blocking", ERROR,
                f"{_PKG}/analysis/execcontext.py", 1,
                f"LOOP_ALLOWED names {a.func}() but no such function "
                f"exists — the allowance table rotted; update it",
            ))
    seen: set[tuple[str, int]] = set()
    for fq in sorted(em.loop_funcs):
        if fq in allowed:
            continue
        fi = m.functions[fq]
        role = em.loop_role_of(fq) or LOOP_ROLES[0]
        chain = m.role_chain(fq, role)
        via = " -> ".join(q for q, _, _ in chain) or fq
        for line, why in em.direct_blocking.get(fq, ()):
            key = (fi.relpath, line)
            if key in seen:
                continue
            seen.add(key)
            out.append(Diagnostic(
                "loop-blocking", ERROR, fi.relpath, line,
                f"{why} in {fq}(), which runs inline on the event loop "
                f"(role '{role}' via {via}) — one stalled callback stalls "
                f"every connection; defer through _WorkerPool.submit or "
                f"the StreamPump, or pre-render off-loop",
            ))
        for acq in fi.acquires:
            holder = em.blocking_holders.get(acq.key)
            if holder is None:
                continue
            hfq, hwhy = holder
            if hfq == fq:
                continue  # the direct finding above already names it
            key = (fi.relpath, acq.line)
            if key in seen:
                continue
            seen.add(key)
            out.append(Diagnostic(
                "loop-blocking", ERROR, fi.relpath, acq.line,
                f"{fq}() acquires {acq.key} on the event loop (role "
                f"'{role}'), but {hfq}() performs {hwhy} while holding "
                f"it — the loop can park for the holder's I/O; shrink "
                f"the holder's critical section or hand the read to a "
                f"worker",
            ))
    return out


# ------------------------------------------------ rule: durability-ordering


def _state_name_constants(ctx: "LintContext") -> dict[str, str]:
    """Named constants (module- or class-level ``NAME = "literal"``) whose
    value is a state-file basename, tree-wide — so a write through
    ``STATUS_NAME`` imported from another module still resolves."""
    consts: dict[str, str] = {}

    def scan(body: list[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, ast.ClassDef):
                scan(stmt.body)
                continue
            if not isinstance(stmt, ast.Assign):
                continue
            if not (isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                continue
            if not _is_state_basename(stmt.value.value):
                continue
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    consts[tgt.id] = stmt.value.value
    for tree in ctx.package_trees.values():
        scan(tree.body)
    return consts


def _is_state_basename(value: str) -> bool:
    base = value.rsplit("/", 1)[-1]
    return any(fnmatchcase(base, pat) for pat in STATE_FILE_PATTERNS)


def _mentions_state_path(expr: ast.expr, consts: dict[str, str]) -> bool:
    for n in ast.walk(expr):
        if (isinstance(n, ast.Constant) and isinstance(n.value, str)
                and _is_state_basename(n.value)):
            return True
        if isinstance(n, ast.Name) and n.id in consts:
            return True
        if isinstance(n, ast.Attribute) and n.attr in consts:
            return True
    return False


def _write_mode(call: ast.Call) -> bool:
    mode: ast.expr | None = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False
    return (isinstance(mode, ast.Constant) and isinstance(mode.value, str)
            and any(c in mode.value for c in "wax+"))


def check_durability_ordering(ctx: "LintContext") -> list[Diagnostic]:
    em = get_exec_model(ctx)
    out: list[Diagnostic] = []
    out.extend(_check_state_writes(ctx, em))
    out.extend(_check_mover_fsync_reach(em))
    out.extend(_check_single_mover(em))
    return out


def _check_state_writes(
    ctx: "LintContext", em: ExecContextModel
) -> list[Diagnostic]:
    """Leg (a): raw writes to state paths bypass the crash discipline —
    a torn ``cursor.json`` replays acked records (or worse, loses the
    clean prefix). Everything must route through persist.atomic_write."""
    consts = _state_name_constants(ctx)
    out: list[Diagnostic] = []
    seen: set[tuple[str, int]] = set()
    for fq, fi in em.model.functions.items():
        for cs in fi.calls:
            call = cs.node
            fn = call.func
            target: ast.expr | None = None
            how = ""
            if (isinstance(fn, ast.Name) and fn.id == "open"
                    and call.args and _write_mode(call)):
                target, how = call.args[0], "open(.., 'w')"
            elif (isinstance(fn, ast.Attribute)
                    and fn.attr in ("write_text", "write_bytes")):
                target, how = fn.value, f".{fn.attr}()"
            elif (isinstance(fn, ast.Attribute)
                    and fn.attr in ("replace", "rename")
                    and _terminal(fn.value) == "os"
                    and len(call.args) >= 2
                    and "atomic_write" not in fq):
                target, how = call.args[1], f"os.{fn.attr}()"
            if target is None:
                continue
            if not _mentions_state_path(target, consts):
                continue
            key = (fi.relpath, cs.line)
            if key in seen:
                continue
            seen.add(key)
            out.append(Diagnostic(
                "durability-ordering", ERROR, fi.relpath, cs.line,
                f"raw {how} on a durability state path in {fq}() — a "
                f"crash mid-write tears the file and corrupts replay; "
                f"route it through persist.atomic_write (write temp, "
                f"fsync, rename, fsync dir)",
            ))
    return out


def _check_mover_fsync_reach(em: ExecContextModel) -> list[Diagnostic]:
    """Leg (b): a cursor mover that returns without the new cursor being
    fsync-reachable lets a crash resurrect acked records. ``_advance``'s
    atomic_write IS the sink; delegating movers reach it transitively."""
    m = em.model
    # Sink: direct os.fsync/fdatasync, or a call resolving to a function
    # whose name ends in "atomic_write" (persist.atomic_write and any
    # same-contract helper a fixture stubs in).
    sinks: set[str] = set()
    for fq, fi in m.functions.items():
        if fq.endswith("atomic_write"):
            sinks.add(fq)
            continue
        for cs in fi.calls:
            fn = cs.node.func
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in ("fsync", "fdatasync")):
                sinks.add(fq)
                break
    reach_sink: set[str] = set(sinks)
    changed = True
    while changed:
        changed = False
        for fq, fi in m.functions.items():
            if fq in reach_sink:
                continue
            for cs in fi.calls:
                if any(c in reach_sink for c in cs.callees):
                    reach_sink.add(fq)
                    changed = True
                    break
    out: list[Diagnostic] = []
    for (mod, cls), ci in sorted(m.classes.items()):
        if not _is_cursor_class(ci.node):
            continue
        for name in _MOVER_NAMES:
            fq = ci.methods.get(name)
            if fq is None or fq not in m.functions:
                continue
            if fq in reach_sink:
                continue
            fi = m.functions[fq]
            out.append(Diagnostic(
                "durability-ordering", ERROR, fi.relpath,
                fi.node.lineno,
                f"cursor mover {fq}() returns without an fsync-reachable "
                f"cursor write (no path reaches persist.atomic_write or "
                f"os.fsync) — a crash after the move re-delivers or "
                f"resurrects records; persist the cursor through "
                f"atomic_write before returning",
            ))
    return out


def _is_cursor_class(node: ast.ClassDef) -> bool:
    """A class that owns an on-disk cursor: declares CURSOR_NAME (or any
    cursor-named attribute/method) in its body."""
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) and "CURSOR" in tgt.id.upper():
                    return True
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and "cursor" in stmt.name.lower():
            return True
    return False


def _check_single_mover(em: ExecContextModel) -> list[Diagnostic]:
    """Leg (c): exactly one DECLARED mover role per WalBuffer cursor."""
    m = em.model
    out: list[Diagnostic] = []
    matched_rules: set[str] = set()
    for ident, site in sorted(em.buffers.items()):
        rule = next(
            (r for r in CURSOR_MOVERS if fnmatchcase(ident, r.buffer)),
            None)
        if rule is None:
            out.append(Diagnostic(
                "durability-ordering", ERROR, site.path, site.line,
                f"WalBuffer cursor '{ident}' has no declared mover role — "
                f"every cursor has exactly ONE moving thread; add a "
                f"CursorMoverRule for it in analysis/execcontext.py "
                f"naming that thread (and why)",
            ))
            continue
        matched_rules.add(rule.buffer)
        for fq, line, path, roles in em.mover_sites.get(ident, ()):
            for role in roles:
                if fnmatchcase(role, rule.role):
                    continue
                out.append(Diagnostic(
                    "durability-ordering", ERROR, path, line,
                    f"{fq}() moves the '{ident}' cursor from thread "
                    f"'{role}', but its declared single mover is "
                    f"'{rule.role}' — {rule.reason}",
                ))
    for rule in CURSOR_MOVERS:
        if rule.demo:
            continue
        if rule.buffer not in matched_rules:
            out.append(Diagnostic(
                "durability-ordering", ERROR,
                f"{_PKG}/analysis/execcontext.py", 1,
                f"CURSOR_MOVERS declares buffer pattern '{rule.buffer}' "
                f"but no such WalBuffer construction site exists — the "
                f"table rotted; update it",
            ))
    return out


# ------------------------------------------------------- rule: fork-safety


_FD_FACTORIES: dict[tuple[str, str], str] = {
    ("socket", "socket"): "socket",
    ("socket", "socketpair"): "socketpair",
    ("socket", "create_connection"): "socket",
    ("os", "pipe"): "pipe",
    ("mmap", "mmap"): "mmap",
    ("selectors", "DefaultSelector"): "selector",
}


def _fd_kind(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return _FD_FACTORIES.get((_terminal(fn.value), fn.attr))
    if isinstance(fn, ast.Name):
        # `from socket import socketpair` style — match by bare name.
        for (_mod, name), kind in _FD_FACTORIES.items():
            if fn.id == name and name != "socket":
                return kind
    return None


def check_fork_safety(ctx: "LintContext") -> list[Diagnostic]:
    """Direct fork/multiprocessing use and import-time thread/fd creation.

    The coming multi-core plane forks AFTER config load and BEFORE the
    serving threads start; anything spawned or opened at import time is
    silently duplicated into every worker (locks held by a thread that
    does not exist post-fork, double-owned fds, re-delivered WAL
    records). Until the sanctioned pre-fork entry point lands, direct
    fork primitives are findings; the full pre-fork resource inventory
    is the committed deploy/fork-inventory.json artifact."""
    em = get_exec_model(ctx)
    out: list[Diagnostic] = []
    for fq, fi in em.model.functions.items():
        for cs in fi.calls:
            fn = cs.node.func
            if isinstance(fn, ast.Attribute):
                recv = _terminal(fn.value)
                if fn.attr in ("fork", "forkpty") and recv == "os":
                    out.append(Diagnostic(
                        "fork-safety", ERROR, fi.relpath, cs.line,
                        f"os.{fn.attr}() in {fq}() — there is no "
                        f"sanctioned pre-fork point yet; the multi-core "
                        f"plane must fork through a reviewed entry that "
                        f"replays deploy/fork-inventory.json",
                    ))
                elif (recv == "multiprocessing"
                        and fn.attr in ("Process", "Pool")):
                    out.append(Diagnostic(
                        "fork-safety", ERROR, fi.relpath, cs.line,
                        f"multiprocessing.{fn.attr} in {fq}() — fork-based "
                        f"workers duplicate every live lock/fd/thread "
                        f"invisibly; the serving plane's pre-fork design "
                        f"owns process fan-out",
                    ))
    # Import-time hazards: module top-level statements run before ANY
    # pre-fork point can exist.
    for relpath, tree in ctx.package_trees.items():
        for stmt in tree.body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    break
                if not isinstance(node, ast.Call):
                    continue
                if (_terminal(node.func) == "Thread"
                        or (isinstance(node.func, ast.Attribute)
                            and node.func.attr == "start"
                            and _terminal(node.func.value) == "Thread")):
                    out.append(Diagnostic(
                        "fork-safety", ERROR, relpath, node.lineno,
                        "thread created at import time — it exists before "
                        "any pre-fork point and silently dies in forked "
                        "workers; spawn from an explicit start() path",
                    ))
                elif _fd_kind(node) is not None:
                    out.append(Diagnostic(
                        "fork-safety", ERROR, relpath, node.lineno,
                        f"{_fd_kind(node)} created at import time — the "
                        f"fd would be shared by every forked worker "
                        f"(cross-process double reads/writes); create it "
                        f"inside an explicit start() path",
                    ))
    return out


def fork_inventory(model: ConcurrencyModel) -> dict:
    """The committed deploy/fork-inventory.json artifact: every resource
    that would be live at a pre-fork point, keyed by STABLE identities
    (qualnames + paths, no line numbers — lock-graph discipline, so the
    artifact churns only on structural change)."""
    threads = sorted({
        (r.role, r.func, r.via, r.path) for r in model.roots
    })
    fds: set[tuple[str, str, str, str]] = set()
    for fq, fi in model.functions.items():
        for cs in fi.calls:
            kind = _fd_kind(cs.node)
            if kind is None:
                continue
            retained = _retained_target(fi, cs.node)
            fds.add((kind, fq, retained or "<transient>", fi.relpath))
    return {
        "comment": (
            "Rendered by `python -m tpu_pod_exporter.analysis "
            "--fork-inventory` (make fork-inventory). Reviewed artifact "
            "for the multi-core pre-fork plane: every thread, lock, and "
            "kernel-object creation site that may be live when the "
            "process forks. CI diffs it; a change means the pre-fork "
            "surface changed and must be re-reviewed."
        ),
        "threads": [
            {"role": role, "entry": func, "via": via, "site": path}
            for role, func, via, path in threads
        ],
        "locks": [
            {"key": lk.key, "kind": lk.kind, "path": lk.path}
            for lk in sorted(model.locks.values(), key=lambda k: k.key)
        ],
        "kernel_objects": [
            {"kind": kind, "creator": fq, "retained_as": tgt, "path": path}
            for kind, fq, tgt, path in sorted(fds)
        ],
    }


def _retained_target(fi: _FuncInfo, call: ast.Call) -> str | None:
    """If the creation call's result is stored (``self.X = ...`` or a
    module global), the attribute/global name — retained kernel objects
    are the ones a fork duplicates."""
    if isinstance(fi.node, ast.Lambda):
        return None
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Assign):
            continue
        found = any(n is call for n in ast.walk(node.value))
        if not found:
            continue
        for tgt in node.targets:
            name = _target_name(tgt)
            if name is not None:
                return name
    return None


def _target_name(tgt: ast.expr) -> str | None:
    """Only ``self.X`` targets count as retained — a bare local name dies
    with the call (module-level creations never appear here: top-level
    code is not in model.functions, the import-time check owns it)."""
    if (isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name) and tgt.value.id == "self"):
        return f"self.{tgt.attr}"
    if isinstance(tgt, ast.Tuple):  # self._r, self._w = socketpair()
        names = [_target_name(e) for e in tgt.elts]
        if any(n is not None for n in names):
            return ", ".join(n or "_" for n in names)
    return None


# ------------------------------------------------- loop-witness cross-check


def _static_qualname(module: str, qualname: str, line: int) -> str | None:
    """Map a runtime (module, __qualname__, firstlineno) identity onto the
    static model's naming scheme: ``a.<locals>.b`` -> ``a.<b>``, a final
    ``<lambda>`` -> ``<lambda@LINE>``."""
    if module == _PKG:
        mod = ""
    elif module.startswith(_PKG + "."):
        mod = module[len(_PKG) + 1:]
    else:
        return None
    parts = qualname.split(".<locals>.")
    mapped = [parts[0]]
    for part in parts[1:]:
        mapped.append(f"<{part}>")
    if mapped[-1] in ("<lambda>", "<<lambda>>"):
        mapped[-1] = f"<lambda@{line}>"
    inner = ".".join(mapped)
    return f"{mod}.{inner}" if mod else inner


def cross_check_loop(model: ConcurrencyModel, dump: dict) -> list[str]:
    """Loop-witness dump vs static model. Empty list = every callback the
    loop actually executed is loop-role-tagged statically and no inline
    stall crossed the threshold.

    Failure classes:
      * a witnessed stall — an inline callback over the threshold (the
        loop-blocking contract violated at runtime);
      * a witnessed package callback the static model has no function
        for (discovery/materialization rotted);
      * a witnessed package callback the model knows but does NOT tag
        with the loop role (role propagation rotted — the static half
        would never check it against the blocking set)."""
    problems: list[str] = []
    for stall in dump.get("stalls", []):
        problems.append(
            f"loop stall: {stall.get('qualname', '?')} "
            f"({stall.get('kind', '?')}) ran "
            f"{stall.get('ms', '?')} ms inline on the loop "
            f"(threshold {dump.get('meta', {}).get('threshold_ms', '?')} "
            f"ms)")
    for cb in dump.get("callbacks", []):
        module = cb.get("module", "")
        if not isinstance(module, str) or not module.startswith(_PKG):
            continue  # stdlib/test callables cannot be in the model
        fq = _static_qualname(
            module, cb.get("qualname", ""), int(cb.get("line", 0)))
        if fq is None:
            continue
        if fq not in model.functions:
            problems.append(
                f"loop-executed callback {module}.{cb.get('qualname')} "
                f"has no static identity ({fq} not in the model) — the "
                f"call-graph materialization in analysis/concurrency.py "
                f"missed it")
            continue
        roles = model.roles.get(fq, {})
        if not any(r in LOOP_ROLES for r in roles):
            problems.append(
                f"loop-executed callback {fq} is not loop-role-tagged in "
                f"the static model (roles: "
                f"{sorted(roles) or ['<none>']}) — the loop-blocking "
                f"rule would never inspect it; extend CALLBACK_ROLES or "
                f"the role fixpoint")
    return problems
