"""Lint engine: schema extraction, file walking, disables, baseline.

Everything is plain ``ast`` over source text — the linter never imports the
modules it checks (so it lints cleanly on hosts missing optional deps like
numpy or grpc, and a syntax error is a diagnostic, not a crash).
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

from tpu_pod_exporter.analysis.diagnostics import Diagnostic, parse_disables
from tpu_pod_exporter.analysis.rules import ALL_RULES

# Files never linted: vendored protobuf output and the native build tree.
_EXCLUDED_SUFFIXES = ("_pb2.py", "_pb2_grpc.py")

_SCHEMA_RELPATH = "tpu_pod_exporter/metrics/schema.py"
_CONFIG_RELPATH = "tpu_pod_exporter/config.py"
_DOC_RELPATHS = ("README.md", "deploy/RUNBOOK.md")


@dataclass
class SchemaRegistry:
    """What metrics/schema.py defines, extracted statically."""

    # Every module-level name schema.py binds (specs, label tuples, lists,
    # helpers) — the legal right-hand sides of ``schema.X``.
    schema_names: set[str] = field(default_factory=set)
    # Every legal exposition family name, including histogram children.
    metric_names: set[str] = field(default_factory=set)


def _spec_name_from_call(call: ast.Call) -> str | None:
    """The ``name=...`` of a MetricSpec/HistogramSpec constructor literal."""
    fn = call.func
    ctor = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
    if ctor not in ("MetricSpec", "HistogramSpec"):
        return None
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant):
            return str(kw.value.value)
    if call.args and isinstance(call.args[0], ast.Constant):
        return str(call.args[0].value)
    return None


def build_registry(schema_src: str) -> SchemaRegistry:
    reg = SchemaRegistry()
    tree = ast.parse(schema_src)
    for stmt in tree.body:
        targets: list[ast.expr] = []
        value: ast.expr | None = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.target is not None:
            targets, value = [stmt.target], stmt.value
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            reg.schema_names.add(stmt.name)
            continue
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                reg.schema_names.add(alias.asname or alias.name.split(".")[0])
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                reg.schema_names.add(t.id)
        if isinstance(value, ast.Call):
            name = _spec_name_from_call(value)
            if name:
                ctor = value.func
                reg.metric_names.add(name)
                is_hist = (
                    isinstance(ctor, ast.Name) and ctor.id == "HistogramSpec"
                )
                if is_hist:
                    # HistogramSpec renders one parent family plus derived
                    # _bucket/_count/_sum lines (and the internal _lines
                    # family key) — all legal references.
                    for suffix in ("_bucket", "_count", "_sum", "_lines"):
                        reg.metric_names.add(name + suffix)
    return reg


@dataclass
class LintContext:
    """Cross-file facts the rules consume."""

    registry: SchemaRegistry
    # relpath -> parsed module, for whole-tree rules.
    package_trees: dict[str, ast.Module] = field(default_factory=dict)
    # (field name, lineno in config.py) for the flag rules.
    config_fields: list[tuple[str, int]] = field(default_factory=list)
    config_relpath: str = _CONFIG_RELPATH
    docs_text: str = ""


def _config_fields(config_src: str) -> list[tuple[str, int]]:
    tree = ast.parse(config_src)
    out: list[tuple[str, int]] = []
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef) and stmt.name == "ExporterConfig":
            for s in stmt.body:
                if isinstance(s, ast.AnnAssign) and isinstance(s.target, ast.Name):
                    out.append((s.target.id, s.lineno))
    return out


def _apply_disables(
    findings: list[Diagnostic], src_lines: list[str]
) -> list[Diagnostic]:
    kept = []
    for d in findings:
        line = src_lines[d.line - 1] if 0 < d.line <= len(src_lines) else ""
        if d.rule not in parse_disables(line):
            kept.append(d)
    return kept


def lint_source(
    src: str, relpath: str, ctx: LintContext, tree: ast.Module | None = None
) -> list[Diagnostic]:
    """Run every per-file rule over one module's source text. ``tree``
    reuses an already-parsed module (lint_package passes the one
    build_context parsed — the second parse was pure waste)."""
    if tree is None:
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            return [Diagnostic(
                "syntax", "error", relpath, e.lineno or 0,
                f"cannot parse: {e.msg}",
            )]
    src_lines = src.splitlines()
    findings: list[Diagnostic] = []
    for rule in ALL_RULES:
        if rule.check_file is not None:
            findings.extend(rule.check_file(tree, src_lines, relpath, ctx))
    return _apply_disables(findings, src_lines)


def _iter_package_files(root: str, package: str) -> list[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, package)):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py") or fn.endswith(_EXCLUDED_SUFFIXES):
                continue
            out.append(os.path.join(dirpath, fn))
    return out


def build_context(root: str, package: str = "tpu_pod_exporter") -> LintContext:
    schema_path = os.path.join(root, *_SCHEMA_RELPATH.split("/"))
    with open(schema_path) as f:
        registry = build_registry(f.read())
    docs = []
    for rel in _DOC_RELPATHS:
        path = os.path.join(root, *rel.split("/"))
        if os.path.exists(path):
            with open(path) as f:
                docs.append(f.read())
    ctx = LintContext(registry=registry, docs_text="\n".join(docs))
    for path in _iter_package_files(root, package):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path) as f:
            src = f.read()
        try:
            ctx.package_trees[relpath] = ast.parse(src)
        except SyntaxError:
            continue  # reported by lint_source
        if relpath == _CONFIG_RELPATH:
            ctx.config_fields = _config_fields(src)
    return ctx


def lint_package(
    root: str, package: str = "tpu_pod_exporter"
) -> list[Diagnostic]:
    """Lint the whole package under ``root``; returns ordered findings
    (disable comments applied, baseline NOT applied — that's the CLI's
    job, so tests can inspect raw findings)."""
    ctx = build_context(root, package)
    findings: list[Diagnostic] = []
    for path in _iter_package_files(root, package):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path) as f:
            findings.extend(lint_source(
                f.read(), relpath, ctx, tree=ctx.package_trees.get(relpath)
            ))
    for rule in ALL_RULES:
        if rule.check_tree is not None:
            tree_findings = rule.check_tree(ctx)
            # Tree-wide findings honor disable comments on their target
            # line too (e.g. a config field annotated as intentionally
            # undocumented).
            by_file: dict[str, list[Diagnostic]] = {}
            for d in tree_findings:
                by_file.setdefault(d.path, []).append(d)
            for relpath, ds in by_file.items():
                path = os.path.join(root, *relpath.split("/"))
                try:
                    with open(path) as f:
                        src_lines = f.read().splitlines()
                except OSError:
                    src_lines = []
                findings.extend(_apply_disables(ds, src_lines))
    findings.sort(key=lambda d: (d.path, d.line, d.rule))
    return findings


# ------------------------------------------------------------------ baseline


def load_baseline(path: str) -> list[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    entries = doc.get("findings", []) if isinstance(doc, dict) else []
    return [e for e in entries if isinstance(e, dict)]


def finding_fingerprint(
    d: Diagnostic, root: str,
    lines_cache: dict[str, list[str]] | None = None,
) -> str:
    lines = lines_cache.get(d.path) if lines_cache is not None else None
    if lines is None:
        try:
            with open(os.path.join(root, *d.path.split("/"))) as f:
                lines = f.read().splitlines()
        except OSError:
            lines = []
        if lines_cache is not None:
            lines_cache[d.path] = lines
    text = lines[d.line - 1] if 0 < d.line <= len(lines) else ""
    return d.fingerprint(text)


def apply_baseline(
    findings: list[Diagnostic], baseline: list[dict], root: str
) -> tuple[list[Diagnostic], int]:
    """Drop findings present in the baseline (multiset semantics: N
    grandfathered instances excuse at most N live ones). Returns (new
    findings, how many were suppressed by the baseline)."""
    budget: dict[str, int] = {}
    for e in baseline:
        fp = e.get("fingerprint", "")
        budget[fp] = budget.get(fp, 0) + 1
    fresh = []
    suppressed = 0
    cache: dict[str, list[str]] = {}
    for d in findings:
        fp = finding_fingerprint(d, root, cache)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            suppressed += 1
        else:
            fresh.append(d)
    return fresh, suppressed


def baseline_document(findings: list[Diagnostic], root: str) -> dict:
    cache: dict[str, list[str]] = {}
    return {
        "comment": (
            "Grandfathered exporter-lint findings. Entries are matched by "
            "fingerprint (rule + file + offending line text), so fixing a "
            "line retires its entry and shifting line numbers does not. "
            "Update with: python -m tpu_pod_exporter.analysis "
            "--update-baseline"
        ),
        "findings": [
            {
                "rule": d.rule,
                "path": d.path,
                "line": d.line,
                "message": d.message,
                "fingerprint": finding_fingerprint(d, root, cache),
            }
            for d in findings
        ],
    }
