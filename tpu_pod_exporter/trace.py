"""End-to-end poll tracing — per-phase spans, slow-poll stack profiling.

The supervision layer (``supervisor.py``) says *that* a poll degraded and
the phase histograms say *how often*, but neither says where the 1.8 s of a
slow poll went or what the poll thread was doing while the deadline burned.
This module closes that gap with three zero-dependency pieces:

- **Spans.** Every collector poll (and every aggregator round) becomes a
  :class:`PollTrace`: a root span plus one child span per supervised phase
  (device read, attribution, process scan, join, publish, history append,
  persist, egress / per-target scrape, history fallback). The post-swap
  phases (history append, persist, egress) are deliberately excluded from
  the publish/total timings they would otherwise inflate — each is its own
  span and its own phase-histogram label. Each span carries a status
  (``ok|err|abandoned|skipped``), the source breaker's state at entry, and
  byte/series counts, and collects free-form events — the supervisor and
  the chaos injector annotate the active span, so a wedge incident reads
  as a causal story instead of a pile of counters.
- **Slow-poll profiler.** When a poll runs past ``--trace-slow-poll-s``,
  :class:`StackSampler` captures the poll thread's Python stack (plus any
  ``tpu-sup-*`` phase-worker threads — a supervised hang blocks the worker,
  not the poll thread) via ``sys._current_frames()`` at ~50 Hz for the
  remainder of the poll and attaches the collapsed stacks to the trace: a
  hang the PR 2 deadline abandons comes with the exact frame it was parked
  in.
- **Propagation.** The aggregator stamps a W3C ``traceparent`` header on
  its scrape fan-out; the exporter's ``/metrics`` handler records a scrape
  span under that remote context, so the aggregator's round trace joins
  the node-side scrape span for true cross-tier latency attribution.

Finished traces land in a bounded ring (:class:`TraceStore`, the same
hard-bounded eviction discipline as ``history.py``'s rings) and export as
Chrome ``trace_event`` JSON via ``GET /debug/trace?last=N`` — loopback-only
by default like every other ``/debug/*`` route, and copy-then-serialize so
export never blocks the poll thread.

Thread-local context (:func:`current_ids`) lets the JSON log formatter and
:class:`~tpu_pod_exporter.utils.RateLimitedLogger` stamp ``trace_id`` /
``span_id`` onto every log line emitted inside a poll; the supervisor
propagates the context onto its worker threads so even a fenced worker's
chaos annotations land on the right span.

``python -m tpu_pod_exporter.trace --replay trace.jsonl`` replays a
recorded backend trace through a traced collector and prints a rendered
trace tree (``make trace-demo``); ``--overhead-check`` measures tracing-on
vs tracing-off poll-loop CPU and fails loudly past a budget (CI smoke).
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import time
from collections import deque
from typing import TYPE_CHECKING, Callable, Sequence

if TYPE_CHECKING:  # typing only — no runtime import cost
    import types

# Span statuses — the vocabulary the collector maps phase outcomes onto.
OK = "ok"
ERR = "err"
ABANDONED = "abandoned"  # phase deadline hit; worker fenced (supervisor)
SKIPPED = "skipped"      # breaker open / quarantined; no call made

# Cap on events per span: annotations are diagnostics, not a log transport.
MAX_SPAN_EVENTS = 16

_tls = threading.local()


def new_trace_id() -> str:
    """16-byte lowercase hex, the W3C trace-id shape (one per poll, so a
    real random read is affordable here)."""
    return os.urandom(16).hex()


# Span ids are minted ~6x per poll on the hot path: os.urandom there is a
# getrandom(2) syscall per span (measured: a visible % of poll CPU at the
# bench shape). A randomly-seeded process-global counter keeps W3C-shaped,
# process-unique, never-zero ids at the cost of one dict-free C-level
# next() — cross-process uniqueness comes from the 64-bit random seed, the
# same collision budget os.urandom(8) had.
_span_ids = itertools.count(int.from_bytes(os.urandom(8), "big") | 1)


def new_span_id() -> str:
    """8-byte lowercase hex, the W3C parent-id shape."""
    return f"{next(_span_ids) & 0xFFFFFFFFFFFFFFFF:016x}"


# ------------------------------------------------------------- TLS context


def current_span() -> "Span | None":
    """The span active on the calling thread (None outside a poll)."""
    return getattr(_tls, "span", None)


def current_ids() -> tuple[str | None, str | None]:
    """(trace_id, span_id) of the active span, or (None, None)."""
    s = getattr(_tls, "span", None)
    if s is None:
        return None, None
    return s.trace_id, s.span_id


def swap_current(span: "Span | None") -> "Span | None":
    """Set the calling thread's active span; returns the previous one.

    Used by the supervisor to carry the poll thread's span context onto
    the phase-worker thread (restore the return value in a finally)."""
    prev = getattr(_tls, "span", None)
    _tls.span = span
    return prev


def annotate(message: str) -> None:
    """Attach a free-form event to the calling thread's active span.

    No-op outside a poll — callers (supervisor, chaos) never need to know
    whether tracing is enabled."""
    s = getattr(_tls, "span", None)
    if s is not None:
        s.add_event(message)


# ------------------------------------------------------------- traceparent


def format_traceparent(trace_id: str, span_id: str) -> str:
    """W3C Trace Context header value (version 00, sampled)."""
    return f"00-{trace_id}-{span_id}-01"


_HEX_DIGITS = frozenset("0123456789abcdefABCDEF")


def _is_hex(s: str) -> bool:
    # NOT int(s, 16): that accepts '+'/'-' signs, underscores and
    # surrounding whitespace, which would let malformed ids through
    # "strict" validation and into the export verbatim.
    return all(c in _HEX_DIGITS for c in s)


def parse_traceparent(header: str) -> tuple[str, str] | None:
    """``traceparent`` header → (trace_id, parent_span_id), or None.

    Strict on the parts we consume (lengths, hex, non-zero ids), lenient on
    the rest (unknown versions parse; trailing fields ignored) — a malformed
    header from an arbitrary client must degrade to "no context", never to
    an error on the scrape path."""
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    _ver, tid, sid = parts[0], parts[1], parts[2]
    if len(tid) != 32 or len(sid) != 16:
        return None
    if not (_is_hex(tid) and _is_hex(sid)):
        return None
    if tid == "0" * 32 or sid == "0" * 16:
        return None
    return tid.lower(), sid.lower()


# ------------------------------------------------------------------- spans


class Span:
    """One timed operation within a trace. Mutable until ``dur_s`` is set
    (by ``PollTrace.end_span``); treated as immutable afterwards — the
    export path copies references, not contents."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0_wall",
                 "t0_mono", "dur_s", "status", "breaker", "attrs", "events",
                 "thread")

    def __init__(self, trace_id: str, name: str, parent_id: str | None,
                 t0_wall: float, t0_mono: float, breaker: str = "") -> None:
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.name = name
        self.t0_wall = t0_wall
        self.t0_mono = t0_mono
        self.dur_s: float | None = None
        self.status = OK
        self.breaker = breaker
        self.attrs: dict | None = None
        self.events: list | None = None
        self.thread = threading.get_ident()

    def add_event(self, message: str) -> None:
        ev = self.events
        if ev is None:
            ev = self.events = []
        if len(ev) >= MAX_SPAN_EVENTS:
            if ev[-1][1] != "…more events dropped":
                ev.append((time.time() - self.t0_wall, "…more events dropped"))  # lint: disable=wall-clock(event stamps are wall offsets from the trace wall epoch by design)
            return
        ev.append((time.time() - self.t0_wall, message))  # lint: disable=wall-clock(event stamps are wall offsets from the trace wall epoch by design)


class PollTrace:
    """One poll's (or aggregation round's) trace: a root span plus phase
    children. ``begin``/``end`` are the poll thread's depth-1 conveniences
    (they also maintain the thread-local context); ``span``/``end_span``
    are the explicit form for fan-out workers (aggregator pool threads),
    where list.append's GIL-atomicity makes concurrent span creation safe.
    """

    __slots__ = ("trace_id", "root", "spans", "profile", "profile_samples",
                 "slow", "_clock", "_wallclock")

    def __init__(self, root_name: str, clock: Callable[[], float],
                 wallclock: Callable[[], float]) -> None:
        self.trace_id = new_trace_id()
        self._clock = clock
        self._wallclock = wallclock
        self.root = Span(self.trace_id, root_name, None,
                         wallclock(), clock())
        self.spans: list[Span] = [self.root]
        # {thread label: {collapsed stack: sample count}} — written by the
        # StackSampler while this poll runs slow, read-only afterwards.
        self.profile: dict[str, dict[str, int]] | None = None
        self.profile_samples = 0
        self.slow = False

    # explicit form (any thread)

    def span(self, name: str, parent_id: str | None = None,
             breaker: str = "") -> Span:
        s = Span(self.trace_id, name,
                 parent_id if parent_id is not None else self.root.span_id,
                 self._wallclock(), self._clock(), breaker)
        self.spans.append(s)
        return s

    def end_span(self, span: Span, status: str = OK, **attrs: object) -> None:
        span.dur_s = self._clock() - span.t0_mono
        span.status = status
        if attrs:
            span.attrs = attrs

    # TLS-threaded form (poll thread only; depth 1 under the root)

    def begin(self, name: str, breaker: str = "") -> Span:
        s = self.span(name, breaker=breaker)
        _tls.span = s
        return s

    def end(self, status: str = OK, **attrs: object) -> None:
        s = getattr(_tls, "span", None)
        if s is None or s is self.root:
            return
        self.end_span(s, status, **attrs)
        _tls.span = self.root


# ------------------------------------------------------------- trace store


class TraceStore:
    """Bounded ring of finished traces plus a ring of remote-context scrape
    spans (the node side of the aggregator's fan-out propagation).

    Same eviction discipline as ``history.py``: hard-capped, oldest-out,
    allocated only for traces actually present. Readers copy *references*
    under the lock and serialize outside it (finished traces are immutable)
    — export must never block the poll thread's append."""

    # Scrape-span recording is driven by a CLIENT-supplied header on the
    # unauthenticated /metrics path, so it is rate-capped: a scraper
    # spraying forged traceparent headers must not be able to churn the
    # genuine aggregator join spans out of the ring (nor spend lock+alloc
    # per storm request). The cap is ~20x any sane fan-in — a handful of
    # aggregators at one scrape per round each.
    SCRAPE_RECORD_WINDOW_S = 10.0
    SCRAPE_RECORDS_PER_WINDOW = 64

    def __init__(self, max_traces: int = 256,
                 max_scrape_spans: int = 512,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if max_traces < 1:
            raise ValueError("max_traces must be >= 1")
        self.max_traces = max_traces
        self._clock = clock
        self._lock = threading.Lock()
        self._traces: deque[PollTrace] = deque(maxlen=max_traces)
        self._scrapes: deque[Span] = deque(maxlen=max_scrape_spans)
        self._spans = 0  # spans retained across the trace ring
        self.traces_total = 0
        self.slow_polls = 0
        self.scrape_spans_total = 0
        self.scrape_spans_dropped = 0
        self._scrape_window_start = 0.0
        self._scrape_window_count = 0

    def append(self, trace: PollTrace) -> None:
        with self._lock:
            if len(self._traces) == self._traces.maxlen:
                self._spans -= len(self._traces[0].spans)
            self._traces.append(trace)
            self._spans += len(trace.spans)
            self.traces_total += 1
            if trace.slow:
                self.slow_polls += 1

    def record_scrape(self, trace_id: str, parent_id: str, t0_wall: float,
                      dur_s: float, **attrs: object) -> Span | None:
        """Record a served-scrape span under a REMOTE trace context (from a
        ``traceparent`` header) — the join point the aggregator's round
        trace links to. Returns None when the record was dropped by the
        rate cap (see SCRAPE_RECORDS_PER_WINDOW)."""
        with self._lock:
            now = self._clock()
            if now - self._scrape_window_start >= self.SCRAPE_RECORD_WINDOW_S:
                self._scrape_window_start = now
                self._scrape_window_count = 0
            if self._scrape_window_count >= self.SCRAPE_RECORDS_PER_WINDOW:
                self.scrape_spans_dropped += 1
                return None
            self._scrape_window_count += 1
            s = Span(trace_id, "scrape", parent_id, t0_wall, t0_mono=0.0)
            s.dur_s = dur_s
            if attrs:
                s.attrs = attrs
            self._scrapes.append(s)
            self.scrape_spans_total += 1
        return s

    # Rough per-span retained cost (Span object + id strings + attrs dict)
    # for the byte accounting the memory-pressure ladder reads. An
    # estimate, not a measurement — but the SAME estimate the shed
    # decision and /debug/vars both see, which is the contract.
    SPAN_EST_BYTES = 640

    def set_max_traces(self, n: int) -> None:
        """Resize the trace ring in place, keeping the NEWEST traces — the
        memory-pressure ladder's ``trace_halved`` rung. Reversible: a
        larger ``n`` re-grows the bound (evicted traces stay gone)."""
        n = max(int(n), 1)
        with self._lock:
            if n == self.max_traces:
                return
            kept = list(self._traces)[-n:]
            self._traces = deque(kept, maxlen=n)
            self._spans = sum(len(t.spans) for t in kept)
            self.max_traces = n

    def memory_bytes(self) -> int:
        """Estimated retained bytes (trace ring + scrape-span ring) for
        the memory budget's component accounting."""
        with self._lock:
            return (self._spans + len(self._scrapes)) * self.SPAN_EST_BYTES

    def last(self, n: int) -> list[PollTrace]:
        """Newest-last reference copy of up to the last ``n`` traces."""
        with self._lock:
            if n >= len(self._traces):
                return list(self._traces)
            return [self._traces[i]
                    for i in range(len(self._traces) - n, len(self._traces))]

    def scrapes(self, n: int) -> list[Span]:
        with self._lock:
            if n >= len(self._scrapes):
                return list(self._scrapes)
            return [self._scrapes[i]
                    for i in range(len(self._scrapes) - n, len(self._scrapes))]

    def counts(self) -> tuple[int, int, int]:
        """(slow_polls, traces retained, spans retained) — the per-poll
        metrics read, allocation-light (the full stats() dict is for
        /debug/vars, not the publish hot path)."""
        with self._lock:
            return self.slow_polls, len(self._traces), self._spans

    def stats(self) -> dict:
        with self._lock:
            return {
                "traces": len(self._traces),
                "spans": self._spans,
                "traces_total": self.traces_total,
                "slow_polls": self.slow_polls,
                "scrape_spans": len(self._scrapes),
                "scrape_spans_total": self.scrape_spans_total,
                "scrape_spans_dropped": self.scrape_spans_dropped,
                "max_traces": self.max_traces,
            }


# ---------------------------------------------------- slow-poll profiler


def _collapse(frame: "types.FrameType | None") -> str:
    """One thread's stack as a collapsed ``mod.func;mod.func`` line,
    outermost first (the flamegraph folded format)."""
    out = []
    while frame is not None:
        mod = frame.f_globals.get("__name__", "?")
        out.append(f"{mod}.{frame.f_code.co_name}")
        frame = frame.f_back
    out.reverse()
    return ";".join(out)


class StackSampler:
    """Samples the poll thread's stack while a poll runs past its slow
    threshold, via ``sys._current_frames()`` (a documented-CPython atomic
    snapshot built under the GIL — a wedged thread's stack renders without
    its cooperation, same mechanism as ``/debug/stacks``).

    One daemon thread, started lazily on first :meth:`arm`. ``arm`` is
    called at poll start (cheap: one lock + event set); the sampler sleeps
    until ``delay_s`` into the poll, then samples at ``hz`` until
    :meth:`disarm` (poll finished), the per-poll sample cap, or a re-arm.
    Supervised phase workers (threads named ``tpu-sup-*``) are sampled too:
    a supervised hang blocks the worker, not the poll thread, and the whole
    point is naming the hung frame.

    Mutation contract: samples write into ``trace.profile`` only while the
    trace is still the armed one, checked under the sampler lock — after
    ``disarm`` returns, the trace is immutable and safe to serialize."""

    WORKER_PREFIX = "tpu-sup-"
    # Idle/pre-threshold scan period. arm() only wakes the sampler thread
    # when the slow threshold lands INSIDE the current scan window — at the
    # production default (slow_poll_s=1.0 > 0.5) arming is just a lock'd
    # store, because a per-poll Event.set() forces a context switch to the
    # sampler thread every poll, which measured as ~10% poll-loop CPU on a
    # single-core host. The scan loop then hits the threshold exactly (it
    # computes the precise remaining wait once it sees the armed poll).
    SCAN_PERIOD_S = 0.5

    def __init__(self, hz: float = 50.0, max_samples: int = 2048,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if hz <= 0:
            raise ValueError("hz must be positive")
        self.hz = hz
        self.max_samples = max_samples
        self._clock = clock
        self._lock = threading.Lock()
        self._wake = threading.Event()
        # (trace, sample_at_mono, poll thread ident) while a poll is armed.
        self._armed: tuple | None = None
        self._thread: threading.Thread | None = None
        self._stop = False
        self.polls_profiled = 0

    def arm(self, trace: PollTrace, delay_s: float) -> None:
        with self._lock:
            self._armed = (trace, self._clock() + delay_s,
                           threading.get_ident())
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="tpu-trace-sampler", daemon=True
                )
                self._thread.start()
        if delay_s < self.SCAN_PERIOD_S + 0.1:
            # Only thresholds inside the scan window need an early wake-up
            # (tests use tiny thresholds); see SCAN_PERIOD_S for why a
            # per-poll set() is too expensive to do unconditionally.
            self._wake.set()

    def disarm(self, trace: PollTrace) -> None:
        with self._lock:
            if self._armed is not None and self._armed[0] is trace:
                self._armed = None
            if trace.profile_samples:
                self.polls_profiled += 1

    def stop(self) -> None:
        self._stop = True
        self._wake.set()

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._armed is not None

    def _run(self) -> None:
        while not self._stop:
            with self._lock:
                st = self._armed
            if st is None:
                self._wake.wait(self.SCAN_PERIOD_S)
                self._wake.clear()
                continue
            trace, sample_at, ident = st
            now = self._clock()
            if now < sample_at:
                # Not slow yet: sleep exactly until the threshold (or a
                # re-arm wakes us for a newer short-threshold poll).
                self._wake.wait(min(sample_at - now, self.SCAN_PERIOD_S))
                self._wake.clear()
                continue
            self._sample(trace, ident)
            time.sleep(1.0 / self.hz)

    def _sample(self, trace: PollTrace, poll_ident: int) -> None:
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        targets = [(poll_ident, names.get(poll_ident, "poll"))]
        targets += [
            (tid, name) for tid, name in names.items()
            if name.startswith(self.WORKER_PREFIX) and tid != poll_ident
        ]
        with self._lock:
            st = self._armed
            if st is None or st[0] is not trace:
                return  # poll finished while we walked the frames
            if trace.profile_samples >= self.max_samples:
                self._armed = None  # cap hit: stop profiling this poll
                return
            prof = trace.profile
            if prof is None:
                prof = trace.profile = {}
            for tid, label in targets:
                frame = frames.get(tid)
                if frame is None:
                    continue
                stack = _collapse(frame)
                d = prof.setdefault(label, {})
                d[stack] = d.get(stack, 0) + 1
            trace.profile_samples += 1


# ------------------------------------------------------------------ tracer


class Tracer:
    """Owns the trace lifecycle for one poll loop: start → phase spans →
    finish (slow detection, profiler collection, store append).

    ``slow_poll_s <= 0`` disables the slow-poll profiler but keeps spans;
    ``sampler=None`` likewise. The whole tracer is optional everywhere it
    is consumed — a collector built without one runs the exact pre-trace
    code path."""

    def __init__(self, store: TraceStore, slow_poll_s: float = 1.0,
                 sampler: StackSampler | None = None, root_name: str = "poll",
                 clock: Callable[[], float] = time.monotonic,
                 wallclock: Callable[[], float] = time.time) -> None:
        self.store = store
        self.slow_poll_s = slow_poll_s
        self.root_name = root_name
        self._sampler = sampler
        self._clock = clock
        self._wallclock = wallclock

    def start_poll(self) -> PollTrace:
        t = PollTrace(self.root_name, self._clock, self._wallclock)
        # A poll aborted by a mid-poll BaseException leaves a stale TLS
        # span; starting the next poll simply overwrites it (the aborted
        # trace is dropped, never stored half-finished).
        _tls.span = t.root
        if self._sampler is not None and self.slow_poll_s > 0:
            self._sampler.arm(t, self.slow_poll_s)
        return t

    def finish(self, trace: PollTrace, status: str = OK, **attrs: object) -> PollTrace:
        trace.end_span(trace.root, status, **attrs)
        if self._sampler is not None:
            self._sampler.disarm(trace)
        trace.slow = bool(
            (self.slow_poll_s > 0 and trace.root.dur_s >= self.slow_poll_s)
            or trace.profile_samples
        )
        self.store.append(trace)
        _tls.span = None
        return trace

    def close(self) -> None:
        if self._sampler is not None:
            self._sampler.stop()


# ------------------------------------------------------------ export/render


def to_chrome_trace(traces: Sequence[PollTrace],
                    scrape_spans: Sequence[Span] = ()) -> dict:
    """Finished traces → a Chrome ``trace_event`` JSON document
    (chrome://tracing / Perfetto "JSON Array with metadata" flavor).

    Pure function over immutable finished spans — callers copy references
    out of the :class:`TraceStore` under its lock and build the (much
    larger) JSON structure here, outside it."""
    pid = os.getpid()
    events: list[dict] = []
    for t in traces:
        for s in t.spans:
            args: dict = {
                "trace_id": t.trace_id,
                "span_id": s.span_id,
                "status": s.status,
            }
            if s.parent_id:
                args["parent_id"] = s.parent_id
            if s.breaker:
                args["breaker"] = s.breaker
            if s.attrs:
                args.update(s.attrs)
            if s.events:
                args["events"] = [[round(dt, 6), msg] for dt, msg in s.events]
            if s is t.root:
                if t.slow:
                    args["slow"] = True
                if t.profile is not None:
                    args["profile"] = t.profile
                    args["profile_samples"] = t.profile_samples
            events.append({
                "name": s.name,
                "cat": "poll",
                "ph": "X",
                "ts": s.t0_wall * 1e6,  # trace_event wants microseconds
                "dur": (s.dur_s or 0.0) * 1e6,
                "pid": pid,
                "tid": s.thread,
                "args": args,
            })
    for s in scrape_spans:
        events.append({
            "name": s.name,
            "cat": "scrape",
            "ph": "X",
            "ts": s.t0_wall * 1e6,
            "dur": (s.dur_s or 0.0) * 1e6,
            "pid": pid,
            "tid": s.thread,
            "args": {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "status": s.status,
                **(s.attrs or {}),
            },
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _fmt_attrs(s: Span) -> str:
    parts = []
    if s.breaker:
        parts.append(f"breaker={s.breaker}")
    if s.attrs:
        parts.extend(f"{k}={v}" for k, v in s.attrs.items())
    return "  ".join(parts)


def render_trace(trace: PollTrace) -> str:
    """Human-readable trace tree (``make trace-demo`` output)."""
    r = trace.root
    lines = [
        f"trace {trace.trace_id[:16]}…  {r.name}  "
        f"total {1e3 * (r.dur_s or 0):.2f}ms  {r.status}"
        + ("  [SLOW]" if trace.slow else "")
    ]
    children = [s for s in trace.spans if s is not r]
    for i, s in enumerate(children):
        tee = "└─" if i == len(children) - 1 else "├─"
        extra = _fmt_attrs(s)
        lines.append(
            f"{tee} {s.name:<16} {1e3 * (s.dur_s or 0):8.2f}ms  "
            f"{s.status:<9}" + (f"  {extra}" if extra else "")
        )
        for dt, msg in s.events or ():
            pad = "   " if i == len(children) - 1 else "│  "
            lines.append(f"{pad}   +{1e3 * dt:.1f}ms  {msg}")
    if trace.profile:
        lines.append(f"profile: {trace.profile_samples} samples")
        for label, stacks in trace.profile.items():
            top = sorted(stacks.items(), key=lambda kv: -kv[1])[:3]
            for stack, n in top:
                leaf = stack.rsplit(";", 2)
                lines.append(f"  [{label}] ×{n}  …{';'.join(leaf[-2:])}")
    return "\n".join(lines)


# --------------------------------------------------------------------- CLI


def _demo_replay(path: str, polls: int, slow_poll_s: float) -> int:
    """Replay a recorded backend trace through a traced collector and print
    the rendered trace tree of the last poll (``make trace-demo``)."""
    from tpu_pod_exporter.attribution.fake import FakeAttribution
    from tpu_pod_exporter.backend.recorded import RecordedBackend
    from tpu_pod_exporter.collector import Collector
    from tpu_pod_exporter.metrics import SnapshotStore

    backend = RecordedBackend(path, loop=True)
    n = polls or len(backend)
    store = TraceStore(max_traces=max(n, 1))
    tracer = Tracer(store, slow_poll_s=slow_poll_s, sampler=StackSampler())
    collector = Collector(backend, FakeAttribution(), SnapshotStore(),
                          tracer=tracer)
    for _ in range(n):
        collector.poll_once()
    st = store.stats()
    print(f"replayed {n} polls from {path}")
    print(f"traces: {st['traces']} retained ({st['spans']} spans), "
          f"{st['slow_polls']} slow\n")
    for t in store.last(1):
        print(render_trace(t))
    tracer.close()
    return 0


def _overhead_check(polls: int, chips: int, budget: float) -> int:
    """Tracing-on vs tracing-off poll-loop CPU on the loadgen/bench shape
    (fake backend, 256 chips — the shape bench.py budgets). Exit 1 past
    the budget — the CI smoke for the 'tracing is on by default' overhead
    contract.

    Methodology: two long-lived collectors (one traced, one not) measured
    in small INTERLEAVED segments with alternating order. Whole-run A/B
    comparisons drown in scheduler/allocator drift on shared hosts
    (measured: ±10% run-to-run for the SAME mode, far above the effect);
    interleaving cancels the drift and reproduces a stable ratio."""
    from tpu_pod_exporter import utils
    from tpu_pod_exporter.attribution.fake import FakeAttribution
    from tpu_pod_exporter.backend.fake import FakeBackend
    from tpu_pod_exporter.collector import Collector
    from tpu_pod_exporter.metrics import SnapshotStore

    # Small ring, filled during warmup: the measured regime must be the
    # STEADY state, where each poll's retained trace objects are balanced
    # by an eviction's deallocations. Measuring the ring's fill phase
    # instead reads as a spurious extra-GC "overhead" (+16 net tracked
    # allocations per poll until the default 256-trace ring fills — ~4
    # minutes of a real deployment, but most of a short bench run).
    ring = TraceStore(max_traces=32)

    def make(tracer: Tracer | None) -> Collector:
        collector = Collector(FakeBackend(chips=chips), FakeAttribution(),
                              SnapshotStore(), tracer=tracer)
        for _ in range(50):  # warm caches/layouts; fill the trace ring
            collector.poll_once()
        return collector

    def segment(collector: Collector, n: int) -> float:
        c0 = utils.process_cpu_seconds()
        for _ in range(n):
            collector.poll_once()
        return utils.process_cpu_seconds() - c0

    tracer = Tracer(ring, slow_poll_s=3600.0, sampler=StackSampler())
    off, on = make(None), make(tracer)
    seg_len = max(polls // 8, 10)
    t_off = t_on = 0.0
    try:
        for seg in range(16):
            if seg % 2:
                t_on += segment(on, seg_len)
                t_off += segment(off, seg_len)
            else:
                t_off += segment(off, seg_len)
                t_on += segment(on, seg_len)
    finally:
        tracer.close()
    overhead = t_on / t_off - 1.0 if t_off > 0 else 0.0
    print(f"poll-loop CPU over {16 * seg_len} interleaved polls/mode at "
          f"{chips} chips: trace-off {t_off:.3f}s, trace-on {t_on:.3f}s "
          f"→ overhead {100 * overhead:+.1f}% (budget {100 * budget:.0f}%)")
    if overhead > budget:
        print("FAIL: tracing overhead exceeds budget")
        return 1
    print("OK: tracing overhead within budget")
    return 0


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="tpu-pod-exporter-trace",
        description="Poll-trace demo and tracing-overhead smoke check.",
    )
    p.add_argument("--replay", default="",
                   help="JSONL backend trace to replay through a traced "
                        "collector; prints the rendered trace tree")
    p.add_argument("--polls", type=int, default=0,
                   help="polls to run (replay default: one pass; "
                        "overhead default: 300)")
    p.add_argument("--slow-poll-s", type=float, default=1.0)
    p.add_argument("--overhead-check", action="store_true",
                   help="measure tracing-on vs tracing-off poll CPU and "
                        "fail past --budget")
    p.add_argument("--chips", type=int, default=256)
    p.add_argument("--budget", type=float, default=0.05,
                   help="max tolerated fractional CPU overhead (0.05 = 5%%)")
    ns = p.parse_args(argv)

    if ns.overhead_check:
        return _overhead_check(ns.polls or 300, ns.chips, ns.budget)
    if ns.replay:
        return _demo_replay(ns.replay, ns.polls, ns.slow_poll_s)
    p.error("need --replay PATH or --overhead-check")
    return 2


if __name__ == "__main__":
    sys.exit(main())
