"""Kubelet podresources gRPC client — the real attribution source.

One local RPC per poll over the kubelet's unix socket replaces the
reference's O(pods × containers) ``kubectl exec`` fan-out plus cluster-wide
pod list (``main.go:77,101-109``): no apiserver traffic, no subprocesses,
and the device IDs it returns are the authoritative allocation record —
there is no PID heuristic to get wrong (``main.go:141-154``, SURVEY.md
§2.6).

The channel is created lazily and kept open across polls (HTTP/2 stream
reuse); any RPC failure surfaces as AttributionError so the collector's
bounded-staleness logic takes over.
"""

from __future__ import annotations

import logging
import threading

from tpu_pod_exporter.attribution import (
    TPU_RESOURCE_NAME,
    AttributionError,
    AttributionProvider,
    AttributionSnapshot,
    DeviceAllocation,
)
from tpu_pod_exporter.attribution.proto import podresources_pb2 as pb

log = logging.getLogger("tpu_pod_exporter.attribution.podresources")

LIST_METHOD = "/v1.PodResourcesLister/List"
GET_ALLOCATABLE_METHOD = "/v1.PodResourcesLister/GetAllocatableResources"
DEFAULT_SOCKET = "/var/lib/kubelet/pod-resources/kubelet.sock"


def allocatable_from_response(
    resp: "pb.AllocatableResourcesResponse", resource_name: str
) -> tuple[str, ...]:
    """GetAllocatableResources → device IDs for one resource."""
    ids: list[str] = []
    for dev in resp.devices:
        if dev.resource_name == resource_name:
            ids.extend(dev.device_ids)
    return tuple(sorted(set(ids)))


def snapshot_from_response(
    resp: "pb.ListPodResourcesResponse",
    resource_prefixes: tuple[str, ...] = (),
    allocatable: tuple[str, ...] | None = None,
) -> AttributionSnapshot:
    """Pure conversion: protobuf → AttributionSnapshot (unit-testable with
    no socket). When ``resource_prefixes`` is non-empty, only matching
    resources are kept; otherwise all device allocations pass through and
    filtering happens at join time."""
    allocations: list[DeviceAllocation] = []
    for pod in resp.pod_resources:
        for container in pod.containers:
            for dev in container.devices:
                if resource_prefixes and not any(
                    dev.resource_name.startswith(p) for p in resource_prefixes
                ):
                    continue
                if not dev.device_ids:
                    continue
                allocations.append(
                    DeviceAllocation(
                        pod=pod.name,
                        namespace=pod.namespace,
                        container=container.name,
                        device_ids=tuple(dev.device_ids),
                        resource_name=dev.resource_name,
                    )
                )
    return AttributionSnapshot(tuple(allocations), allocatable_device_ids=allocatable)


class PodResourcesAttribution(AttributionProvider):
    name = "podresources"

    def __init__(
        self,
        socket_path: str = DEFAULT_SOCKET,
        timeout_s: float = 2.0,
        target: str | None = None,
        resource_name: str = TPU_RESOURCE_NAME,
    ) -> None:
        """``target`` overrides the unix-socket URI (tests use tmpdir sockets)."""
        import grpc  # deferred: keep import cost off the fake-only path

        self._grpc = grpc
        self._target = target if target is not None else f"unix://{socket_path}"
        self._timeout_s = timeout_s
        self._resource_name = resource_name
        self._lock = threading.Lock()
        self._channel = None
        self._list = None
        self._get_allocatable = None
        # GetAllocatableResources needs kubelet >=1.23 (and a feature gate on
        # older ones); probed once, degraded to None thereafter.
        self._allocatable_supported: bool | None = None

    def _ensure_channel(self) -> None:
        with self._lock:
            if self._channel is not None:
                return
            self._channel = self._grpc.insecure_channel(
                self._target,
                options=[
                    # podresources List responses are tiny, but never truncate
                    ("grpc.max_receive_message_length", 16 * 1024 * 1024),
                    ("grpc.enable_http_proxy", 0),
                ],
            )
            self._list = self._channel.unary_unary(
                LIST_METHOD,
                request_serializer=pb.ListPodResourcesRequest.SerializeToString,
                response_deserializer=pb.ListPodResourcesResponse.FromString,
            )
            self._get_allocatable = self._channel.unary_unary(
                GET_ALLOCATABLE_METHOD,
                request_serializer=pb.AllocatableResourcesRequest.SerializeToString,
                response_deserializer=pb.AllocatableResourcesResponse.FromString,
            )

    def snapshot(self) -> AttributionSnapshot:
        try:
            self._ensure_channel()
            resp = self._list(pb.ListPodResourcesRequest(), timeout=self._timeout_s)
        except self._grpc.RpcError as e:
            # Drop the channel so the next poll reconnects (kubelet restarts).
            self._reset_channel()
            raise AttributionError(f"podresources List failed: {e.code()}") from e
        except Exception as e:  # noqa: BLE001
            self._reset_channel()
            raise AttributionError(f"podresources List failed: {e}") from e
        return snapshot_from_response(resp, allocatable=self._read_allocatable())

    def _read_allocatable(self) -> tuple[str, ...] | None:
        """Best-effort inventory read; never fails the attribution poll."""
        if self._allocatable_supported is False:
            return None
        try:
            resp = self._get_allocatable(
                pb.AllocatableResourcesRequest(), timeout=self._timeout_s
            )
        except self._grpc.RpcError as e:
            if self._allocatable_supported is None and e.code() in (
                self._grpc.StatusCode.UNIMPLEMENTED,
                self._grpc.StatusCode.NOT_FOUND,
            ):
                log.info("GetAllocatableResources unsupported by this kubelet")
                self._allocatable_supported = False
            return None
        except Exception:  # noqa: BLE001
            return None
        self._allocatable_supported = True
        return allocatable_from_response(resp, self._resource_name)

    def _reset_channel(self) -> None:
        with self._lock:
            if self._channel is not None:
                try:
                    self._channel.close()
                except Exception:  # noqa: BLE001
                    pass
            self._channel = None
            self._list = None
            self._get_allocatable = None

    def close(self) -> None:
        self._reset_channel()
