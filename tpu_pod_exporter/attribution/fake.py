"""Scripted attribution provider — the podresources fake (SURVEY.md §4.2).

Supports instantaneous reassignment (``set_allocations``) for churn stress
(baseline config 5) and fault injection (``fail_next``) for §4.5.
"""

from __future__ import annotations

import threading
from typing import Iterable, Sequence

from tpu_pod_exporter.attribution import (
    AttributionError,
    AttributionProvider,
    AttributionSnapshot,
    DeviceAllocation,
    TPU_RESOURCE_NAME,
)


class FakeAttribution(AttributionProvider):
    name = "fake"

    def __init__(self, allocations: Sequence[DeviceAllocation] = ()) -> None:
        self._lock = threading.Lock()
        self._snapshot = AttributionSnapshot(tuple(allocations))
        self._fail_next = 0
        self.snapshot_calls = 0
        self.closed = False

    def set_allocations(self, allocations: Iterable[DeviceAllocation]) -> None:
        snap = AttributionSnapshot(tuple(allocations))
        with self._lock:
            self._snapshot = snap

    def fail_next(self, n: int = 1) -> None:
        with self._lock:
            self._fail_next += n

    def snapshot(self) -> AttributionSnapshot:
        with self._lock:
            self.snapshot_calls += 1
            if self._fail_next > 0:
                self._fail_next -= 1
                raise AttributionError("fake attribution: injected failure")
            return self._snapshot

    def close(self) -> None:
        self.closed = True


def simple_allocation(
    pod: str,
    device_ids: Sequence[str],
    namespace: str = "default",
    container: str = "main",
) -> DeviceAllocation:
    return DeviceAllocation(
        pod=pod,
        namespace=namespace,
        container=container,
        device_ids=tuple(device_ids),
        resource_name=TPU_RESOURCE_NAME,
    )
