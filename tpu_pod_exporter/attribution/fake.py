"""Scripted attribution provider — the podresources fake (SURVEY.md §4.2).

Supports instantaneous reassignment (``set_allocations``) for churn stress
(baseline config 5) and fault injection (``fail_next``) for §4.5.
"""

from __future__ import annotations

import threading
from typing import Iterable, Sequence

from tpu_pod_exporter.attribution import (
    AttributionError,
    AttributionProvider,
    AttributionSnapshot,
    DeviceAllocation,
    TPU_RESOURCE_NAME,
)


class FakeAttribution(AttributionProvider):
    name = "fake"

    def __init__(
        self,
        allocations: Sequence[DeviceAllocation] = (),
        allocatable: Sequence[str] | None = None,
    ) -> None:
        self._lock = threading.Lock()
        self._snapshot = AttributionSnapshot(
            tuple(allocations),
            allocatable_device_ids=tuple(allocatable) if allocatable is not None else None,
        )
        self._fail_next = 0
        self.snapshot_calls = 0
        self.closed = False

    _KEEP = object()  # sentinel: preserve current allocatable on churn

    def set_allocations(
        self,
        allocations: Iterable[DeviceAllocation],
        allocatable: "Sequence[str] | None | object" = _KEEP,
    ) -> None:
        with self._lock:
            if allocatable is FakeAttribution._KEEP:
                # Real kubelets keep reporting the device inventory across
                # pod churn; the fake must too unless explicitly overridden.
                alloc_ids = self._snapshot.allocatable_device_ids
            else:
                alloc_ids = (
                    tuple(allocatable) if allocatable is not None else None  # type: ignore[arg-type]
                )
            self._snapshot = AttributionSnapshot(
                tuple(allocations), allocatable_device_ids=alloc_ids
            )

    def fail_next(self, n: int = 1) -> None:
        with self._lock:
            self._fail_next += n

    def snapshot(self) -> AttributionSnapshot:
        with self._lock:
            self.snapshot_calls += 1
            if self._fail_next > 0:
                self._fail_next -= 1
                raise AttributionError("fake attribution: injected failure")
            return self._snapshot

    def close(self) -> None:
        self.closed = True


def simple_allocation(
    pod: str,
    device_ids: Sequence[str],
    namespace: str = "default",
    container: str = "main",
) -> DeviceAllocation:
    return DeviceAllocation(
        pod=pod,
        namespace=namespace,
        container=container,
        device_ids=tuple(device_ids),
        resource_name=TPU_RESOURCE_NAME,
    )
