"""UID → (pod name, namespace) resolution for the checkpoint fallback.

The kubelet device-plugin checkpoint knows only pod *UIDs*
(``checkpoint.py``), so without help the fallback path emits
``pod="uid:<uid>"`` series. Two node-local sources fix that — both avoid
any apiserver call, preserving the design rule that the exporter talks
only to kubelet-local surfaces (SURVEY.md §7 delta 1; the reference
instead pulled the *cluster-wide* pod list, ``main.go:74-89``):

- :class:`StaticUidMap` — a JSON file the operator mounts/renders
  (``{"<uid>": {"name": "...", "namespace": "..."}}``; also accepts
  ``[name, namespace]`` pairs).
- :class:`KubeletPodsUidMap` — the kubelet's own ``/pods`` endpoint
  (``https://127.0.0.1:10250/pods`` with the pod's service-account token,
  or the legacy read-only ``http://127.0.0.1:10255/pods``), refreshed at
  most every ``refresh_s`` seconds and serving the last good map on
  fetch errors (same bounded-staleness posture as the collector).
"""

from __future__ import annotations

import json
import logging
import ssl
import time
import urllib.request
from typing import Mapping

log = logging.getLogger("tpu_pod_exporter.attribution.uidmap")

DEFAULT_TOKEN_FILE = "/var/run/secrets/kubernetes.io/serviceaccount/token"
DEFAULT_CA_FILE = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"


class UidMapError(RuntimeError):
    """The UID map source was unreadable/unparseable."""


def parse_uid_map_file(raw: str | bytes) -> dict[str, tuple[str, str]]:
    """Parse the static-file shape: uid -> {name, namespace} | [name, ns]."""
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError as e:
        raise UidMapError(f"uid map is not valid JSON: {e}") from e
    if not isinstance(doc, dict):
        raise UidMapError("uid map must be a JSON object keyed by pod UID")
    out: dict[str, tuple[str, str]] = {}
    for uid, val in doc.items():
        if isinstance(val, dict):
            out[str(uid)] = (str(val.get("name", "")), str(val.get("namespace", "")))
        elif isinstance(val, (list, tuple)) and len(val) == 2:
            out[str(uid)] = (str(val[0]), str(val[1]))
        else:
            raise UidMapError(f"uid {uid!r}: expected object or [name, namespace]")
    return out


def parse_kubelet_pods(raw: str | bytes) -> dict[str, tuple[str, str]]:
    """Parse the kubelet ``/pods`` PodList: items[].metadata.{uid,name,namespace}."""
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError as e:
        raise UidMapError(f"kubelet /pods response is not valid JSON: {e}") from e
    out: dict[str, tuple[str, str]] = {}
    for item in doc.get("items") or []:
        meta = item.get("metadata") or {}
        uid = meta.get("uid")
        if uid:
            out[str(uid)] = (str(meta.get("name", "")), str(meta.get("namespace", "")))
    return out


class StaticUidMap:
    """Operator-provided JSON file; re-read only when its mtime changes."""

    def __init__(self, path: str) -> None:
        self._path = path
        self._mtime: float | None = None
        self._map: dict[str, tuple[str, str]] = {}

    def mapping(self) -> Mapping[str, tuple[str, str]]:
        import os

        try:
            mtime = os.stat(self._path).st_mtime
        except OSError as e:
            raise UidMapError(f"cannot stat uid map {self._path}: {e}") from e
        if mtime != self._mtime:
            with open(self._path, "rb") as f:
                self._map = parse_uid_map_file(f.read())
            self._mtime = mtime
        return self._map


class KubeletPodsUidMap:
    """Kubelet ``/pods`` poller with TTL refresh and last-good fallback."""

    def __init__(
        self,
        url: str,
        token_file: str | None = None,
        ca_file: str | None = None,
        refresh_s: float = 30.0,
        timeout_s: float = 5.0,
        insecure_tls: bool = False,
        _fetch=None,  # test seam: (url, headers, timeout_s) -> bytes
        _clock=time.monotonic,
    ) -> None:
        if url.startswith("https:") and token_file and not ca_file:
            # A bearer token is a real cluster credential; sending it over
            # an unverified TLS channel hands it to any MITM. Refuse at
            # construction (fail loud at startup, not quietly at runtime)
            # unless the operator explicitly accepted the risk.
            if not insecure_tls:
                raise UidMapError(
                    "kubelet_token_file is set for an https kubelet URL but "
                    "kubelet_ca_file is not: refusing to send a bearer token "
                    "over unverified TLS. Set --kubelet-ca-file (the SA "
                    "mount's ca.crt) or explicitly opt in with "
                    "--kubelet-insecure-tls."
                )
            log.warning(
                "sending the kubelet bearer token over UNVERIFIED TLS "
                "(--kubelet-insecure-tls): acceptable only when %s "
                "never leaves this node", url,
            )
        self._url = url
        self._token_file = token_file
        self._ca_file = ca_file
        self._refresh_s = refresh_s
        self._timeout_s = timeout_s
        self._fetch = _fetch or self._http_fetch
        self._clock = _clock
        self._map: dict[str, tuple[str, str]] = {}
        self._fetched_at: float | None = None
        # Cumulative; surfaced by CheckpointAttribution.error_counters() as
        # tpu_exporter_poll_errors_total{source="attribution.uid_map"}.
        self.fetch_errors = 0

    def _http_fetch(self, url: str, headers: dict, timeout_s: float) -> bytes:
        ctx = None
        if url.startswith("https:"):
            if self._ca_file:
                ctx = ssl.create_default_context(cafile=self._ca_file)
                # The kubelet's serving cert is for the node name, not the
                # loopback IP this DaemonSet dials — verify the chain, not
                # the hostname (the socket never leaves the node).
                ctx.check_hostname = False
            else:
                ctx = ssl._create_unverified_context()  # noqa: S323 — node-local
        req = urllib.request.Request(url, headers=headers)
        with urllib.request.urlopen(req, timeout=timeout_s, context=ctx) as resp:
            return resp.read()

    def _headers(self) -> dict:
        if not self._token_file:
            return {}
        try:
            with open(self._token_file) as f:
                return {"Authorization": f"Bearer {f.read().strip()}"}
        except OSError as e:
            raise UidMapError(
                f"cannot read kubelet token {self._token_file}: {e}"
            ) from e

    def mapping(self) -> Mapping[str, tuple[str, str]]:
        now = self._clock()
        if self._fetched_at is not None and now - self._fetched_at < self._refresh_s:
            return self._map
        try:
            raw = self._fetch(self._url, self._headers(), self._timeout_s)
            self._map = parse_kubelet_pods(raw)
            self._fetched_at = now
        except Exception as e:  # noqa: BLE001 — degrade to last-good map
            self.fetch_errors += 1
            self._fetched_at = now  # back off a full refresh interval
            log.warning("kubelet /pods fetch failed (%s); serving last-good "
                        "uid map (%d entries)", e, len(self._map))
        return self._map
