"""Pod ↔ device attribution — replaces the reference's L2+L3 entirely.

The reference joins device telemetry to pods via a cluster-wide pod list
(``main.go:77``), a per-pod ``kubectl exec … ps`` PID harvest
(``main.go:101-109``), and a triple-nested PID comparison
(``main.go:141-154``). That path is broken three ways (index-vs-value join,
PID-namespace mismatch, container mistargeting — SURVEY.md §2.6) and costs
O(pods) process spawns plus apiserver round-trips per poll.

Here attribution is one local call: the kubelet **podresources API**
(``List`` over ``/var/lib/kubelet/pod-resources/kubelet.sock``), which
reports exactly which ``google.com/tpu`` device IDs each container was
allocated. No apiserver traffic, no exec, no PID translation — and the join
key (device ID) is authoritative rather than heuristic.

Implementations:
- :class:`~tpu_pod_exporter.attribution.fake.FakeAttribution` — scripted
  allocations for tests/bench, with churn and fault injection.
- :class:`~tpu_pod_exporter.attribution.podresources.PodResourcesAttribution`
  — the real gRPC client (vendored proto, unix socket).
- :class:`~tpu_pod_exporter.attribution.checkpoint.CheckpointAttribution` —
  zero-dependency fallback that reads the kubelet device-plugin checkpoint
  file directly.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field

TPU_RESOURCE_NAME = "google.com/tpu"


class AttributionError(RuntimeError):
    """Attribution source failed; the poll should degrade, not die."""


@dataclass(frozen=True)
class DeviceAllocation:
    """One container's claim on a set of device IDs."""

    pod: str
    namespace: str
    container: str
    device_ids: tuple[str, ...]
    resource_name: str = TPU_RESOURCE_NAME


@dataclass(frozen=True)
class AttributionSnapshot:
    """All allocations on this node at one instant.

    ``allocatable_device_ids`` is the kubelet's full device-plugin inventory
    for the resource (GetAllocatableResources); None when the source cannot
    report it (checkpoint fallback, old kubelets).
    """

    allocations: tuple[DeviceAllocation, ...] = ()
    allocatable_device_ids: tuple[str, ...] | None = None

    def by_device_id(self, resource_name: str = TPU_RESOURCE_NAME) -> dict[str, DeviceAllocation]:
        """device_id -> owning allocation. Kubelet guarantees a device is
        allocated to at most one container; on (buggy) duplicates the first
        claim wins deterministically."""
        out: dict[str, DeviceAllocation] = {}
        for alloc in self.allocations:
            if alloc.resource_name != resource_name:
                continue
            for did in alloc.device_ids:
                out.setdefault(did, alloc)
        return out


class AttributionProvider(abc.ABC):
    name: str = "abstract"

    @abc.abstractmethod
    def snapshot(self) -> AttributionSnapshot:
        """Current pod↔device allocations. Raises AttributionError on failure."""

    def close(self) -> None:
        return None


from tpu_pod_exporter.attribution.fake import FakeAttribution  # noqa: E402

__all__ = [
    "TPU_RESOURCE_NAME",
    "AttributionError",
    "AttributionProvider",
    "AttributionSnapshot",
    "DeviceAllocation",
    "FakeAttribution",
]
