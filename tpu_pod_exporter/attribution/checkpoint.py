"""Kubelet device-plugin checkpoint reader — zero-dependency fallback.

The kubelet persists device-plugin allocations to
``/var/lib/kubelet/device-plugins/kubelet_internal_checkpoint`` as JSON:

    {"Data": {"PodDeviceEntries": [
        {"PodUID": "...", "ContainerName": "...",
         "ResourceName": "google.com/tpu",
         "DeviceIDs": {"-1": ["0", "1"]}},   # numa-node -> ids (k8s >=1.20)
       ...],
      "RegisteredDevices": {...}},
     "Checksum": ...}

Older kubelets store ``DeviceIDs`` as a flat list. Both shapes are handled.

This is a *fallback* for nodes where the podresources socket is not mounted:
it knows pod UIDs, not names/namespaces, so series carry
``pod="uid:<uid>"`` unless a UID→name hint map is provided. The primary path
(podresources) should be preferred whenever available.
"""

from __future__ import annotations

import json
import logging
from typing import Mapping

from tpu_pod_exporter.attribution import (
    AttributionError,
    AttributionProvider,
    AttributionSnapshot,
    DeviceAllocation,
)

log = logging.getLogger("tpu_pod_exporter.attribution.checkpoint")

DEFAULT_CHECKPOINT = "/var/lib/kubelet/device-plugins/kubelet_internal_checkpoint"


def parse_checkpoint(
    raw: str | bytes,
    uid_to_pod: Mapping[str, tuple[str, str]] | None = None,
) -> AttributionSnapshot:
    """Pure parser: checkpoint JSON → AttributionSnapshot.

    ``uid_to_pod`` optionally maps pod UID → (name, namespace).
    """
    try:
        doc = json.loads(raw)
    except json.JSONDecodeError as e:
        raise AttributionError(f"checkpoint is not valid JSON: {e}") from e

    entries = (doc.get("Data") or {}).get("PodDeviceEntries") or []
    allocations: list[DeviceAllocation] = []
    for entry in entries:
        if not isinstance(entry, dict):
            continue
        uid = entry.get("PodUID", "")
        resource = entry.get("ResourceName", "")
        container = entry.get("ContainerName", "")
        raw_ids = entry.get("DeviceIDs")
        if isinstance(raw_ids, dict):  # numa-node -> [ids]
            ids = [d for ids_list in raw_ids.values() for d in (ids_list or [])]
        elif isinstance(raw_ids, list):  # pre-1.20 flat shape
            ids = list(raw_ids)
        else:
            ids = []
        if not ids:
            continue
        if uid_to_pod and uid in uid_to_pod:
            pod, namespace = uid_to_pod[uid]
        else:
            pod, namespace = f"uid:{uid}", ""
        allocations.append(
            DeviceAllocation(
                pod=pod,
                namespace=namespace,
                container=container,
                device_ids=tuple(str(d) for d in ids),
                resource_name=resource,
            )
        )
    return AttributionSnapshot(tuple(allocations))


class CheckpointAttribution(AttributionProvider):
    name = "checkpoint"

    def __init__(
        self,
        path: str = DEFAULT_CHECKPOINT,
        uid_to_pod: Mapping[str, tuple[str, str]] | None = None,
        uid_source=None,
    ) -> None:
        """``uid_to_pod`` is a fixed mapping; ``uid_source`` is a live
        resolver with a ``mapping()`` method (``uidmap.StaticUidMap`` /
        ``uidmap.KubeletPodsUidMap``) re-consulted every snapshot so pod
        churn is picked up. If both are given the source wins."""
        self._path = path
        self._uid_to_pod = uid_to_pod
        self._uid_source = uid_source
        self._uid_map_errors = 0

    def error_counters(self) -> dict[str, float]:
        """Cumulative side-channel error counts, published by the collector
        as ``tpu_exporter_poll_errors_total{source="attribution.uid_map"}`` — covers
        both resolver exceptions seen here and the kubelet source's
        internal fetch failures (which degrade to last-good silently)."""
        total = self._uid_map_errors + int(
            getattr(self._uid_source, "fetch_errors", 0) or 0
        )
        return {"uid_map": float(total)} if total else {}

    def snapshot(self) -> AttributionSnapshot:
        try:
            with open(self._path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise AttributionError(f"cannot read checkpoint {self._path}: {e}") from e
        uid_map = self._uid_to_pod
        if self._uid_source is not None:
            try:
                uid_map = self._uid_source.mapping()
            except Exception as e:  # noqa: BLE001 — names are best-effort
                # Degrade to uid:<uid> series rather than failing the whole
                # attribution phase: allocations are still correct.
                self._uid_map_errors += 1
                log.warning("uid map unavailable (%s); emitting uid-keyed pods", e)
                uid_map = self._uid_to_pod
        return parse_checkpoint(raw, uid_map)
