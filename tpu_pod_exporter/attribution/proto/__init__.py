"""Vendored kubelet podresources v1 protobufs.

``podresources_pb2.py`` is generated from ``podresources.proto`` via
``protoc --python_out=.``; regenerate with ``make proto`` at the repo root.
"""

from tpu_pod_exporter.attribution.proto import podresources_pb2

__all__ = ["podresources_pb2"]
