"""The poll loop: read devices, read attribution, join, publish.

Redesign of the reference's collection loop (``main.go:74-157``) with these
deliberate inversions (SURVEY.md §7):

- **Error containment**: every phase catches its errors, increments an error
  counter, and degrades — the reference instead ``log.Fatalf``s on any NVML
  error mid-loop (``main.go:119,126,131,137``) and ``panic``s on apiserver
  blips (``main.go:79``).
- **Join by device ID**: chip → allocation via the podresources device-ID
  map, O(chips) dict lookups — the reference does an O(devices × procs ×
  pods × pids) nested scan over the wrong join key (``main.go:141-154``).
- **Structural stale-series GC**: each poll builds a complete snapshot and
  swaps it; dead pods' series vanish on the next poll — the reference never
  deletes a series.
- **Bounded attribution staleness**: if the attribution source fails, the
  last good snapshot is reused for up to ``attribution_max_stale_s`` so chip
  metrics keep flowing with slightly stale ownership, then attribution
  labels drop to "" rather than lie indefinitely.
- **Drift-free scheduling**: ticks are scheduled at ``start + n·interval``
  (the reference sleeps a flat 30 s *after* each iteration, ``main.go:156``,
  so its period is interval + iteration time).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable, Iterable, Sequence

from tpu_pod_exporter.attribution import (
    AttributionError,
    AttributionProvider,
    AttributionSnapshot,
    TPU_RESOURCE_NAME,
)
from tpu_pod_exporter.backend import BackendError, DeviceBackend, HostSample
from tpu_pod_exporter.metrics import (
    CounterStore,
    HistogramStore,
    SnapshotBuilder,
    SnapshotStore,
)
from tpu_pod_exporter import trace as trace_mod
from tpu_pod_exporter import utils
from tpu_pod_exporter.metrics import schema
from tpu_pod_exporter.metrics.registry import PrefixCache
from tpu_pod_exporter.supervisor import SourceSkipped, SourceTimeout
from tpu_pod_exporter.topology import HostTopology
from tpu_pod_exporter.utils import RateLimitedLogger
from tpu_pod_exporter.version import __version__

if TYPE_CHECKING:  # import-cycle-free typing only
    from tpu_pod_exporter.egress import RemoteWriteShipper
    from tpu_pod_exporter.history import HistoryStore
    from tpu_pod_exporter.metrics.registry import Snapshot
    from tpu_pod_exporter.persist import StatePersister
    from tpu_pod_exporter.supervisor import SourceSupervisor
    from tpu_pod_exporter.trace import PollTrace, Tracer

log = logging.getLogger("tpu_pod_exporter.collector")


@dataclass
class PollStats:
    """Per-phase timing + outcome of one poll (SURVEY.md §5 tracing)."""

    device_read_s: float = 0.0
    attribution_s: float = 0.0
    process_scan_s: float = 0.0
    join_s: float = 0.0
    publish_s: float = 0.0
    total_s: float = 0.0
    ok: bool = True
    errors: tuple[str, ...] = ()
    # Phases skipped by an open circuit breaker this poll. A skip degrades
    # the phase exactly like an error (absent/stale data, up=0 for device)
    # but is NOT a failure — it is the quarantine working — so it never
    # counts into tpu_exporter_poll_errors_total (skips have their own
    # counter, tpu_exporter_source_calls_skipped_total); same split the
    # aggregator applies to its per-target scrape-error counter.
    skipped: tuple[str, ...] = ()
    # Trace id of this poll's trace ("" when tracing is off) — the join key
    # between /debug/vars' last_poll, the JSON log stream, and /debug/trace.
    trace_id: str = ""


class Collector:
    def __init__(
        self,
        backend: DeviceBackend,
        attribution: AttributionProvider,
        store: SnapshotStore,
        topology: HostTopology | None = None,
        resource_name: str = TPU_RESOURCE_NAME,
        attribution_max_stale_s: float = 30.0,
        legacy_metrics: bool = False,
        process_scanner: Any = None,
        # () -> {cause: int}, from the HTTP guard
        scrape_rejects_fn: Callable[[], dict[str, int]] | None = None,
        # () -> int, from the CollectorLoop
        loop_overruns_fn: Callable[[], int] | None = None,
        # HistogramStore fed by the HTTP server
        scrape_duration_hist: HistogramStore | None = None,
        # HistoryStore fed after each snapshot swap
        history: "HistoryStore | None" = None,
        # {"device"|"attribution"|"process_scan": SourceSupervisor}
        supervisors: "dict[str, SourceSupervisor] | None" = None,
        # trace.Tracer; None = zero tracing work per poll
        tracer: "Tracer | None" = None,
        # persist.StatePersister; None = no persistence
        persister: "StatePersister | None" = None,
        # egress.RemoteWriteShipper; None = no push egress
        shipper: "RemoteWriteShipper | None" = None,
        # pressure.PressureGovernor; None = no pressure surface. The
        # governor runs its own check thread — the collector only emits
        # its cached stats (never a disk walk on the poll thread).
        governor: Any = None,
        # () -> int, from the HTTP server
        client_write_timeouts_fn: Callable[[], int] | None = None,
        # Incremental splice render (--render-splice); False restores the
        # per-family full re-render at every poll.
        render_splice: bool = True,
        clock: Callable[[], float] = time.monotonic,
        wallclock: Callable[[], float] = time.time,
    ) -> None:
        self._backend = backend
        self._attribution = attribution
        self._process_scanner = process_scanner
        self._scrape_rejects_fn = scrape_rejects_fn
        self._loop_overruns_fn = loop_overruns_fn
        self._store = store
        # Optional supervision layer (tpu_pod_exporter.supervisor): when a
        # source has a supervisor, its phase call runs on a fenced worker
        # with a hard deadline behind a circuit breaker; without one, the
        # call runs in-thread exactly as before (tests/bench construct the
        # Collector bare).
        self._supervisors = supervisors or {}
        # End-to-end poll tracing (tpu_pod_exporter.trace): every poll
        # becomes a trace with one span per phase; None skips every hook.
        self._tracer = tracer
        # Consecutive-failure counts per phase error key, for recovery log
        # lines on the UNsupervised path (supervisors log their own).
        self._phase_failures: dict[str, int] = {}
        self._topology = topology or HostTopology()
        self._resource_name = resource_name
        self._attribution_max_stale_s = attribution_max_stale_s
        self._legacy_metrics = legacy_metrics
        # GPU surface latch: once the backend (or any observed chip) is
        # GPU-family, the gpu_* twins are declared every poll — sticky, so
        # scrapers see a stable surface from the first GPU sighting on
        # (the same conditional-surface rule as TPU_CHIP_PROCESS_INFO).
        self._gpu_surface = getattr(backend, "family", "tpu") == "gpu"
        self._clock = clock
        self._wallclock = wallclock

        self._counters = CounterStore()
        # Distributions of the exporter's own latencies (VERDICT r4: a p99
        # of poll phases must be computable from the exposition). Phase
        # observations land at poll end; the scrape store is fed by the
        # HTTP handler threads and emitted here, one poll behind — fine
        # for a cumulative histogram.
        self._phase_hist = HistogramStore(
            schema.TPU_EXPORTER_POLL_PHASE_DURATION_HIST
        )
        self._scrape_hist = scrape_duration_hist
        # Flight recorder: fed once per poll AFTER the snapshot swap, so the
        # scrape path never contends on the history lock. The append
        # duration lands in the next snapshot (one poll behind, like
        # publish/total timings).
        self._history = history
        self._history_append_s = 0.0
        # Crash-safe persistence: fed once per poll AFTER the history
        # append, on its own phase — like the history append it is
        # excluded from the publish/total timings it is separately
        # accounted against. The poll-side cost is one queue put; all
        # I/O runs on the persister's writer thread.
        self._persister = persister
        self._persist_s = 0.0
        # Remote-write egress: fed once per poll AFTER persistence, on its
        # own phase — the same excluded-from-publish/total accounting. The
        # poll-side cost is one non-blocking queue put; batch building and
        # every byte of network/disk I/O run on the shipper's own threads.
        self._shipper = shipper
        self._egress_s = 0.0
        self._governor = governor
        self._client_write_timeouts_fn = client_write_timeouts_fn
        # Poll-phase faults repeat every interval (1 s) while a source is
        # down; rate-limit per fault key so logs show the fault, not 86k
        # lines/day. Per-instance: multiple collectors (tests, bench)
        # must not suppress each other.
        self._rlog = RateLimitedLogger(log)
        self._prefix_cache = PrefixCache(splice=render_splice)
        # Topology labels are fixed for the process lifetime; pre-order them
        # once for the tuple fast path (CHIP_LABELS[2:6]).
        t = self._topology.labels()
        self._topo_tuple = (
            t["accelerator"], t["slice_name"], t["host"], t["worker_id"],
        )
        # tpu_host_info label tuple (TOPO_LABELS + multislice membership):
        # static for the process lifetime, published every poll as the
        # cross-slice join key (see HostTopology.host_info_labels).
        hi = self._topology.host_info_labels()
        self._host_info_tuple = self._topo_tuple + (
            hi["multislice_group"], hi["num_slices"],
        )
        self._last_attr: AttributionSnapshot | None = None
        self._last_attr_at: float = 0.0
        # Last good holder set, reused under the same bounded-staleness rule
        # as attribution: a transient scan failure must not flip the legacy
        # series identity from {pid="<holder>"} to {pid=""} for one poll.
        self._last_holders: tuple | None = None
        self._last_holders_at: float = 0.0
        # (chip_id, owner pod/ns/container) -> (chip label tuple,
        # {link id -> link label tuple}, chip-info label tuple or None).
        # Label tuples are invariant between
        # churn events, so rebuilding + re-interning them per chip per poll
        # is the main Python cost of publish at 256 chips; cache and reuse.
        # The cached inner tuples also make the PrefixCache layout comparison
        # hit its pointer-identity fast path. Bounded: wiped wholesale when
        # churn outgrows it (entries for dead owners are unreachable after).
        self._label_cache: dict[tuple, tuple[tuple, dict]] = {}
        # chip_id -> {link id -> [raw_prev, folded, rate_base, last_seq]}:
        # per-link monotonic-fold state, deliberately keyed by chip (not by
        # owner) so counters and rates continue across pod reassignment.
        # Mutable-list slot access instead of tuple-keyed CounterStore
        # lookups, which at 1.5k links × ~5 nested-tuple hashes each were
        # the hottest publish cost. A wiped record re-seeds its counter at
        # the current raw value, which is ≥ the folded value barring a
        # device reset in the same instant, so exported counters stay
        # monotonic.
        self._chip_state: dict[int, dict[str, list]] = {}
        # Same per-link fold state for DCN counters. No flat/numpy fast
        # path: DCN cardinality is a handful of NIC-class links per host
        # (vs 6 ICI links × every chip), so a plain loop is already cheap.
        self._dcn_state: dict[int, dict[str, list]] = {}
        # Monotonic publish sequence for polls that carried a device sample;
        # a link's rate is published only when it was also seen at seq-1
        # (dt measures exactly that window).
        self._publish_seq = 0
        # Flat ICI fold block for the steady state: when the (chip, owner,
        # link-id) layout is identical to the previous sampled poll, all
        # links fold in one numpy pass (delta/clip/rate over flat arrays)
        # instead of ~15 interpreted ops per link — at 256 chips × 6 links
        # that is the single largest publish cost. Any layout change (churn,
        # re-enumeration, link set change) falls back to the per-link loop
        # for that poll, which also (re)builds this block. The per-link recs
        # in _chip_state go stale while the fast path runs and are written
        # back by _export_ici_flat() before any slow-path fold.
        self._ici_flat: dict | None = None
        # monotonic time of the previous published device sample, for rates
        self._prev_ici_at: float | None = None
        self.last_stats = PollStats()

    def render_stats(self) -> dict[str, int] | None:
        """Splice-render counters for /debug/vars, or None when the
        incremental render is disabled (--render-splice false)."""
        tmpl = self._prefix_cache.template
        return tmpl.stats() if tmpl is not None else None

    # ------------------------------------------------------------------ poll

    def poll_once(self) -> PollStats:
        # One trace per poll (tpu_pod_exporter.trace): the root span also
        # arms the slow-poll stack sampler, and setting the thread-local
        # context here is what stamps trace ids onto every log line below.
        tracer = self._tracer
        tr = tracer.start_poll() if tracer is not None else None
        t0 = self._clock()
        errors: list[str] = []
        skips: list[str] = []

        # Phase 1: device read (analog of main.go:116-138, error-contained).
        # Supervised when a "device" supervisor exists: the call runs on a
        # fenced worker with a hard deadline, behind the source's breaker.
        td0 = self._clock()
        sup = self._supervisors.get("device")
        if tr is not None:
            tr.begin("device_read",
                     breaker=sup.breaker.state if sup is not None else "")
        dev_status = "ok"
        host_sample: HostSample | None = None
        try:
            host_sample = sup.call() if sup is not None else self._backend.sample()
            self._phase_recovered("device_read", supervised=sup is not None)
            for msg in host_sample.partial_errors:
                errors.append("device_partial")
                self._rlog.warning("device_partial", "device partial error: %s", msg)
        except SourceSkipped as e:
            # The breaker quarantine working as designed — the phase
            # degrades like an error (stale/absent data is the truth), but
            # it is neither counted as a poll error nor logged past INFO:
            # the fault already logged when the breaker opened.
            dev_status = "skipped"
            skips.append("device_read")
            self._rlog.info("device_skip", "device read skipped: %s", e)
        except SourceTimeout as e:
            dev_status = "abandoned"
            errors.append("device_read")
            self._rlog.warning("device_timeout", "device read abandoned: %s", e)
        except BackendError as e:
            dev_status = "err"
            errors.append("device_read")
            self._count_phase_failure("device_read", sup)
            self._rlog.warning("device_read", "device read failed: %s", e)
        except Exception as e:  # noqa: BLE001 — never die in the loop
            dev_status = "err"
            errors.append("device_read")
            self._count_phase_failure("device_read", sup)
            self._rlog.error("device_read_unexpected", "device read failed unexpectedly: %s", e, exc_info=True)
        td1 = self._clock()
        if tr is not None:
            tr.end(dev_status,
                   chips=len(host_sample.chips) if host_sample is not None else 0)

        # Phase 2: attribution (replaces main.go:74-114).
        attr = self._read_attribution(errors, skips, tr)
        ta1 = self._clock()

        # Phase 2b: process scan (the honest analog of the reference's PID
        # harvest, main.go:92-109 — local procfs instead of kubectl exec).
        holders = None
        if self._process_scanner is not None:
            psup = self._supervisors.get("process_scan")
            if tr is not None:
                tr.begin("process_scan",
                         breaker=psup.breaker.state if psup is not None else "")
            scan_status = "ok"
            try:
                holders = (
                    psup.call() if psup is not None
                    else self._process_scanner.scan()
                )
                self._phase_recovered("process_scan", supervised=psup is not None)
                self._last_holders = holders
                self._last_holders_at = self._clock()
            except Exception as e:  # noqa: BLE001 — never die in the loop
                if isinstance(e, SourceSkipped):
                    scan_status = "skipped"
                    skips.append("process_scan")
                    self._rlog.info("process_scan_skip", "process scan skipped: %s", e)
                elif isinstance(e, SourceTimeout):
                    scan_status = "abandoned"
                    errors.append("process_scan")
                    self._rlog.warning("process_scan_timeout", "process scan abandoned: %s", e)
                else:
                    scan_status = "err"
                    errors.append("process_scan")
                    self._count_phase_failure("process_scan", psup)
                    self._rlog.warning("process_scan", "process scan failed: %s", e)
                if (
                    self._last_holders is not None
                    and self._clock() - self._last_holders_at
                    <= self._attribution_max_stale_s
                ):
                    holders = self._last_holders
            if tr is not None:
                tr.end(scan_status,
                       holders=len(holders) if holders is not None else 0)
        tps1 = self._clock()

        # Phase 3: join (replaces main.go:141-154).
        if tr is not None:
            tr.begin("join")
        device_owner = attr.by_device_id(self._resource_name) if attr else {}
        allocatable = attr.allocatable_device_ids if attr else None
        # None ⇒ "source cannot report"; 0 is a real, publishable value on an
        # idle node. A source that reports neither allocations nor inventory
        # (attribution disabled / "none") stays absent rather than claiming 0.
        allocated = (
            len(device_owner)
            if attr is not None
            and (attr.allocations or attr.allocatable_device_ids is not None)
            else None
        )
        tj1 = self._clock()
        if tr is not None:
            tr.end("ok", owned_devices=len(device_owner))

        # Phase 4: publish (snapshot build + swap).
        stats = PollStats(
            device_read_s=td1 - td0,
            attribution_s=ta1 - td1,
            process_scan_s=tps1 - ta1,
            join_s=tj1 - tps1,
            # A skipped device phase degrades up exactly like a failed one:
            # no device data was read either way.
            ok="device_read" not in errors and "device_read" not in skips,
            errors=tuple(errors),
            skipped=tuple(skips),
            trace_id=tr.trace_id if tr is not None else "",
        )
        if tr is not None:
            tr.begin("publish")
        snap = self._publish(host_sample, device_owner, stats, now_mono=tj1,
                             allocatable=allocatable, allocated=allocated,
                             holders=holders)
        tp1 = self._clock()
        if tr is not None:
            tr.end("ok", series=snap.series_count)
        stats.publish_s = tp1 - tj1
        stats.total_s = tp1 - t0
        self.last_stats = stats
        # Cumulative distributions; this poll's publish/total are complete
        # here (unlike the point-in-time gauges, which lag them by one poll).
        for phase, dur in (
            ("device_read", stats.device_read_s),
            ("attribution", stats.attribution_s),
            ("process_scan", stats.process_scan_s),
            ("join", stats.join_s),
            ("publish", stats.publish_s),
            ("total", stats.total_s),
        ):
            self._phase_hist.observe(dur, (phase,))
        # History append LAST, outside every phase timing: the snapshot is
        # already swapped (scrapes serve it; the history lock is never on
        # the scrape path) and the append must not inflate the publish/total
        # phase distributions it is separately accounted against
        # (tpu_exporter_history_append_seconds).
        if self._history is not None:
            if tr is not None:
                tr.begin("history_append")
            th0 = self._clock()
            appended = 0
            hist_status = "ok"
            try:
                appended = self._history.append_snapshot(
                    snap, now_mono=th0, now_wall=snap.timestamp
                )
            except Exception as e:  # noqa: BLE001 — recording must not fail a poll
                hist_status = "err"
                self._rlog.error(
                    "history_append", "history append failed: %s", e,
                    exc_info=True,
                )
            self._history_append_s = self._clock() - th0
            if tr is not None:
                tr.end(hist_status, samples=appended)
            # The append IS part of the poll's latency story even though it
            # is excluded from publish/total: give it its own distribution
            # label so the per-phase heatmap shows where post-swap time goes.
            self._phase_hist.observe(self._history_append_s, ("history_append",))
        # Persistence LAST, on its own supervised phase: the snapshot is
        # swapped and the history append has run, so the WAL record covers
        # exactly what a restart would need — and like the history append
        # it never inflates the publish/total distributions (satellite
        # audit: persistence I/O must not read as poll latency).
        if self._persister is not None:
            if tr is not None:
                tr.begin("persist")
            tq0 = self._clock()
            queued = 0
            persist_status = "ok"
            try:
                queued = self._persister.on_poll(snap)
            except Exception as e:  # noqa: BLE001 — persistence must not fail a poll
                persist_status = "err"
                self._rlog.error(
                    "persist", "persistence enqueue failed: %s", e,
                    exc_info=True,
                )
            self._persist_s = self._clock() - tq0
            if tr is not None:
                tr.end(persist_status, queued=queued)
            self._phase_hist.observe(self._persist_s, ("persist",))
        # Egress LAST, on its own phase: the snapshot is swapped, recorded,
        # and persisted, so the batch the shipper's writer extracts covers
        # exactly what every other consumer saw — and like persist, the
        # enqueue must never read as publish/total poll latency (the
        # phase-exclusion is test-asserted in tests/test_egress.py).
        if self._shipper is not None:
            if tr is not None:
                tr.begin("egress")
            te0 = self._clock()
            equeued = 0
            egress_status = "ok"
            try:
                equeued = self._shipper.on_snapshot(snap)
            except Exception as e:  # noqa: BLE001 — egress must not fail a poll
                egress_status = "err"
                self._rlog.error(
                    "egress", "egress enqueue failed: %s", e, exc_info=True,
                )
            self._egress_s = self._clock() - te0
            if tr is not None:
                tr.end(egress_status, queued=equeued)
            self._phase_hist.observe(self._egress_s, ("egress",))
        if tr is not None:
            tracer.finish(tr, status="ok" if stats.ok else "err",
                          errors=len(errors), skips=len(skips))
            if tr.slow:
                # Trace-correlated breadcrumb for the incident timeline; the
                # profile itself lives in /debug/trace, not in the logs.
                # Logs the ROOT SPAN duration — the number the slow
                # classification actually compared (it includes the
                # history append, which stats.total_s deliberately
                # excludes; printing total_s here could contradict the
                # budget the line claims was exceeded).
                self._rlog.warning(
                    "slow_poll",
                    "slow poll: %.3fs > %.3gs budget (trace %s, %d profile "
                    "samples — GET /debug/trace)",
                    tr.root.dur_s, tracer.slow_poll_s, tr.trace_id,
                    tr.profile_samples,
                )
        return stats

    def _read_attribution(self, errors: list[str], skips: list[str],
                          tr: "PollTrace | None" = None) -> AttributionSnapshot | None:
        now = self._clock()
        sup = self._supervisors.get("attribution")
        if tr is not None:
            tr.begin("attribution",
                     breaker=sup.breaker.state if sup is not None else "")
        status = "ok"
        snap = None
        try:
            snap = sup.call() if sup is not None else self._attribution.snapshot()
            self._phase_recovered("attribution", supervised=sup is not None)
            self._last_attr = snap
            self._last_attr_at = now
        except SourceSkipped as e:
            status = "skipped"
            skips.append("attribution")
            self._rlog.info("attribution_skip", "attribution read skipped: %s", e)
        except SourceTimeout as e:
            status = "abandoned"
            errors.append("attribution")
            self._rlog.warning("attribution_timeout", "attribution read abandoned: %s", e)
        except AttributionError as e:
            status = "err"
            errors.append("attribution")
            self._count_phase_failure("attribution", sup)
            self._rlog.warning("attribution", "attribution read failed: %s", e)
        except Exception as e:  # noqa: BLE001
            status = "err"
            errors.append("attribution")
            self._count_phase_failure("attribution", sup)
            self._rlog.error("attribution_unexpected", "attribution failed unexpectedly: %s", e, exc_info=True)
        if snap is None and (
            self._last_attr is not None
            and now - self._last_attr_at <= self._attribution_max_stale_s
        ):
            # Bounded-staleness reuse of the last good snapshot.
            snap = self._last_attr
            if tr is not None:
                trace_mod.annotate(
                    f"reusing attribution snapshot from "
                    f"{now - self._last_attr_at:.1f}s ago (bounded staleness)"
                )
        if tr is not None:
            tr.end(status,
                   allocations=len(snap.allocations) if snap is not None else 0)
        return snap

    # ------------------------------------------------- phase fault tracking

    def _count_phase_failure(self, key: str, sup: "SourceSupervisor | None") -> None:
        """Track consecutive failures for recovery log lines — only on the
        unsupervised path (a SourceSupervisor tracks and logs its own)."""
        if sup is None:
            self._phase_failures[key] = self._phase_failures.get(key, 0) + 1

    def _phase_recovered(self, key: str, supervised: bool) -> None:
        if supervised:
            return
        n = self._phase_failures.get(key, 0)
        if n:
            self._phase_failures[key] = 0
            # Bypasses the rate limit: the end of an incident must always
            # be visible, even inside the fault lines' suppression window.
            self._rlog.recovery(
                key, "source %s healthy again after %d failure(s)", key, n
            )

    # --------------------------------------------------------------- publish

    def _publish(self, host_sample: HostSample | None,
                 device_owner: dict[str, Any], stats: PollStats,
                 now_mono: float, allocatable: Iterable[str] | None = None,
                 allocated: int | None = None,
                 holders: Sequence[Any] | None = None) -> "Snapshot":
        b = SnapshotBuilder(prefix_cache=self._prefix_cache)

        # GPU-family detection BEFORE the declares: a recorded/fake mixed
        # host whose first GPU chip appears this poll must declare the
        # gpu_* families in the same snapshot that carries their samples.
        if not self._gpu_surface and host_sample is not None:
            for c in host_sample.chips:
                if c.info.family == "gpu":
                    self._gpu_surface = True
                    break

        # Declare the full schema up front so families are present (and typed)
        # even when sample-less — scrapers see a stable surface from poll #1.
        for spec in schema.ALL_SPECS:
            b.declare(spec)
        self._phase_hist.emit(b)
        if self._scrape_hist is not None:
            self._scrape_hist.emit(b)
        if self._legacy_metrics:
            b.declare(schema.LEGACY_POD_MEMORY_USAGE)
            b.declare(schema.LEGACY_POD_MEMORY_PERC_USAGE)
        if self._process_scanner is not None:
            b.declare(schema.TPU_CHIP_PROCESS_INFO)
        if self._gpu_surface:
            for spec in schema.GPU_NODE_SPECS:
                b.declare(spec)

        # device_path -> holders, for the per-chip process join. Holder sets
        # are tiny (≈ one workload process per chip), so a plain dict-of-lists
        # rebuilt per poll is cheaper than caching machinery.
        holders_by_path: dict[str, list] = {}
        if holders:
            for h in holders:
                holders_by_path.setdefault(h.device_path, []).append(h)

        # (family, *pod labels) -> [chips, hbm_used, chips_with_readable_hbm]
        # — family-keyed so a mixed host (recorded/fake) rolls each pod up
        # under its own namespace (tpu_pod_* vs gpu_pod_*), never summed
        # across families.
        pod_rollup: dict[tuple[str, ...], list[float]] = {}
        # (pod, pid) -> [hbm_used, hbm_total] for the legacy aliases; pid is
        # "" when no process scanner or no holder was seen for the chip.
        legacy_rollup: dict[tuple[str, str], list[float]] = {}

        if host_sample is not None:
            dt = None
            if self._prev_ici_at is not None:
                dt = max(now_mono - self._prev_ici_at, 1e-9)
            seq = self._publish_seq = self._publish_seq + 1
            # Direct samples-dict handles: one dict store per series instead
            # of a full add() (family lookup + shape checks) — at 256 chips ×
            # ~16 series × 1 s that overhead is the largest publish cost.
            hbm_used_s = b.series(schema.TPU_HBM_USED_BYTES)
            hbm_total_s = b.series(schema.TPU_HBM_TOTAL_BYTES)
            hbm_pct_s = b.series(schema.TPU_HBM_USED_PERCENT)
            hbm_peak_s = b.series(schema.TPU_HBM_PEAK_BYTES)
            chip_info_s = b.series(schema.TPU_CHIP_INFO)
            duty_s = b.series(schema.TPU_TENSORCORE_DUTY_CYCLE_PERCENT)
            if self._gpu_surface:
                # The gpu_* twins; the per-chip loop below selects handles
                # by ChipInfo.family (one compare per chip — free next to
                # the dict stores it gates).
                g_used_s = b.series(schema.GPU_HBM_USED_BYTES)
                g_total_s = b.series(schema.GPU_HBM_TOTAL_BYTES)
                g_pct_s = b.series(schema.GPU_HBM_USED_PERCENT)
                g_util_s = b.series(schema.GPU_UTILIZATION_PERCENT)
                g_info_s = b.series(schema.GPU_CHIP_INFO)
            ici_total_s = b.series(schema.TPU_ICI_TRANSFERRED_BYTES_TOTAL)
            ici_bw_s = b.series(schema.TPU_ICI_LINK_BANDWIDTH_BYTES_PER_SECOND)
            dcn_total_s = b.series(schema.TPU_DCN_TRANSFERRED_BYTES_TOTAL)
            dcn_bw_s = b.series(schema.TPU_DCN_LINK_BANDWIDTH_BYTES_PER_SECOND)
            label_cache = self._label_cache
            if len(label_cache) > 4 * len(host_sample.chips) + 64:
                label_cache.clear()
            chip_state = self._chip_state
            if len(chip_state) > 2 * len(host_sample.chips) + 64:
                # Prune only vanished chips — never live ones. A wholesale
                # clear would re-seed surviving links' counters at the raw
                # reading, which regresses the exported counter whenever a
                # device reset ever happened (folded > raw from then on).
                live = {c.info.chip_id for c in host_sample.chips}
                for cid in [cid for cid in chip_state if cid not in live]:
                    del chip_state[cid]
                for cid in [c for c in self._dcn_state if c not in live]:
                    del self._dcn_state[cid]
            chips = host_sample.chips
            flat = self._ici_flat
            # Steady-state fast path precondition; per-chip identity is
            # verified inside the loop and any mismatch drops to slow.
            fast = (
                flat is not None
                and dt is not None
                and len(chips) == len(flat["chips"])
            )
            raw_buf = flat["raw_buf"] if fast else None
            chip_cached: list = []  # (chip, cached) for the link fold pass
            for ci, chip in enumerate(chips):
                owner = None
                for did in chip.info.device_ids:
                    owner = device_owner.get(did)
                    if owner is not None:
                        break
                info = chip.info
                cache_key = (
                    info.chip_id,
                    info.device_path,  # re-enumeration can move a chip_id
                    owner.pod if owner else "",
                    owner.namespace if owner else "",
                    owner.container if owner else "",
                )
                cached = label_cache.get(cache_key)
                if cached is None:
                    # Pre-ordered to CHIP_LABELS.
                    chip_tuple = (
                        str(info.chip_id),
                        info.device_path,
                        *self._topo_tuple,
                        *cache_key[2:],
                    )
                    # device_kind/coords are static per chip: build the
                    # tpu_chip_info label tuple once here, not per poll.
                    # ALWAYS published (empty kind/coords stay empty labels):
                    # since round 4 a chip with unreadable HBM emits no
                    # tpu_hbm_* series, so chip_info is the one guaranteed
                    # per-chip presence series — the aggregator counts
                    # chips/hosts_reporting from it.
                    info_tuple = chip_tuple + (info.device_kind, info.coords)
                    cached = (chip_tuple, {}, info_tuple)
                    label_cache[cache_key] = cached
                chip_tuple, link_tuples, info_tuple = cached
                # Family dispatch: one string compare per chip selects the
                # tpu_* or gpu_* series handles — the label schema is
                # shared, only the namespace differs.
                fam = info.family
                if fam == "gpu":
                    used_sel, total_sel = g_used_s, g_total_s
                    pct_sel, duty_sel, info_sel = g_pct_s, g_util_s, g_info_s
                else:
                    used_sel, total_sel = hbm_used_s, hbm_total_s
                    pct_sel, duty_sel, info_sel = hbm_pct_s, duty_s, chip_info_s
                # None = backend couldn't read HBM (tunnel with empty
                # memory_stats): publish no series — absent beats fake-zero
                # (main.go:129-132 never publishes an unread value).
                used = chip.hbm_used_bytes
                total_b = chip.hbm_total_bytes
                if used is not None:
                    used_sel[chip_tuple] = used
                if total_b is not None:
                    total_sel[chip_tuple] = total_b
                if used is not None and total_b is not None and total_b > 0:
                    # hbm_used_percent inlined (analog of main.go:149-150).
                    # total==0 ⇒ omit the series: a percent of a zero/unread
                    # total is undefined, and 0.0 would read as "idle".
                    pct_sel[chip_tuple] = used / total_b * 100.0
                if chip.hbm_peak_bytes is not None and fam == "tpu":
                    # No gpu twin: NVML serves no allocator high-water mark.
                    hbm_peak_s[chip_tuple] = chip.hbm_peak_bytes
                if chip.tensorcore_duty_cycle_percent is not None:
                    # For GPU chips this slot carries the NVML utilization
                    # rate (GetUtilizationRates.gpu) — see ChipSample.
                    duty_sel[chip_tuple] = chip.tensorcore_duty_cycle_percent
                info_sel[info_tuple] = 1.0
                if fam == "gpu" and chip.processes:
                    # The runtime's own per-process table
                    # (GetComputeRunningProcesses, main.go:134-155): honest
                    # host PIDs straight from the driver, pod attribution
                    # from the same device-ID join as every chip series.
                    for pr in chip.processes:
                        b.add(
                            schema.GPU_PROCESS_MEMORY_USED_BYTES,
                            pr.used_bytes,
                            chip_tuple + (str(pr.pid), pr.comm),
                        )

                # Link work is deferred to the fold pass below; here the fast
                # path only verifies layout identity and extracts raw totals.
                links = chip.ici_links
                if fast:
                    ent = flat["chips"][ci]
                    if ent[0] is cached and len(links) == len(ent[1]):
                        ids = ent[1]
                        base = ent[2]
                        # Index access (IciLinkSample is a NamedTuple:
                        # [0]=link, [1]=transferred_bytes_total) skips two
                        # descriptor lookups per link on the hottest loop.
                        for j, l in enumerate(links):
                            lid = l[0]
                            if lid is ids[j] or lid == ids[j]:
                                raw_buf[base + j] = l[1]
                            else:
                                fast = False
                                break
                    else:
                        fast = False
                chip_cached.append((chip, cached))

                chip_holders = (
                    holders_by_path.get(info.device_path)
                    if holders_by_path
                    else None
                )
                if chip_holders:
                    for h in chip_holders:
                        b.add(
                            schema.TPU_CHIP_PROCESS_INFO,
                            1.0,
                            chip_tuple + (str(h.pid), h.comm, h.pod_uid),
                        )

                if owner is not None:
                    rk = (fam, owner.pod, owner.namespace) + self._topo_tuple
                    # [chips, hbm_used, chips_with_readable_hbm]
                    agg = pod_rollup.setdefault(rk, [0.0, 0.0, 0])
                    agg[0] += 1.0
                    # Unreadable (None) HBM contributes nothing — and if NO
                    # chip of the pod was readable, the pod HBM series is
                    # omitted below, same absent-beats-fake-zero rule as the
                    # per-chip series.
                    if used is not None:
                        agg[1] += used
                        agg[2] += 1
                    if (
                        self._legacy_metrics
                        and used is not None
                        and total_b is not None
                    ):
                        # The legacy shape has no namespace label (the
                        # reference collided on pod name, main.go:113); sum
                        # across namespaces rather than last-write-wins. With
                        # the process scanner on, the pid label carries the
                        # chip's primary (lowest-pid) holder so each chip's
                        # HBM is counted exactly once even under forked
                        # workers; "" otherwise. A chip missing EITHER HBM
                        # number is skipped entirely: half-folding would
                        # publish a fake-zero usage row or skew the percent
                        # denominator (used without total → pct inflation).
                        pid = str(chip_holders[0].pid) if chip_holders else ""
                        lagg = legacy_rollup.setdefault((owner.pod, pid), [0.0, 0.0])
                        lagg[0] += used
                        lagg[1] += total_b

            if fast:
                self._fold_ici_fast(ici_total_s, ici_bw_s, dt, seq)
            else:
                self._fold_ici_slow(chip_cached, ici_total_s, ici_bw_s, dt, seq)
            self._fold_dcn(chip_cached, dcn_total_s, dcn_bw_s, dt, seq)
            self._prev_ici_at = now_mono

        for rk, (nchips, hbm, readable) in pod_rollup.items():
            # rk[0] is the family key; the published labels are rk[1:].
            if rk[0] == "gpu":
                count_spec = schema.GPU_POD_CHIP_COUNT
                mem_spec = schema.GPU_POD_MEMORY_USED_BYTES
            else:
                count_spec = schema.TPU_POD_CHIP_COUNT
                mem_spec = schema.TPU_POD_HBM_USED_BYTES
            b.add(count_spec, nchips, rk[1:])
            if readable:
                b.add(mem_spec, hbm, rk[1:])
        for (pod, pid), (hbm, hbm_total) in legacy_rollup.items():
            # Reference-name aliases (main.go:24,31), label shape {pid, pod}.
            b.add(schema.LEGACY_POD_MEMORY_USAGE, hbm, (pid, pod))
            b.add(
                schema.LEGACY_POD_MEMORY_PERC_USAGE,
                schema.hbm_used_percent(hbm, hbm_total),
                (pid, pod),
            )

        # Host identity incl. multi-slice membership — the cross-slice
        # rollup join key (always published; empty labels off multi-slice).
        b.add(schema.TPU_HOST_INFO, 1.0, self._host_info_tuple)

        # Kubelet inventory (absent when the source cannot report it; an
        # allocated count of 0 on an idle node is real data, not absence).
        if allocatable is not None:
            b.add(schema.TPU_KUBELET_ALLOCATABLE_CHIPS, len(allocatable),
                  self._topo_tuple)
        if allocated is not None:
            b.add(schema.TPU_KUBELET_ALLOCATED_CHIPS, allocated,
                  self._topo_tuple)

        # Self-metrics (SURVEY.md §5).
        b.add(schema.TPU_EXPORTER_UP, 1.0 if stats.ok else 0.0)
        if self._gpu_surface:
            # Per-backend up for the GPU family: tracks the device half of
            # the poll (a GPU-node wedge drops this exactly the way a TPU
            # node drops tpu_exporter_up — the mixed-wedge drill's parity).
            b.add(schema.GPU_BACKEND_UP, 1.0 if stats.ok else 0.0)
        # Warm-start markers: every LIVE poll publishes 0 — a restored
        # exposition (persist.RestoredSnapshot) patches these two values to
        # 1 / the measured staleness, which only works because the series
        # are unconditionally present.
        b.add(schema.TPU_EXPORTER_WARM_START, 0.0)
        b.add(schema.TPU_EXPORTER_SNAPSHOT_STALE_SECONDS, 0.0)
        # This poll's read/join timings; publish/total are not known until
        # after the swap, so the previous poll's values stand in for them.
        for phase, dur in (
            ("device_read", stats.device_read_s),
            ("attribution", stats.attribution_s),
            ("process_scan", stats.process_scan_s),
            ("join", stats.join_s),
            ("publish", self.last_stats.publish_s),
            ("total", self.last_stats.total_s),
        ):
            b.add(schema.TPU_EXPORTER_POLL_DURATION_SECONDS, dur, {"phase": phase})
        for source in stats.errors:
            self._counters.inc(schema.TPU_EXPORTER_POLL_ERRORS_TOTAL.name, (source,))
        for lv, v in self._counters.items_for(schema.TPU_EXPORTER_POLL_ERRORS_TOTAL.name):
            b.add(schema.TPU_EXPORTER_POLL_ERRORS_TOTAL, v, lv)
        # Side-channel error counters a provider tracks itself (e.g. the
        # checkpoint path's uid-map fetch failures, which degrade to
        # last-good data without raising into a poll phase).
        attr_errors = getattr(self._attribution, "error_counters", None)
        if callable(attr_errors):
            for source, v in attr_errors().items():
                # Namespaced: a provider-chosen source name must never
                # collide with (b.add overwrites, not sums) a poll-phase
                # counter series like source="attribution".
                b.add(
                    schema.TPU_EXPORTER_POLL_ERRORS_TOTAL,
                    float(v),
                    (f"attribution.{source}",),
                )
        # Source-supervision surface (tpu_pod_exporter.supervisor): breaker
        # state + transition/abandon/skip/reconnect counters per source.
        # Families are declared via ALL_SPECS either way; samples exist only
        # when supervision is on.
        for source, sup in self._supervisors.items():
            st = sup.stats()
            b.add(schema.TPU_EXPORTER_SOURCE_BREAKER_STATE,
                  st["state_value"], (source,))
            for state, n in st["transitions"].items():
                b.add(schema.TPU_EXPORTER_SOURCE_BREAKER_TRANSITIONS_TOTAL,
                      float(n), (source, state))
            b.add(schema.TPU_EXPORTER_SOURCE_CALLS_ABANDONED_TOTAL,
                  float(st["abandoned"]), (source,))
            b.add(schema.TPU_EXPORTER_SOURCE_CALLS_SKIPPED_TOTAL,
                  float(st["skipped"]), (source,))
            b.add(schema.TPU_EXPORTER_SOURCE_RECONNECTS_TOTAL,
                  float(st["reconnects"]), (source,))

        # Tracing surface: slow-poll count + ring occupancy. Read one poll
        # behind (this publish runs before the current trace finishes) —
        # the same lag every other point-in-time self-metric carries.
        if self._tracer is not None:
            slow, traces, spans = self._tracer.store.counts()
            b.add(schema.TPU_EXPORTER_SLOW_POLLS_TOTAL, float(slow))
            b.add(schema.TPU_EXPORTER_TRACES, float(traces))
            b.add(schema.TPU_EXPORTER_TRACE_SPANS, float(spans))

        polls = self._counters.inc(schema.TPU_EXPORTER_POLLS_TOTAL.name, ())
        b.add(schema.TPU_EXPORTER_POLLS_TOTAL, polls)
        b.add(
            schema.TPU_EXPORTER_INFO,
            1.0,
            {
                "version": __version__,
                "backend": getattr(self._backend, "name", "?"),
                "attribution": getattr(self._attribution, "name", "?"),
            },
        )
        b.add(schema.TPU_EXPORTER_LAST_POLL_TIMESTAMP_SECONDS, self._wallclock())

        # Self-resource accounting (<1% CPU budget, auditable in production).
        cpu_s = utils.process_cpu_seconds()
        if cpu_s is not None:
            b.add(schema.TPU_EXPORTER_CPU_SECONDS_TOTAL, cpu_s)
        rss = utils.process_rss_bytes()
        if rss is not None:
            b.add(schema.TPU_EXPORTER_RSS_BYTES, rss)
        if self._scrape_rejects_fn is not None:
            try:
                for cause, n in self._scrape_rejects_fn().items():
                    b.add(
                        schema.TPU_EXPORTER_SCRAPE_REJECTS_TOTAL,
                        float(n),
                        (cause,),
                    )
            except Exception:  # noqa: BLE001 — accounting must never fail a poll
                pass
        if self._loop_overruns_fn is not None:
            try:
                b.add(
                    schema.TPU_EXPORTER_POLL_OVERRUNS_TOTAL,
                    float(self._loop_overruns_fn()),
                )
            except Exception:  # noqa: BLE001 — accounting must never fail a poll
                pass
        if self._client_write_timeouts_fn is not None:
            try:
                b.add(
                    schema.TPU_EXPORTER_CLIENT_WRITE_TIMEOUTS_TOTAL,
                    float(self._client_write_timeouts_fn()),
                )
            except Exception:  # noqa: BLE001 — accounting must never fail a poll
                pass

        # ICI counter state lives in self._chip_state (pruned above when it
        # outgrows its bound: vanished chips only, never live ones).
        # CounterStore now holds only the node-lifetime self-metric
        # counters, so there is nothing to prune per poll.

        if self._history is not None:
            # Point-in-time history accounting; reflects the append that ran
            # after the PREVIOUS swap (this poll's append happens below).
            hs = self._history.stats()
            b.add(schema.TPU_EXPORTER_HISTORY_SERIES, float(hs["series"]))
            b.add(schema.TPU_EXPORTER_HISTORY_SAMPLES, float(hs["samples"]))
            b.add(
                schema.TPU_EXPORTER_HISTORY_MEMORY_BYTES,
                float(hs["memory_bytes"]),
            )
            for reason, n in hs["evicted"].items():
                b.add(
                    schema.TPU_EXPORTER_HISTORY_EVICTED_SERIES_TOTAL,
                    float(n),
                    (reason,),
                )
            b.add(
                schema.TPU_EXPORTER_HISTORY_APPEND_SECONDS,
                self._history_append_s,
            )
            for tier in hs.get("tiers", ()):
                lbl = (f"{tier['step_s']:g}",)
                b.add(
                    schema.TPU_EXPORTER_HISTORY_TIER_BUCKETS,
                    float(tier["buckets"]), lbl,
                )
                b.add(
                    schema.TPU_EXPORTER_HISTORY_TIER_SPAN_SECONDS,
                    tier["span_s"], lbl,
                )

        if self._persister is not None:
            # Point-in-time persistence accounting (one poll behind, like
            # every other self-stat read mid-publish).
            try:
                ps = self._persister.stats()
                b.add(schema.TPU_EXPORTER_PERSIST_WAL_BYTES,
                      float(ps["wal_bytes"]))
                b.add(schema.TPU_EXPORTER_PERSIST_WAL_RECORDS_TOTAL,
                      float(ps["wal_records"]))
                b.add(schema.TPU_EXPORTER_PERSIST_SNAPSHOTS_TOTAL,
                      float(ps["snapshots"]))
                # Reason-split error/drop counters: a full disk
                # (reason="disk_full") and a flaky one (reason="io") page
                # different playbooks — see the DiskPressure alert.
                for reason, n in ps["errors_by_reason"].items():
                    b.add(schema.TPU_EXPORTER_PERSIST_ERRORS_TOTAL,
                          float(n), (reason,))
                for reason, n in ps["dropped_by_reason"].items():
                    b.add(schema.TPU_EXPORTER_PERSIST_DROPPED_TOTAL,
                          float(n), (reason,))
                b.add(schema.TPU_EXPORTER_PERSIST_FSYNC_SECONDS,
                      ps["last_fsync_s"])
                if ps["last_snapshot_wall"] > 0:
                    b.add(
                        schema.TPU_EXPORTER_PERSIST_SNAPSHOT_AGE_SECONDS,
                        max(self._wallclock() - ps["last_snapshot_wall"], 0.0),
                    )
            except Exception:  # noqa: BLE001 — accounting must never fail a poll
                pass

        if self._shipper is not None:
            # Conditional egress surface (EGRESS_SPECS), same rule as the
            # history/persist stats: declared + sampled only when a shipper
            # is attached, read one poll behind like every other self-stat.
            try:
                self._shipper.emit(b)
            except Exception:  # noqa: BLE001 — accounting must never fail a poll
                pass

        if self._governor is not None:
            # Conditional pressure surface (PRESSURE_SPECS): the ladder
            # rung, bytes-vs-budget pair, and every shed/recover
            # transition — the governor's cached numbers, no disk walk.
            try:
                self._governor.emit(b)
            except Exception:  # noqa: BLE001 — accounting must never fail a poll
                pass

        # +1 accounts for the series-count series itself.
        b.add(schema.TPU_EXPORTER_SERIES, float(b.series_count + 1))
        snap = b.build(timestamp=self._wallclock(), transfer=True)
        self._store.swap(snap)
        return snap

    # ------------------------------------------------------------- ICI fold

    def _fold_ici_fast(self, ici_total_s: dict[tuple[str, ...], float],
                       ici_bw_s: dict[tuple[str, ...], float],
                       dt: float, seq: int) -> None:
        """Steady-state fold: raw totals were extracted into flat['raw_buf']
        by the chip loop (layout verified); delta/clip/accumulate/rate happen
        as four numpy ops over all links at once, and the series dicts fill
        via C-speed dict.update. Valid because every link in the block was
        seen at the previous sampled publish (flat['seq'] == seq-1 by
        construction), which is exactly the slow path's bw-eligibility rule.
        """
        import numpy as np

        flat = self._ici_flat
        raw = np.array(flat["raw_buf"], dtype=np.float64)
        delta = raw - flat["raw_prev"]
        np.maximum(delta, 0.0, out=delta)  # device reset ⇒ counter holds
        folded = flat["folded"]
        folded += delta
        keys = flat["keys"]
        ici_total_s.update(zip(keys, folded.tolist()))
        # Same whole-bytes/s rounding as the slow path (renderer fast path).
        bw = np.rint(delta * (1.0 / dt))
        ici_bw_s.update(zip(keys, bw.tolist()))
        flat["raw_prev"] = raw
        flat["seq"] = seq

    def _export_ici_flat(self) -> None:
        """Write the flat arrays back into the per-link recs in _chip_state —
        they went stale while the fast path ran — then drop the block."""
        flat = self._ici_flat
        if flat is None:
            return
        raw_prev = flat["raw_prev"]
        folded = flat["folded"]
        seq = flat["seq"]
        for i, rec in enumerate(flat["recs"]):
            f = float(folded[i])
            rec[0] = float(raw_prev[i])
            rec[1] = f
            rec[2] = f
            rec[3] = seq
        self._ici_flat = None

    def _fold_ici_slow(self, chip_cached: list[tuple[Any, tuple]],
                       ici_total_s: dict[tuple[str, ...], float],
                       ici_bw_s: dict[tuple[str, ...], float],
                       dt: float | None, seq: int) -> None:
        """Per-link fold (first poll, churn, layout change): the reference
        semantics — monotonic fold with reset tolerance, rate only for links
        also seen at seq-1 — and the builder of the flat block the fast path
        uses on subsequent polls."""
        self._export_ici_flat()
        chip_state = self._chip_state
        flat_chips: list = []
        keys: list = []
        flat_recs: list = []
        base = 0
        for chip, cached in chip_cached:
            chip_tuple, link_tuples, _ = cached
            info = chip.info
            link_recs = chip_state.get(info.chip_id)
            if link_recs is None:
                link_recs = chip_state[info.chip_id] = {}
            ids: list = []
            for link in chip.ici_links:
                raw = link.transferred_bytes_total
                lv = link_tuples.get(link.link)
                if lv is None:
                    lv = link_tuples[link.link] = chip_tuple + (link.link,)  # ICI_LABELS ordering
                rec = link_recs.get(link.link)
                if rec is None:
                    # First sighting of this chip+link: seed the monotonic
                    # fold at the current raw reading
                    # (CounterStore.observe_total semantics).
                    folded = raw if raw >= 0 else 0.0
                    rec = link_recs[link.link] = [raw, folded, folded, seq]
                    ici_total_s[lv] = folded
                else:
                    raw_prev, folded, rate_base, last_seq = rec
                    delta = raw - raw_prev
                    if delta > 0:
                        folded = rec[1] = folded + delta
                    rec[0] = raw
                    ici_total_s[lv] = folded
                    if dt is not None and last_seq == seq - 1:
                        # Rounded to whole bytes/s: sub-byte rate precision
                        # is noise, and integral values take the renderer's
                        # fast integer path.
                        bw = (folded - rate_base) / dt
                        ici_bw_s[lv] = round(bw) if bw > 0.0 else 0.0
                    rec[2] = folded
                    rec[3] = seq
                ids.append(link.link)
                keys.append(lv)
                flat_recs.append(rec)
            flat_chips.append((cached, tuple(ids), base))
            base += len(ids)

        try:
            import numpy as np
        except ImportError:
            # No numpy (minimal image): stay on the per-link fold every poll
            # — correct, just without the steady-state speedup.
            return

        self._ici_flat = {
            "chips": flat_chips,
            "keys": keys,
            "recs": flat_recs,
            "raw_buf": [0.0] * len(keys),
            "raw_prev": np.array([r[0] for r in flat_recs], dtype=np.float64),
            "folded": np.array([r[1] for r in flat_recs], dtype=np.float64),
            "seq": seq,
        }

    def _fold_dcn(self, chip_cached: list[tuple[Any, tuple]],
                  dcn_total_s: dict[tuple[str, ...], float],
                  dcn_bw_s: dict[tuple[str, ...], float],
                  dt: float | None, seq: int) -> None:
        """Per-link DCN fold: identical semantics to the slow ICI fold
        (monotonic with reset tolerance; rate only for links also seen at
        seq-1). Shares each chip's cached link-label-tuple dict with ICI —
        a given link id renders to the same label tuple either way, and
        the two counter families never collide (different metric names)."""
        dcn_state = self._dcn_state
        for chip, cached in chip_cached:
            links = chip.dcn_links
            if not links:
                continue
            chip_tuple, link_tuples, _ = cached
            link_recs = dcn_state.get(chip.info.chip_id)
            if link_recs is None:
                link_recs = dcn_state[chip.info.chip_id] = {}
            for link in links:
                raw = link.transferred_bytes_total
                lv = link_tuples.get(link.link)
                if lv is None:
                    lv = link_tuples[link.link] = chip_tuple + (link.link,)
                rec = link_recs.get(link.link)
                if rec is None:
                    folded = raw if raw >= 0 else 0.0
                    link_recs[link.link] = [raw, folded, folded, seq]
                    dcn_total_s[lv] = folded
                    continue
                raw_prev, folded, rate_base, last_seq = rec
                delta = raw - raw_prev
                if delta > 0:
                    folded = rec[1] = folded + delta
                rec[0] = raw
                dcn_total_s[lv] = folded
                if dt is not None and last_seq == seq - 1:
                    bw = (folded - rate_base) / dt
                    dcn_bw_s[lv] = round(bw) if bw > 0.0 else 0.0
                rec[2] = folded
                rec[3] = seq

    def close(self) -> None:
        for sup in self._supervisors.values():
            sup.shutdown()
        self._backend.close()
        self._attribution.close()


class CollectorLoop:
    """Background thread driving Collector.poll_once on a fixed schedule.

    Ticks at ``start + n·interval`` (no drift), skips ticks it cannot meet
    (logs + counts overruns rather than queueing), and exits promptly on
    ``stop()`` — real SIGTERM drain for DaemonSet rolling updates, which the
    reference lacks entirely (SURVEY.md §3.4).

    Thread-death supervision: per-iteration containment catches ``Exception``,
    but a ``BaseException`` escaping ``poll_once`` (SystemExit from a
    misbehaving dependency, MemoryError, a bug in the containment itself)
    would silently kill the thread — snapshots stop swapping and only the
    slow ``health_max_age_s`` staleness trip would notice. Instead the loop
    is restarted ONCE; a second death marks it ``dead``, which the app's
    ``/healthz`` hook reports as an immediate 503 so kubelet restarts the
    pod promptly.

    Boot is the exception to restart-once: a crash BEFORE the first
    iteration ever completed is usually a transient boot-time wedge (the
    device runtime still initializing while kubelet races the DaemonSet
    up), and declaring ``dead`` after one retry turns a 2-second wedge
    into a pod restart loop. First-poll crash loops therefore retry up to
    ``boot_max_restarts`` times with a small exponential delay
    (``boot_restart_backoff_s`` · 2^n) before staying down; once any
    iteration has completed, the steady-state restart-once contract is
    unchanged.
    """

    MAX_RESTARTS = 1
    BOOT_MAX_RESTARTS = 3

    def __init__(self, collector: Collector, interval_s: float = 1.0,
                 boot_max_restarts: int = BOOT_MAX_RESTARTS,
                 boot_restart_backoff_s: float = 0.25) -> None:
        if interval_s <= 0:
            raise ValueError("interval must be positive")
        self._collector = collector
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._restart_lock = threading.Lock()
        self.overruns = 0
        self.restarts = 0
        self.dead = False
        self.boot_max_restarts = boot_max_restarts
        self.boot_restart_backoff_s = boot_restart_backoff_s
        # Flipped after the first completed iteration (crash or not inside
        # poll_once's own containment — "completed" means the thread
        # survived it); selects the boot vs steady-state restart budget.
        self.first_iteration_done = False

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("already started")
        self._thread = self._spawn()

    def _spawn(self) -> threading.Thread:
        t = threading.Thread(
            target=self._run_guarded, name="tpu-exporter-poll", daemon=True
        )
        t.start()
        return t

    def _run_guarded(self) -> None:
        try:
            self._run()
        except BaseException:  # noqa: BLE001  # lint: disable=bare-except(thread-death supervision: the ONE sanctioned poll-restart path — see class docstring)
            # Decide + mutate under the lock; log AFTER release (lock-io
            # discipline — log handlers do stream I/O, and stop() takes
            # this lock on the SIGTERM drain path).
            with self._restart_lock:
                if self._stop.is_set():
                    return
                boot = not self.first_iteration_done
                budget = self.boot_max_restarts if boot else self.MAX_RESTARTS
                respawn = self.restarts < budget
                delay = 0.0
                if respawn:
                    self.restarts += 1
                    if boot:
                        # Exponential boot backoff: a transient device
                        # wedge gets a beat to clear before the retry; a
                        # deterministic crash burns the budget in ~2 s
                        # instead of hot-looping.
                        delay = self.boot_restart_backoff_s * (
                            2.0 ** (self.restarts - 1)
                        )
                    else:
                        self._thread = self._spawn()
                else:
                    self.dead = True
            if respawn:
                log.critical(
                    "poll loop thread died unexpectedly%s; restarting "
                    "(%d/%d)%s",
                    " during boot (first poll never completed)" if boot
                    else "",
                    self.restarts, budget,
                    f" in {delay:g}s" if delay > 0 else "",
                    exc_info=True,
                )
                if delay > 0:
                    # Outside the lock: stop() must never wait on this.
                    if self._stop.wait(delay):
                        return
                    with self._restart_lock:
                        if self._stop.is_set():
                            return
                        self._thread = self._spawn()
            else:
                log.critical(
                    "poll loop died again (%d restart(s) used); staying "
                    "down — /healthz reports 503", self.restarts,
                    exc_info=True,
                )

    def _run(self) -> None:
        start = time.monotonic()
        n = 0
        while not self._stop.is_set():
            try:
                self._collector.poll_once()
            except Exception:  # noqa: BLE001 — the loop must survive anything
                log.exception("poll iteration failed")
            if not self.first_iteration_done:
                self.first_iteration_done = True
                if self.restarts:
                    # The boot-time wedge cleared: the steady-state budget
                    # starts fresh (a restart used booting must not spend
                    # the one steady-state restart).
                    self.restarts = 0
            n += 1
            next_tick = start + n * self.interval_s
            now = time.monotonic()
            if next_tick <= now:
                missed = int((now - start) / self.interval_s) - n + 1
                if missed > 0:
                    self.overruns += missed
                    n += missed
                    next_tick = start + n * self.interval_s
            self._stop.wait(max(next_tick - time.monotonic(), 0.0))

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._restart_lock:  # the thread may have been restart-swapped
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=timeout)
