"""Deterministic fault injection — the chaos harness for source supervision.

Wraps any poll source (device backend, attribution provider, process
scanner) and injects faults on a **seeded, reproducible schedule**:

- ``hang``    — block the call for a duration (exercises the phase
  deadline + abandoned-worker path in ``supervisor.py``);
- ``err``     — raise :class:`ChaosError` (the ordinary error-containment
  path);
- ``slow``    — add latency, then proceed (deadline-adjacent but returning);
- ``garbage`` — return a *well-formed but bogus* value (negative HBM, NaN
  duty cycle, label-hostile pod names) so value-robustness is exercised,
  not just control flow.
- ``kill``    — SIGKILL the whole process mid-call: no drain, no flush, no
  atexit — the crash the persistence layer (``persist.py``) must survive.
  Exercised by ``make restart-demo``.

Spec grammar (``--chaos-spec``, test-only flag)::

    spec  := rule ("," rule)*
    rule  := kind ":" source (":" token)*
    kind  := hang | err | slow | garbage | kill | reject | truncate
    source:= device | attribution | procscan | recv

The ``recv`` source is the **remote-write receiver** (:class:`ChaosReceiver`
— an in-process HTTP receiver the egress shipper posts batches at, used by
``make egress-demo`` and ``tests/test_egress.py``) rather than a wrapped
poll source: ``hang``/``slow`` park the request, ``err`` answers 500,
``reject`` answers 429 (backpressure), and ``truncate`` reads part of the
request body then drops the connection mid-transfer. ``reject``/``truncate``
are receiver-only; ``garbage``/``kill`` are source-only.

Tokens after the source are order-free: a bare float in [0, 1] is the
per-call probability (default 1.0), a duration with a unit ("500ms",
"10s", "0.3s") is the hang/slow length, ``xN`` caps the rule at N
injections total, and ``@N`` arms the rule only from call index N on
(0-based — the knob that places a kill *mid-run*, after state worth
persisting exists). Examples::

    hang:device:0.01                 1% of device reads hang (default 3600s)
    err:attribution:0.05             5% of attribution reads raise
    slow:procscan:500ms              every process scan takes +500ms
    hang:device:1:10s:x3             the first three device reads hang 10s
    kill:device:1:@20:x1             SIGKILL on the 21st device read

Determinism: each source draws from its own ``random.Random`` seeded with
``f"{seed}:{source}"``, and the single poll thread calls sources in a fixed
order — so a given (spec, seed) injects the same faults on the same call
indices on every run, regardless of wall-clock timing. Used by
``tests/test_chaos.py`` and ``make chaos-demo``.
"""

from __future__ import annotations

import logging
import random
import re
import threading
import time
from dataclasses import dataclass, field

from tpu_pod_exporter import trace as trace_mod

log = logging.getLogger("tpu_pod_exporter.chaos")

KINDS = ("hang", "err", "slow", "garbage", "kill", "reject", "truncate")
SOURCES = ("device", "attribution", "procscan", "recv")

# The remote-write receiver target (``recv``) injects wire-level faults
# the wrapped in-process sources have no analog for — and vice versa.
RECEIVER_ONLY_KINDS = ("reject", "truncate")
RECEIVER_INVALID_KINDS = ("garbage", "kill")

DEFAULT_HANG_S = 3600.0   # "forever" at poll-loop scale; the deadline fences it
DEFAULT_SLOW_S = 0.25

_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s)$")
_COUNT_RE = re.compile(r"^x(\d+)$")
_OFFSET_RE = re.compile(r"^@(\d+)$")


# --------------------------------------------------------------- seam registry
#
# Every distinct place this toolbox (plus the scenario engine driving it)
# can inject a fault, enumerable at runtime. The fuzzer's coverage ledger
# keys its (seam × invariant) matrix off this registry and cross-checks it
# against the DSL's kind→seam map in BOTH directions, so an injector added
# here without a generator (or a generator naming a ghost seam) fails a
# tier-1 test instead of being silently omitted from coverage.

@dataclass(frozen=True)
class Seam:
    """One injection seam: a named fault surface and the mechanism that
    cuts it (class or engine hook), for the coverage report."""

    name: str
    description: str


SEAM_REGISTRY: dict[str, Seam] = {}


def register_seam(name: str, description: str) -> Seam:
    """Register one seam (module-import time, next to its injector). Loud
    on duplicates: two injectors claiming one seam would make the
    coverage matrix under-count."""
    if name in SEAM_REGISTRY:
        raise ValueError(f"chaos seam {name!r} registered twice")
    seam = Seam(name=name, description=description)
    SEAM_REGISTRY[name] = seam
    return seam


def registered_seams() -> tuple[str, ...]:
    """Sorted seam names — the coverage matrix's row space."""
    return tuple(sorted(SEAM_REGISTRY))


# The wire seams PartitionState/PartitionedFetch/PartitionedSend cut, one
# per tier edge the stack actually crosses (scenario.PARTITION_EDGES).
register_seam("wire:node-leaf",
              "leaf→target scrape fetches (PartitionedFetch at the leaf "
              "poll seam)")
register_seam("wire:leaf-root",
              "root→leaf merge fetches + query fan-out (PartitionedFetch "
              "at the root seam)")
register_seam("wire:root-recv",
              "root→receiver remote-write posts (PartitionedSend at the "
              "egress seam)")
# Host-level injectors.
register_seam("wallclock",
              "NTP-shaped wall-clock steps (ClockStepper — the egress "
              "clock fence's subject)")
register_seam("memory",
              "memory-budget collapse over the byte-accounted caches "
              "(MemoryHog / the governor's squeezed memory budget)")
register_seam("disk",
              "disk-budget collapse under the durable-state dirs (the "
              "governor's squeezed disk budget)")
register_seam("serving",
              "aggressive keep-alive scrape load on the serving tier "
              "(ScrapeStorm vs the admission caps)")
register_seam("receiver",
              "remote-write receiver outage/flap (ChaosReceiver "
              "set_outage — breaker + backlog + exactly-once drain)")
# Process/fleet seams the scenario engine injects through the sim.
register_seam("target-process",
              "target processes dying and returning (farm dead set: "
              "preempt / restart_wave)")
register_seam("root-process",
              "SIGKILL-shaped root death + fresh-instance restart "
              "(_ShardSim.kill_root/restart_root)")
register_seam("workload",
              "workload behavior shifts: hotspot duty/HBM spikes and "
              "pod label churn (farm hot set / pod_gen)")
register_seam("membership",
              "targets-file membership churn (add/remove waves through "
              "the shared targets file)")
register_seam("stream",
              "streaming dashboard subscription load against "
              "/api/v1/stream (_StormSubscribers vs the hub caps)")


class ChaosError(RuntimeError):
    """An injected source failure (the ``err`` fault kind)."""


@dataclass
class ChaosRule:
    kind: str
    source: str
    prob: float = 1.0
    duration_s: float | None = None  # hang/slow length; kind-default if None
    max_count: int | None = None     # total injection cap; None = unlimited
    min_index: int = 0               # rule armed from this call index on (@N)
    # err:device rules may speak exact NVML error shapes
    # (``err:device:1:nvml=gpu_is_lost``): the injected exception is an
    # NvmlError carrying this code, so GPU-path drills exercise the same
    # typed failures the reference dies on (main.go:119-137).
    nvml_code: str = ""
    fired: int = field(default=0, compare=False)

    @property
    def effective_duration_s(self) -> float:
        if self.duration_s is not None:
            return self.duration_s
        return DEFAULT_HANG_S if self.kind == "hang" else DEFAULT_SLOW_S


def parse_chaos_spec(spec: str) -> list[ChaosRule]:
    """``--chaos-spec`` string → rule list. Raises ValueError loudly on any
    malformed rule — a typo'd chaos spec must fail at startup, not silently
    inject nothing during the test it was written for."""
    rules: list[ChaosRule] = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) < 2:
            raise ValueError(f"chaos rule {raw!r}: want kind:source[:tokens]")
        kind, source = parts[0].strip().lower(), parts[1].strip().lower()
        if kind not in KINDS:
            raise ValueError(f"chaos rule {raw!r}: unknown kind {kind!r} "
                             f"(want one of {'/'.join(KINDS)})")
        if source not in SOURCES:
            raise ValueError(f"chaos rule {raw!r}: unknown source {source!r} "
                             f"(want one of {'/'.join(SOURCES)})")
        if kind in RECEIVER_ONLY_KINDS and source != "recv":
            raise ValueError(f"chaos rule {raw!r}: kind {kind!r} is only "
                             f"valid for the recv (remote-write receiver) "
                             f"source")
        if source == "recv" and kind in RECEIVER_INVALID_KINDS:
            raise ValueError(f"chaos rule {raw!r}: kind {kind!r} is not "
                             f"valid for the recv source (the receiver "
                             f"answers requests; it has no payload or "
                             f"process to corrupt)")
        rule = ChaosRule(kind=kind, source=source)
        for tok in parts[2:]:
            tok = tok.strip().lower()
            if not tok:
                continue
            m = _DURATION_RE.match(tok)
            if m:
                v = float(m.group(1))
                rule.duration_s = v / 1000.0 if m.group(2) == "ms" else v
                continue
            m = _COUNT_RE.match(tok)
            if m:
                rule.max_count = int(m.group(1))
                continue
            m = _OFFSET_RE.match(tok)
            if m:
                rule.min_index = int(m.group(1))
                continue
            if tok.startswith("nvml="):
                if kind != "err" or source != "device":
                    raise ValueError(
                        f"chaos rule {raw!r}: nvml= codes only apply to "
                        f"err:device rules (the NVML-shaped GPU backend)"
                    )
                from tpu_pod_exporter.backend.nvml import normalize_nvml_code

                try:
                    rule.nvml_code = normalize_nvml_code(tok[5:])[0]
                except ValueError as e:
                    raise ValueError(f"chaos rule {raw!r}: {e}") from None
                continue
            try:
                p = float(tok)
            except ValueError:
                raise ValueError(
                    f"chaos rule {raw!r}: token {tok!r} is neither a "
                    f"probability, a duration (500ms/10s), a count (x3), "
                    f"nor a call offset (@20)"
                ) from None
            if not 0.0 <= p <= 1.0:
                raise ValueError(
                    f"chaos rule {raw!r}: bare number {tok!r} must be a "
                    f"probability in [0, 1]; use units for durations (e.g. "
                    f"{tok}s)"
                )
            rule.prob = p
        rules.append(rule)
    if not rules:
        raise ValueError(f"chaos spec {spec!r} contains no rules")
    return rules


# --- Garbage generators ------------------------------------------------------
# Well-formed-but-bogus values, per wrapped method: they must flow through
# the collector's normal code paths (that is the point — value robustness),
# so the types are real, only the contents are hostile.


def _garbage_sample(rng: random.Random):
    from tpu_pod_exporter.backend import (
        ChipInfo,
        ChipSample,
        HostSample,
        IciLinkSample,
    )

    return HostSample(
        chips=(
            ChipSample(
                info=ChipInfo(chip_id=999, device_path="/dev/chaos999"),
                hbm_used_bytes=-float(rng.randrange(1, 2**40)),
                hbm_total_bytes=0.0,
                tensorcore_duty_cycle_percent=float("nan"),
                # Counter regression: the monotonic fold must clamp it.
                ici_links=(IciLinkSample("0", -1.0),),
            ),
        ),
        partial_errors=("chaos: garbage sample",),
    )


def _garbage_snapshot(rng: random.Random):
    from tpu_pod_exporter.attribution import (
        AttributionSnapshot,
        DeviceAllocation,
    )

    # Label-hostile identity: escaping bugs in the renderer or a consumer
    # would corrupt the exposition framing exactly here.
    return AttributionSnapshot(
        allocations=(
            DeviceAllocation(
                pod='chaos"pod\n\\' + str(rng.randrange(10)),
                namespace="chaos\tns",
                container="c☃",
                device_ids=("0",),
            ),
        ),
    )


def _garbage_scan(rng: random.Random):  # noqa: ARG001 — signature symmetry
    return []


_GARBAGE = {
    "sample": _garbage_sample,
    "snapshot": _garbage_snapshot,
    "scan": _garbage_scan,
}


class ChaosWrapper:
    """Duck-typed chaos proxy for any poll source.

    Exposes ``sample``/``snapshot``/``scan`` (whichever the inner object
    has is the one the collector calls) plus ``close()`` passthrough so the
    supervisor's reconnect hook reaches the real source. Injections happen
    *outside* any inner lock — a hang parks only the caller (or its
    supervised worker), never the source's internal state.
    """

    def __init__(
        self,
        inner,
        source: str,
        rules: list[ChaosRule],
        seed: int = 0,
        sleep=time.sleep,
    ) -> None:
        self._inner = inner
        self.source = source
        self.rules = [r for r in rules if r.source == source]
        self._rng = random.Random(f"{seed}:{source}")
        # Garbage payload contents draw from their OWN stream: the schedule
        # rng must consume exactly one draw per rule per call (the
        # determinism invariant), and payload generation takes a varying
        # number of draws.
        self._garbage_rng = random.Random(f"{seed}:{source}:garbage")
        self._sleep = sleep
        self.calls = 0
        # (call_index, kind) per injection — the deterministic schedule,
        # asserted verbatim by tests.
        self.injected: list[tuple[int, str]] = []

    @property
    def name(self) -> str:
        return f"chaos({getattr(self._inner, 'name', '?')})"

    def _invoke(self, method: str, *args, **kwargs):
        idx = self.calls
        self.calls += 1
        # Every rule consumes exactly one rng draw per call, no matter what
        # earlier rules did: the schedule of one rule can never shift
        # because another rule fired, was capped out, or was removed —
        # determinism is per (rule position, call index), not per hit. The
        # first hitting, non-exhausted rule (spec order) is the one applied.
        triggered: ChaosRule | None = None
        for rule in self.rules:
            draw = self._rng.random()
            if (
                triggered is None
                and draw < rule.prob
                and idx >= rule.min_index
                and (rule.max_count is None or rule.fired < rule.max_count)
            ):
                triggered = rule
        if triggered is not None:
            triggered.fired += 1
            self.injected.append((idx, triggered.kind))
            log.debug("chaos: %s[%d] %s", self.source, idx, triggered.kind)
            # Annotate the active phase span (the supervisor propagates the
            # poll's trace context onto its worker threads, so this lands on
            # the right span even when the injection runs supervised): an
            # injected wedge must read as a *caused* incident in the trace.
            detail = ""
            if triggered.kind in ("hang", "slow"):
                detail = f" {triggered.effective_duration_s:g}s"
            trace_mod.annotate(
                f"chaos: injected {triggered.kind}{detail} "
                f"(call {idx}, rule {triggered.kind}:{triggered.source})"
            )
            if triggered.kind == "kill":
                # The crash persistence must survive: SIGKILL, delivered to
                # ourselves, mid-call — no drain, no Python cleanup, no
                # buffered-write flush. Anything not already fsynced is
                # gone, which is the point (make restart-demo).
                import os
                import signal

                log.critical("chaos: SIGKILL mid-%s-call (call %d)",
                             self.source, idx)
                os.kill(os.getpid(), signal.SIGKILL)
            if triggered.kind in ("hang", "slow"):
                # Sleep OUTSIDE any inner lock, then proceed with the real
                # call — a wedged-then-released source returns real data.
                self._sleep(triggered.effective_duration_s)
            elif triggered.kind == "err":
                if triggered.nvml_code:
                    from tpu_pod_exporter.backend.nvml import NvmlError

                    raise NvmlError(
                        f"chaos: injected {self.source} error (call {idx})",
                        triggered.nvml_code,
                    )
                raise ChaosError(
                    f"chaos: injected {self.source} error (call {idx})"
                )
            elif triggered.kind == "garbage":
                return _GARBAGE[method](self._garbage_rng)
        return getattr(self._inner, method)(*args, **kwargs)

    # The collector calls exactly one of these per source kind.
    def sample(self):
        return self._invoke("sample")

    def snapshot(self):
        return self._invoke("snapshot")

    def scan(self):
        return self._invoke("scan")

    def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()

    def __getattr__(self, item):
        # Introspection passthrough (e.g. FakeBackend.fail_next in tests).
        return getattr(self._inner, item)


def apply_chaos(spec: str, seed: int, backend, attribution, scanner):
    """Wrap the three poll sources per ``spec``. Sources with no matching
    rules are returned unwrapped; returns (backend, attribution, scanner,
    {source: ChaosWrapper}) with the wrapper map for /debug/vars."""
    rules = parse_chaos_spec(spec)
    wrappers: dict[str, ChaosWrapper] = {}
    by_source = {s: [r for r in rules if r.source == s] for s in SOURCES}
    if by_source["device"] and backend is not None:
        backend = wrappers["device"] = ChaosWrapper(
            backend, "device", by_source["device"], seed
        )
    if by_source["attribution"] and attribution is not None:
        attribution = wrappers["attribution"] = ChaosWrapper(
            attribution, "attribution", by_source["attribution"], seed
        )
    if by_source["procscan"] and scanner is not None:
        scanner = wrappers["procscan"] = ChaosWrapper(
            scanner, "procscan", by_source["procscan"], seed
        )
    return backend, attribution, scanner, wrappers


# --- Network partitions (fleet scenario engine) ------------------------------
#
# Partitions are injected at the HTTP *fetch seam*: every tier-to-tier call
# in the stack (leaf → node scrape, root → leaf scrape, fleet-query
# fan-out, egress send) goes through an injectable callable, so ONE wrapper
# composes with every tier. A cut raises the same ConnectionError a real
# unreachable network yields — the wrapped tier cannot tell chaos from an
# actual partition, which is the point.


class PartitionError(ConnectionError):
    """An injected network cut (the fetch never reached the peer)."""


def _sel_matches(selector: str, addr: str) -> bool:
    """``selector`` matches ``addr`` when equal, or when the selector is a
    bare tier and the addr is an instance of it (``leaf`` matches
    ``leaf:1a``; ``leaf:1a`` matches only itself)."""
    return addr == selector or addr.split(":", 1)[0] == selector


@dataclass
class Cut:
    """One directed edge cut. ``src``/``dst`` are tier selectors —
    ``"root"``, ``"leaf"``, ``"leaf:1a"``, ``"node"``, ``"node:17"``,
    ``"recv"`` — a bare tier matches every instance. ``flapping`` cuts
    only on alternating engine rounds (deterministic: seeded phase +
    round parity, no wall clock), so a flapping edge is open and cut on a
    reproducible schedule."""

    src: str
    dst: str
    flapping: bool = False
    since_round: int = 0
    phase: int = 0  # seeded flap phase: cut when (round - phase) is even


class PartitionState:
    """The fault switchboard every :class:`PartitionedFetch` /
    :class:`PartitionedSend` consults. Thread-safe for concurrent fetch
    threads (scrape pools, query fan-out, the egress sender); mutation
    happens from the scenario driver between rounds.

    ``round`` is the engine's logical clock: flapping cuts key their
    open/cut alternation off it so the schedule is deterministic under a
    fixed seed regardless of thread timing."""

    def __init__(self, seed: int = 0) -> None:
        self._lock = threading.Lock()
        self._cuts: list[Cut] = []
        self._rng = random.Random(f"{seed}:partition")
        self.round = 0
        # (round, "cut|heal", src, dst) — the injected history, for traces.
        self.log: list[tuple[int, str, str, str]] = []

    def advance(self, round_idx: int) -> None:
        with self._lock:
            self.round = round_idx

    def cut(self, src: str, dst: str, flapping: bool = False) -> None:
        """Cut the directed edge src→dst (selectors, see :class:`Cut`).
        Symmetric partitions are two cuts; asymmetric ones are one."""
        with self._lock:
            phase = self._rng.randrange(2) if flapping else 0
            self._cuts.append(Cut(src=src, dst=dst, flapping=flapping,
                                  since_round=self.round, phase=phase))
            self.log.append((self.round, "cut", src, dst))

    def heal(self, src: str, dst: str) -> None:
        """Remove every cut matching exactly (src, dst) as given."""
        with self._lock:
            self._cuts = [
                c for c in self._cuts if not (c.src == src and c.dst == dst)
            ]
            self.log.append((self.round, "heal", src, dst))

    def heal_all(self) -> None:
        with self._lock:
            for c in self._cuts:
                self.log.append((self.round, "heal", c.src, c.dst))
            self._cuts = []

    def is_cut(self, src: str, dst: str) -> bool:
        """Is the concrete edge src→dst cut right now (both are instance
        addresses; cuts may be tier-wide selectors)?"""
        with self._lock:
            rnd = self.round
            for c in self._cuts:
                if not (_sel_matches(c.src, src) and _sel_matches(c.dst, dst)):
                    continue
                if c.flapping and (rnd - c.phase) % 2 != 0:
                    continue  # the flap's open half-round
                return True
            return False

    def active(self) -> list[tuple[str, str, bool]]:
        """Currently-effective cuts as (src, dst, flapping) — flapping cuts
        are listed only on their cut half-rounds."""
        with self._lock:
            rnd = self.round
            return [
                (c.src, c.dst, c.flapping)
                for c in self._cuts
                if not (c.flapping and (rnd - c.phase) % 2 != 0)
            ]

    def any_cuts(self) -> bool:
        """Any cut INSTALLED (flapping ones count even on their open
        half-round — the window is still an injected-fault window)."""
        with self._lock:
            return bool(self._cuts)


class PartitionedFetch:
    """Wrap any ``fetch(target, timeout_s[, traceparent])`` seam with a
    partition check: when the (src, dst(target)) edge is cut the call
    raises :class:`PartitionError` without touching the wire — exactly a
    black-holed SYN from the caller's point of view, minus the timeout
    burn (the drills inject hundreds of cut calls per round).

    Deliberately a 2-arg callable: the aggregator tiers auto-detect
    traceparent support by signature, and the wrapper must not promise a
    kwarg it cannot forward to arbitrary inner fetches.
    """

    def __init__(self, net: PartitionState, src: str,
                 dst_of, inner) -> None:
        self._net = net
        self.src = src
        self._dst_of = dst_of  # target/url -> instance addr ("node:17", "leaf:1a")
        self._inner = inner
        self.blocked = 0

    def __call__(self, target: str, timeout_s: float) -> str:
        dst = self._dst_of(target)
        if self._net.is_cut(self.src, dst):
            self.blocked += 1
            raise PartitionError(
                f"partition: {self.src} -> {dst} is cut ({target})"
            )
        return self._inner(target, timeout_s)


class PartitionedSend:
    """The egress half of the seam: wraps an egress ``send(url, body,
    headers, timeout_s)`` callable (``egress.RemoteWriteShipper``'s
    injectable sender) with the same switchboard check."""

    def __init__(self, net: PartitionState, src: str, dst: str,
                 inner) -> None:
        self._net = net
        self.src = src
        self.dst = dst
        self._inner = inner
        self.blocked = 0

    def __call__(self, url: str, body: bytes, headers, timeout_s: float) -> int:
        if self._net.is_cut(self.src, self.dst):
            self.blocked += 1
            raise PartitionError(
                f"partition: {self.src} -> {self.dst} is cut ({url})"
            )
        return self._inner(url, body, headers, timeout_s)


# --- Host-level chaos (resource-pressure drills) -----------------------------
#
# The pressure drills (tpu_pod_exporter.pressure, scenario kinds
# ``disk_full`` / ``mem_pressure`` / ``scrape_storm`` / ``clock_step``)
# need faults no wrapped poll source can model: the MACHINE misbehaving.
# Like LeafKillHook, these are timeline-driven harness classes rather than
# ``--chaos-spec`` rules — the scenario engine and ``make pressure-demo``
# fire them at fixed round coordinates, deterministically.


class ClockStepper:
    """An injectable wall clock with a mutable offset — the ``clock_step``
    fault. Components take it as their ``wallclock=`` callable; the drill
    calls :meth:`step` mid-run and asserts the wall-time seams (egress
    batch gating, backlog ages, staleness gauges) stay fenced: ages never
    go negative, and a backward step never silently stops a pipeline."""

    def __init__(self, base: "float | None" = None,
                 real=time.time) -> None:
        self._real = real
        self._base = base
        self.offset_s = 0.0
        self.steps: list[float] = []

    def step(self, seconds: float) -> None:
        """Apply one NTP-shaped step (positive = forward)."""
        self.offset_s += seconds
        self.steps.append(seconds)
        log.warning("chaos: wall clock stepped %+gs (offset now %+gs)",
                    seconds, self.offset_s)

    def __call__(self) -> float:
        now = self._real() if self._base is None else self._base
        return now + self.offset_s


class MemoryHog:
    """Holds real referenced memory (the ``mem_pressure`` fault's RSS
    half): allocates touch-backed bytearrays so the drill's RSS assertions
    measure genuine pages, not lazily-mapped zeros."""

    def __init__(self) -> None:
        self._blocks: list[bytearray] = []

    def hold(self, n_bytes: int, block: int = 1 << 20) -> None:
        remaining = n_bytes
        while remaining > 0:
            size = min(block, remaining)
            buf = bytearray(size)
            # Touch one byte per page so the kernel actually commits it.
            for i in range(0, size, 4096):
                buf[i] = 1
            self._blocks.append(buf)
            remaining -= size

    def held_bytes(self) -> int:
        return sum(len(b) for b in self._blocks)

    def release(self) -> None:
        self._blocks.clear()


class ScrapeStorm:
    """A misconfigured scrape fleet: N concurrent connections hammering
    one URL in tight keep-alive loops — the admission-control drill's
    storm half. Each worker binds its own loopback SOURCE address
    (127.0.0.N pool) so the per-client-IP cap sees distinct clients from
    the polite scraper sharing the same box."""

    def __init__(self, host: str, port: int, path: str = "/metrics",
                 conns: int = 100, source_ips: int = 8,
                 pause_s: float = 0.0,
                 reject_pause_s: float = 0.25) -> None:
        self.host = host
        self.port = port
        self.path = path
        self.conns = conns
        self.source_ips = max(source_ips, 1)
        # Per-request pause: 0 is a maximally-hostile tight loop; in-process
        # drills pace slightly so the STORM THREADS' own GIL churn does not
        # drown the polite-scraper measurement they run alongside.
        self.pause_s = pause_s
        # Back-off after a reject/reset before reconnecting: a fraction of
        # the Retry-After: 1 the 429 carries (a storm of merely
        # MISCONFIGURED scrapers retries eventually; one that ignores 429s
        # entirely is modeled with 0 — at the cost of the client threads'
        # own reconnect churn dominating an in-process measurement).
        self.reject_pause_s = reject_pause_s
        self.responses: dict[int, int] = {}   # status -> count
        self.errors = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def _worker(self, idx: int) -> None:
        import http.client

        source = f"127.0.0.{2 + idx % self.source_ips}"
        conn: http.client.HTTPConnection | None = None
        while not self._stop.is_set():
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(
                        self.host, self.port, timeout=5,
                        source_address=(source, 0),
                    )
                conn.request("GET", self.path)
                resp = conn.getresponse()
                resp.read()
                status = resp.status
                if resp.headers.get("Connection") == "close":
                    conn.close()
                    conn = None
                with self._lock:
                    self.responses[status] = (
                        self.responses.get(status, 0) + 1
                    )
                if status == 429 and self.reject_pause_s > 0:
                    self._stop.wait(self.reject_pause_s)
                elif self.pause_s > 0:
                    self._stop.wait(self.pause_s)
            except OSError:
                with self._lock:
                    self.errors += 1
                if conn is not None:
                    conn.close()
                    conn = None
                if self.reject_pause_s > 0:
                    self._stop.wait(self.reject_pause_s)
        if conn is not None:
            conn.close()

    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()
        self._threads = [
            threading.Thread(
                target=self._worker, args=(i,),
                name=f"tpu-chaos-storm-{i}", daemon=True,
            )
            for i in range(self.conns)
        ]
        for t in self._threads:
            t.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads = []

    def stats(self) -> dict:
        with self._lock:
            return {
                "responses": dict(self.responses),
                "errors": self.errors,
                "served": self.responses.get(200, 0),
                "rejected": self.responses.get(429, 0),
            }


# --- Leaf chaos (sharded aggregation tree) -----------------------------------


@dataclass
class LeafEvent:
    """One scripted action against a leaf aggregator in the shard-demo
    timeline: ``kill`` (SIGKILL-shaped: the leaf's HTTP server stops
    serving and its in-flight round never becomes visible) or ``restart``
    (a fresh leaf process on the same state dir — breaker + shard-map
    carryover is exactly what the restart asserts)."""

    action: str              # "kill" | "restart"
    leaf: str                # leaf id as the harness registered it
    round_idx: int           # driver round the event arms at
    at_call: int | None = None  # kill MID-round, after this many scrapes
    fired: bool = field(default=False, compare=False)


LEAF_ACTIONS = ("kill", "restart")

_LEAF_EVENT_RE = re.compile(
    r"^(?P<action>[a-z]+):(?P<leaf>[^@]+)@(?P<round>\d+)(?:#(?P<call>\d+))?$"
)


def parse_leaf_timeline(spec: str) -> list[LeafEvent]:
    """``--leaf-timeline`` grammar, one event per comma::

        event := action ":" leaf "@" round ["#" call]
        action := kill | restart

    ``kill:1a@3#12`` kills leaf ``1a`` in driver round 3 after its 12th
    target scrape of that round (mid-round — the crash shape the HA dedup
    must absorb); ``restart:1a@6`` brings it back in round 6. Malformed
    events raise ValueError loudly, same contract as parse_chaos_spec."""
    events: list[LeafEvent] = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        m = _LEAF_EVENT_RE.match(raw)
        if m is None:
            raise ValueError(
                f"leaf timeline event {raw!r}: want action:leaf@round[#call]"
            )
        action = m.group("action")
        if action not in LEAF_ACTIONS:
            raise ValueError(
                f"leaf timeline event {raw!r}: unknown action {action!r} "
                f"(want one of {'/'.join(LEAF_ACTIONS)})"
            )
        call = m.group("call")
        if action == "restart" and call is not None:
            raise ValueError(
                f"leaf timeline event {raw!r}: #call only applies to kill"
            )
        events.append(LeafEvent(
            action=action,
            leaf=m.group("leaf"),
            round_idx=int(m.group("round")),
            at_call=int(call) if call is not None else None,
        ))
    if not events:
        raise ValueError(f"leaf timeline {spec!r} contains no events")
    return events


class LeafKillHook:
    """Executes a :func:`parse_leaf_timeline` schedule against a running
    leaf tier — the shard-demo's kill switch (``loadgen/fleet.py``).

    The harness provides ``kill_fn(leaf)`` / ``restart_fn(leaf)``;
    whole-round events fire from :meth:`begin_round` (driver thread),
    mid-round kills fire from :meth:`on_scrape`, which the victim leaf's
    fetch wrapper calls per target scrape — concurrently from the leaf's
    scrape pool, hence the lock. Deterministic by construction: events
    fire at fixed (round, call) coordinates, no randomness."""

    def __init__(self, events: "list[LeafEvent]", kill_fn, restart_fn) -> None:
        self.events = list(events)
        self._kill_fn = kill_fn
        self._restart_fn = restart_fn
        self._lock = threading.Lock()
        # (round_idx, action, leaf) per fired event — the executed
        # timeline, asserted by the harness.
        self.executed: list[tuple[int, str, str]] = []

    def begin_round(self, round_idx: int) -> None:
        """Fire restarts and whole-round kills armed at this round (called
        once per driver round, before the leaves poll)."""
        for ev in self.events:
            if ev.fired or ev.round_idx != round_idx:
                continue
            if ev.action == "restart":
                ev.fired = True
                self.executed.append((round_idx, "restart", ev.leaf))
                self._restart_fn(ev.leaf)
            elif ev.action == "kill" and ev.at_call is None:
                ev.fired = True
                self.executed.append((round_idx, "kill", ev.leaf))
                self._kill_fn(ev.leaf)

    def on_scrape(self, leaf: str, round_idx: int, call_idx: int) -> bool:
        """Mid-round kill check, called per target scrape from the leaf's
        fetch path; True exactly once, when the leaf just died."""
        with self._lock:
            fire = None
            for ev in self.events:
                if (
                    not ev.fired
                    and ev.action == "kill"
                    and ev.at_call is not None
                    and ev.leaf == leaf
                    and ev.round_idx == round_idx
                    and call_idx >= ev.at_call
                ):
                    fire = ev
                    break
            if fire is None:
                return False
            fire.fired = True
            self.executed.append((round_idx, "kill", leaf))
        self._kill_fn(leaf)
        return True


# --- Chaos remote-write receiver ---------------------------------------------


class ChaosReceiver:
    """In-process Prometheus remote-write receiver with a seeded fault
    schedule — the wire-side twin of :class:`ChaosWrapper`, proving the
    egress breaker + WAL story end to end (``make egress-demo``).

    Applies ``recv``-source rules per request index with the same
    one-rng-draw-per-rule-per-request determinism as the wrapper: ``hang``
    parks the request for its duration then answers 503 (the client has
    long since timed out — answering 200 after the client gave up would
    poison the exactly-once ledger), ``err`` → 500, ``reject`` → 429,
    ``slow`` sleeps then accepts, ``truncate`` reads part of the body and
    drops the connection mid-transfer.

    Accepted batches are decoded (vendored snappy + protobuf decoders from
    ``tpu_pod_exporter.egress``) into a ledger: batch seqs (from the
    shipper's ``X-Tpe-Egress-Seq`` header), per-(series, timestamp) sample
    identity, and duplicate counts — the demo's zero-loss / no-acked-
    re-send assertions read straight off it. A batch is recorded only
    AFTER its 200 response was written successfully: if the client vanished
    mid-response the write raises and the batch stays unaccounted, exactly
    as the sender (which saw a failure and will re-send) believes.

    ``poison_seqs`` (test knob): respond 400 to those batch seqs — the
    shipper must count-and-skip them without wedging the queue.
    """

    def __init__(self, rules: list[ChaosRule], seed: int = 0,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        import http.server

        self.rules = [r for r in rules if r.source == "recv"]
        self._rng = random.Random(f"{seed}:recv")
        self.calls = 0
        self.injected: list[tuple[int, str]] = []
        self.poison_seqs: set[int] = set()
        self._lock = threading.Lock()
        self._accepted_seqs: list[int] = []
        self._accepted_set: set[int] = set()
        self._samples: set[tuple] = set()
        self._accepted_samples = 0
        self._duplicate_seqs: list[int] = []
        self._duplicate_samples = 0
        self._requests = 0
        # Scenario-driven outage switch (set_outage): while True every
        # request answers 503 WITHOUT consuming the seeded rule schedule —
        # the outage is driven by the scenario timeline's rounds, and the
        # probabilistic rules must keep their own deterministic call
        # indices for when it lifts.
        self._outage = False
        self._outage_responses = 0
        # hold_next() choreography: park one request mid-handling and tell
        # the caller it is in flight (the demo SIGKILLs the sender there).
        self._hold_pending: threading.Event | None = None
        self._hold_release = threading.Event()
        self._hold_s = 0.0

        receiver = self

        class _RecvHandler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_POST(self) -> None:  # noqa: N802 — stdlib API
                receiver._handle(self)

            def log_message(self, fmt: str, *args) -> None:
                log.debug("chaos-recv: " + fmt, *args)

        class _RecvServer(http.server.ThreadingHTTPServer):
            daemon_threads = True

            def handle_error(self, request, client_address) -> None:
                # A SIGKILLed sender leaves a broken pipe mid-response —
                # expected chaos, not a server fault worth a stack trace.
                log.debug("chaos-recv: handler error from %s",
                          client_address)

        self._httpd = _RecvServer((host, port), _RecvHandler)
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}/api/v1/write"

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="tpu-chaos-recv", daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._hold_release.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------- schedule

    def _draw(self, idx: int) -> ChaosRule | None:
        """Same determinism contract as ChaosWrapper._invoke: every rule
        consumes exactly one draw per request regardless of what earlier
        rules did; first hitting, armed, non-exhausted rule wins."""
        triggered: ChaosRule | None = None
        for rule in self.rules:
            draw = self._rng.random()
            if (
                triggered is None
                and draw < rule.prob
                and idx >= rule.min_index
                and (rule.max_count is None or rule.fired < rule.max_count)
            ):
                triggered = rule
        if triggered is not None:
            triggered.fired += 1
            self.injected.append((idx, triggered.kind))
        return triggered

    # ------------------------------------------------------------- handling

    def hold_next(self, hold_s: float = 10.0) -> threading.Event:
        """Arm a one-shot hold: the NEXT request parks un-answered for up
        to ``hold_s`` (or until release_hold()). Returns an Event set the
        moment that request is in flight — the demo's SIGKILL-mid-send
        trigger."""
        ev = threading.Event()
        with self._lock:
            self._hold_pending = ev
            self._hold_s = hold_s
            self._hold_release.clear()
        return ev

    def release_hold(self) -> None:
        self._hold_release.set()

    def set_outage(self, down: bool) -> None:
        """Receiver-side outage (the ``recv_outage`` scenario event): every
        request answers 503 while set — the receiver process is "down",
        which is different from a network cut (the client sees an HTTP
        error, not a connection failure)."""
        with self._lock:
            self._outage = down

    def _handle(self, h) -> None:
        with self._lock:
            if self._outage:
                self._outage_responses += 1
                outage = True
            else:
                outage = False
        if outage:
            # Drain the body first: dropping a connection with an unread
            # body reads as a RESET client-side, and an outage must look
            # like a live-but-refusing receiver, not a cut wire.
            length = int(h.headers.get("Content-Length") or 0)
            if length:
                h.rfile.read(length)
            self._respond(h, 503, b"receiver outage\n")
            return
        with self._lock:
            idx = self.calls
            self.calls += 1
            rule = self._draw(idx)
            hold = self._hold_pending
            if hold is not None:
                self._hold_pending = None
        if hold is not None:
            hold.set()
            self._hold_release.wait(self._hold_s)
            self._respond(h, 503, b"held\n")
            return
        length = int(h.headers.get("Content-Length") or 0)
        if rule is not None and rule.kind == "truncate":
            # Read part of the body, then drop the connection mid-transfer
            # — the client sees a reset, nothing was received.
            h.rfile.read(min(length, max(length // 2, 1)))
            try:
                h.connection.close()
            except OSError:
                pass
            return
        body = h.rfile.read(length) if length else b""
        if rule is not None:
            if rule.kind in ("hang", "slow"):
                time.sleep(rule.effective_duration_s)
                if rule.kind == "hang":
                    self._respond(h, 503, b"wedged\n")
                    return
            elif rule.kind == "err":
                self._respond(h, 500, b"injected error\n")
                return
            elif rule.kind == "reject":
                self._respond(h, 429, b"backpressure\n")
                return
        self._accept(h, body)

    def _accept(self, h, body: bytes) -> None:
        from tpu_pod_exporter.egress import (
            SEQ_HEADER,
            parse_write_request,
            snappy_decompress,
        )

        try:
            series = parse_write_request(snappy_decompress(body))
        except ValueError as e:
            self._respond(h, 400, f"bad batch: {e}\n".encode())
            return
        try:
            seq = int(h.headers.get(SEQ_HEADER) or 0)
        except ValueError:
            seq = 0
        if seq in self.poison_seqs:
            self._respond(h, 400, b"poisoned\n")
            return
        # Respond FIRST; ledger only what the client could have seen acked.
        try:
            self._respond(h, 200, b"ok\n")
        except OSError:
            return  # client gone mid-response: it will re-send; no record
        with self._lock:
            self._requests += 1
            if seq:
                if seq in self._accepted_set:
                    self._duplicate_seqs.append(seq)
                else:
                    self._accepted_set.add(seq)
                    self._accepted_seqs.append(seq)
            for labels, samples in series:
                ident = tuple(sorted(labels.items()))
                for _value, ts_ms in samples:
                    key = (ident, ts_ms)
                    if key in self._samples:
                        self._duplicate_samples += 1
                    else:
                        self._samples.add(key)
                        self._accepted_samples += 1

    @staticmethod
    def _respond(h, code: int, body: bytes) -> None:
        h.send_response(code)
        h.send_header("Content-Type", "text/plain")
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)
        h.wfile.flush()

    # ----------------------------------------------------------------- stats

    def accepted_batches(self) -> int:
        with self._lock:
            return len(self._accepted_seqs)

    def stats(self) -> dict:
        with self._lock:
            return {
                "requests": self._requests,
                "calls": self.calls,
                "outage_responses": self._outage_responses,
                "injected": list(self.injected),
                "accepted_seqs": list(self._accepted_seqs),
                "accepted_samples": self._accepted_samples,
                "duplicate_seqs": list(self._duplicate_seqs),
                "duplicate_samples": self._duplicate_samples,
            }


# --- Demo: a wedge, observed end to end --------------------------------------


def main(argv: list[str] | None = None) -> int:
    """``make chaos-demo``: wedge the device backend of a live in-process
    exporter, watch the supervisor abandon the call, the breaker open,
    the backend reconnect, and ``tpu_exporter_up`` return to 1 — while
    /metrics keeps answering from the stale snapshot throughout."""
    import argparse
    import json
    import urllib.request

    from tpu_pod_exporter import utils as _utils
    from tpu_pod_exporter.app import ExporterApp
    from tpu_pod_exporter.config import ExporterConfig

    p = argparse.ArgumentParser(
        prog="tpu-pod-exporter-chaos",
        description="Chaos demo: survive a wedged device backend, visibly.",
    )
    p.add_argument("--hang-s", type=float, default=6.0,
                   help="how long each injected device hang blocks")
    p.add_argument("--hangs", type=int, default=3,
                   help="number of consecutive device reads that hang")
    p.add_argument("--deadline-s", type=float, default=0.5)
    p.add_argument("--interval-s", type=float, default=0.25)
    p.add_argument("--timeout-s", type=float, default=60.0,
                   help="give up if the exporter has not recovered by then")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--trace-out", default="",
                   help="write the incident's poll traces as Chrome "
                        "trace_event JSON to this path on exit (CI uploads "
                        "it as an artifact when the demo fails)")
    ns = p.parse_args(argv)

    _utils.setup_logging("warning")
    cfg = ExporterConfig(
        port=0, host="127.0.0.1", interval_s=ns.interval_s,
        backend="fake", fake_chips=4, attribution="none",
        phase_deadline_s=ns.deadline_s,
        breaker_failures=2, breaker_backoff_s=0.5, breaker_backoff_max_s=2.0,
        chaos_spec=f"hang:device:1:{ns.hang_s:g}s:x{ns.hangs}",
        chaos_seed=ns.seed,
        history_retention_s=0.0,
        # Slow-poll threshold under the deadline, so every wedged poll gets
        # its stacks sampled — the incident trace then names the hung frame
        # (chaos._invoke here), not just the abandoned span.
        trace_slow_poll_s=ns.deadline_s / 2.0,
    )
    app = ExporterApp(cfg)
    app.start()
    base = f"http://127.0.0.1:{app.port}"
    print(f"exporter up on {base}  "
          f"(spec: {cfg.chaos_spec}, deadline {ns.deadline_s:g}s)")
    saw_open = saw_reconnect = False
    t0 = time.monotonic()
    rc = 1
    try:
        while time.monotonic() - t0 < ns.timeout_s:
            ts0 = time.monotonic()
            with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
                body = r.read().decode()
            scrape_ms = (time.monotonic() - ts0) * 1000.0

            def val(name: str, default: float = 0.0) -> float:
                for line in body.splitlines():
                    if line.startswith(name) and " " in line:
                        try:
                            return float(line.rsplit(" ", 1)[1])
                        except ValueError:
                            pass
                return default

            up = val("tpu_exporter_up ")
            sup = app.supervisors["device"].stats()
            print(f"t={time.monotonic() - t0:5.1f}s  up={up:g}  "
                  f"breaker={sup['state']:<9}  abandoned={sup['abandoned']}  "
                  f"reconnects={sup['reconnects']}  "
                  f"skipped={sup['skipped']}  scrape={scrape_ms:.1f}ms")
            saw_open = saw_open or sup["state"] != "closed"
            saw_reconnect = saw_reconnect or sup["reconnects"] > 0
            if saw_open and saw_reconnect and up == 1.0 and sup["state"] == "closed":
                print("recovered: breaker closed, backend reconnected, up=1")
                with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
                    print("readyz:", json.dumps(json.loads(r.read())))
                rc = 0
                break
            time.sleep(max(ns.interval_s, 0.25))
        else:
            print("TIMEOUT: exporter did not recover", flush=True)
    finally:
        if ns.trace_out and app.trace is not None:
            # The abandoned device spans + profiler stacks of the wedge,
            # viewable in chrome://tracing / Perfetto. Written win or lose —
            # CI only uploads it when the demo failed.
            from tpu_pod_exporter.trace import to_chrome_trace

            doc = to_chrome_trace(app.trace.last(app.trace.max_traces),
                                  app.trace.scrapes(256))
            with open(ns.trace_out, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            print(f"incident trace written to {ns.trace_out} "
                  f"({len(doc['traceEvents'])} events)")
        app.stop()
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(main())
