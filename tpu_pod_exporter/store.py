"""Fleet TSDB-lite — durable, queryable fleet history at the root.

Every history layer so far dies with its process or its node: the node
rings (PR 1/6) vanish when a host is drained, the leaf tier holds no
history at all, and the root's federated ``/api/v1`` (PR 6/8) can only
fan out to whatever is *currently* alive — which means fleet-wide history
**ends** exactly when incident forensics need it most: when a node dies,
a leaf reshards, or the root itself restarts. The common incident path
("what did the fleet look like over the last N hours/days?") therefore
still needs an external Prometheus.

:class:`FleetStore` closes that gap by turning the aggregation tree into a
self-contained small TSDB. After each root merge round it appends the
merged rollups plus the per-target series (``STORE_TRACKED_METRICS`` —
the same "what is the fleet doing" set the remote-write egress ships)
into **multi-resolution downsample tiers**: the wall-bucketed
:class:`~tpu_pod_exporter.history.TierRing` machinery generalized to be
disk-backed. Each tier persists its finalized buckets through its own
:class:`~tpu_pod_exporter.persist.WalBuffer` segment directory (CRC
framing, torn-write-tolerant clean-prefix replay, cursor-advance trim —
the exact machinery the egress send buffer proved), so retention is
measured in **days** (``--store-tiers``, default 4 h at 1 min plus 7 days
at 10 min) and survives root restarts, leaf death, and resharding:

- a restart replays every tier's pending records back into its rings,
  re-opens the newest bucket as the live accumulator (post-restart samples
  of the same wall bucket MERGE exactly — every accumulator field rides
  the record) and resumes counter-delta tracking from the restored last
  value, so rates stay continuous across the boundary;
- replay is idempotent: a re-finalized bucket's record REPLACES its
  pre-crash twin (``TierRing.push``), never duplicates it.

**Recording rules** (``--store-rules``): a small declarative file of
per-slice/per-workload aggregates — ``name = agg(metric{match}) by
(labels)`` — evaluated each round against the root's published snapshot
and appended as their own stored series, so dashboard queries hit
precomputed rollups instead of fan-outs.

**Serving**: :class:`StoreQueryPlane` wraps the root's live two-level
query plane and serves the same ``/api/v1/query_range|window_stats|
series`` shapes with a ``source: live|store|merged`` field — the store
fills where the live fan-out has no coverage (dead nodes, pre-restart
windows, rule series), and ``?source=store`` answers from the store
alone. Every row carries its own ``source`` so attribution is honest
per series, not per envelope.

**Pressure integration** (``tpu_pod_exporter.pressure``): the disk ladder
gains a ``store_thin`` rung — the store drops its FINEST tier first
(coarse tiers last: they are the cheapest bytes per second of answerable
history), counted as ``reason="shed"`` — and the store's in-memory tier
bytes register with the memory ladder's component accounting.

``python -m tpu_pod_exporter.store --demo`` (``make store-demo``) drills
both acceptance gates: a 7-day synthetic-retention run at 1000 targets on
a compressed timescale inside a governor-enforced disk budget (the ladder
must exercise ``store_thin`` and the 7-day span must survive it), and a
query-latency comparison proving a stored-rollup query beats the cold
two-level fan-out at 200 real-HTTP targets.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable, Iterable, Mapping, Sequence

from tpu_pod_exporter.fleet import (
    data_shape as _data_shape,
    rows_of as _rows_of,
)
from tpu_pod_exporter.history import (
    TierRing,
    align_grid,
    fold_tier_window,
    is_counter_metric,
    parse_tier_spec,
    tier_items,
)
from tpu_pod_exporter.metrics import schema
from tpu_pod_exporter.persist import WalBuffer, atomic_write
from tpu_pod_exporter.utils import RateLimitedLogger

if TYPE_CHECKING:  # typing only — no runtime import cost
    from tpu_pod_exporter.metrics.registry import Snapshot, SnapshotBuilder

log = logging.getLogger("tpu_pod_exporter.store")

# What the root folds into the store each round: the merged rollups + the
# per-target series — the same "what is the fleet doing" set the egress
# ships to an external TSDB, plus per-leaf liveness (the first question of
# any incident timeline is "which leaves were up at T?").
STORE_TRACKED_METRICS: frozenset[str] = frozenset(
    spec.name for spec in schema.AGGREGATE_EGRESS_SPECS
) | {schema.TPU_ROOT_LEAF_UP.name}

# Default tiers: 4 h at 1-minute buckets for the incident close-up, 7 days
# at 10-minute buckets for the forensics horizon (600 × 1008 = exactly
# 7 d). Memory per series ≈ (240 + 1008) × 88 B ≈ 107 KiB, hard-bounded by
# max_series; disk per tier ≈ one WAL record per bucket boundary.
DEFAULT_STORE_TIERS = "60:240,600:1008"

SIDECAR_NAME = "store-status.json"

# Per retained bucket: 11 float64 cells (see history._TIER_BUCKET_BYTES).
_BUCKET_BYTES = 11 * 8
# Rough per-series bookkeeping (labels dict, key tuple, slots).
_SERIES_OVERHEAD_BYTES = 512

_SPEC_BY_NAME = {
    spec.name: spec
    for group in (schema.ALL_SPECS, schema.AGGREGATE_SPECS,
                  schema.LEAF_SPECS, schema.ROOT_SPECS)
    for spec in group
}

# Extra records a tier buffer may hold past its ring capacity before the
# retention trim advances the cursor (slack absorbs re-finalization
# records without trimming every round).
_RETENTION_SLACK_RECORDS = 16


# ------------------------------------------------------------ recording rules


RULE_AGGS: tuple[str, ...] = ("sum", "avg", "min", "max", "count")

_RULE_RE = re.compile(
    r"^(?P<name>[A-Za-z_][A-Za-z0-9_:]*)\s*=\s*"
    r"(?P<agg>[a-z]+)\s*\(\s*(?P<metric>[A-Za-z_][A-Za-z0-9_]*)\s*"
    r"(?P<match>\{[^}]*\})?\s*\)\s*"
    r"(?:by\s*\(\s*(?P<by>[^)]*)\)\s*)?$"
)
_MATCHER_RE = re.compile(
    r"""^\s*(?P<label>[A-Za-z_][A-Za-z0-9_]*)\s*=\s*"(?P<value>[^"]*)"\s*$"""
)


@dataclass(frozen=True)
class RecordingRule:
    """One parsed rule: ``name = agg(metric{label="v"}) by (l1, l2)``.
    Evaluated per root round over the published snapshot; the output lands
    in the store as metric ``name`` labeled by the ``by`` labels."""

    name: str
    agg: str
    metric: str
    by: tuple[str, ...]
    match: tuple[tuple[str, str], ...]
    line_no: int


def _rule_err(line_no: int, line: str, msg: str) -> ValueError:
    return ValueError(f"store rule line {line_no} ({line!r}): {msg}")


def parse_rules(text: str) -> tuple[RecordingRule, ...]:
    """Parse a rule file body; raises ValueError naming the offending line
    and what would be accepted — a typo'd rule file must fail at startup,
    never silently store nothing (the parse_chaos_spec contract)."""
    rules: list[RecordingRule] = []
    seen: dict[str, int] = {}
    for line_no, raw in enumerate(text.splitlines(), start=1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        m = _RULE_RE.match(line)
        if m is None:
            raise _rule_err(
                line_no, raw.strip(),
                'want name = agg(metric[{label="value", ...}]) '
                "[by (label, ...)] with agg one of " + "/".join(RULE_AGGS),
            )
        name = m.group("name")
        agg = m.group("agg")
        metric = m.group("metric")
        if agg not in RULE_AGGS:
            raise _rule_err(line_no, raw.strip(),
                            f"unknown aggregation {agg!r} "
                            f"(want one of {'/'.join(RULE_AGGS)})")
        if name in _SPEC_BY_NAME:
            raise _rule_err(line_no, raw.strip(),
                            f"rule name {name!r} shadows a schema-registered "
                            f"metric; pick a distinct name "
                            f"(convention: level:metric:operation)")
        if name in seen:
            raise _rule_err(line_no, raw.strip(),
                            f"duplicate rule name {name!r} "
                            f"(first defined on line {seen[name]})")
        spec = _SPEC_BY_NAME.get(metric)
        if spec is None:
            raise _rule_err(line_no, raw.strip(),
                            f"unknown metric {metric!r}: rules evaluate over "
                            f"the root's published families "
                            f"(schema-registered names)")
        by: list[str] = []
        if m.group("by") is not None:
            for part in m.group("by").split(","):
                lbl = part.strip()
                if not lbl:
                    continue
                if lbl not in spec.label_names:
                    raise _rule_err(
                        line_no, raw.strip(),
                        f"by-label {lbl!r} is not a label of {metric} "
                        f"(has: {', '.join(spec.label_names) or 'none'})")
                by.append(lbl)
        matchers: list[tuple[str, str]] = []
        if m.group("match"):
            inner = m.group("match")[1:-1].strip()
            if inner:
                for part in inner.split(","):
                    mm = _MATCHER_RE.match(part)
                    if mm is None:
                        raise _rule_err(
                            line_no, raw.strip(),
                            f'bad matcher {part.strip()!r}: want '
                            f'label="value"')
                    lbl = mm.group("label")
                    if lbl not in spec.label_names:
                        raise _rule_err(
                            line_no, raw.strip(),
                            f"matcher label {lbl!r} is not a label of "
                            f"{metric} "
                            f"(has: {', '.join(spec.label_names) or 'none'})")
                    matchers.append((lbl, mm.group("value")))
        seen[name] = line_no
        rules.append(RecordingRule(
            name=name, agg=agg, metric=metric,
            by=tuple(by), match=tuple(matchers), line_no=line_no,
        ))
    return tuple(rules)


def load_rules_file(path: str) -> tuple[RecordingRule, ...]:
    """Read + parse a rule file; OSError/ValueError propagate (a missing
    or malformed rule file is a startup error, not a silent no-op)."""
    with open(path, encoding="utf-8") as f:
        return parse_rules(f.read())


def evaluate_rule(
    rule: RecordingRule, snapshot: "Snapshot"
) -> list[tuple[dict[str, str], float]]:
    """One rule against one published snapshot → ``[(labels, value), …]``
    grouped by the rule's ``by`` labels (one unlabeled output when ``by``
    is empty). Absent families produce no output (not an error: a fleet
    with no DCN simply has no DCN rollups)."""
    spec = _SPEC_BY_NAME[rule.metric]
    view = snapshot.samples_view(rule.metric)
    if not view:
        return []
    label_names = spec.label_names
    idx_of = {ln: i for i, ln in enumerate(label_names)}
    match_idx = [(idx_of[lbl], val) for lbl, val in rule.match]
    by_idx = [idx_of[lbl] for lbl in rule.by]
    groups: dict[tuple[str, ...], list[float]] = {}
    for lvs, value in view.items():
        if any(lvs[i] != val for i, val in match_idx):
            continue
        groups.setdefault(tuple(lvs[i] for i in by_idx), []).append(value)
    out: list[tuple[dict[str, str], float]] = []
    for gkey, values in groups.items():
        if rule.agg == "sum":
            v = sum(values)
        elif rule.agg == "avg":
            v = sum(values) / len(values)
        elif rule.agg == "min":
            v = min(values)
        elif rule.agg == "max":
            v = max(values)
        else:  # count
            v = float(len(values))
        out.append((dict(zip(rule.by, gkey)), v))
    return out


# ----------------------------------------------------------------- the store


class _StoreSeries:
    """One stored series: identity plus its per-tier downsample rings.
    No raw ring — the store's inputs are already once-per-round merged
    samples; the finest tier IS the raw resolution it keeps."""

    __slots__ = ("name", "labels", "tiers", "pv", "last_wall")

    def __init__(self, name: str, labels: dict[str, str],
                 tier_spec: Sequence[tuple[float, int]]) -> None:
        self.name = name
        self.labels = labels
        self.tiers = tuple(TierRing(step, cap) for step, cap in tier_spec)
        self.pv = float("nan")
        self.last_wall = 0.0


class FleetStore:
    """Durable multi-tier downsample store for the root's merged series.

    Thread contract: ``append_snapshot``/``append_samples`` are called by
    ONE thread (the root's round loop); queries come from HTTP handler
    threads and copy ring contents out under the store lock (the
    HistoryStore discipline — per-bucket Python tuples are built outside
    it). ``set_thin`` may be called from the pressure governor's thread:
    ring state flips under the store lock, and the tier buffer's cursor
    trim uses the WalBuffer consumer side, which is concurrency-safe
    against the appender by the same contract the egress sender relies on.

    Timestamps: tier rings are wall-bucketed and the store feeds the wall
    time into BOTH ring time axes — monotonic time is meaningless across
    the restarts this store exists to survive."""

    def __init__(
        self,
        path: str,
        tiers: str | Sequence[tuple[float, int]] = DEFAULT_STORE_TIERS,
        rules: Sequence[RecordingRule] = (),
        max_series: int = 8192,
        tracked: frozenset[str] = STORE_TRACKED_METRICS,
        segment_max_bytes: int = 4 << 20,
        fsync: bool = False,
        wallclock: Callable[[], float] = time.time,
        sidecar_interval_s: float = 30.0,
    ) -> None:
        spec = (parse_tier_spec(tiers) if isinstance(tiers, str)
                else tuple(sorted(tiers)))
        if not spec:
            raise ValueError("the fleet store needs at least one tier "
                             "(--store-tiers cannot be 'off')")
        self.dir = path
        self.tier_spec = spec
        self.rules = tuple(rules)
        self.max_series = max_series
        self._tracked = tuple(sorted(tracked))
        self._segment_max_bytes = segment_max_bytes
        self._fsync = fsync
        self._wallclock = wallclock
        self._sidecar_interval_s = sidecar_interval_s
        self._rlog = RateLimitedLogger(log)
        self._lock = threading.Lock()
        self._series: dict[tuple, _StoreSeries] = {}
        self._buffers: tuple[WalBuffer, ...] = ()
        self._thinned = False
        self._samples_total = 0
        self._append_failures = 0
        self._dropped = {"shed": 0, "retention": 0, "corrupt": 0}
        self._rules_evaluated = 0
        self._rule_failures = 0
        # Last DURABLE round (every WAL frame landed) — the published
        # last-append timestamp and the AppendFailing alert's age arm.
        self._last_append_wall = 0.0
        # Last ingestion wall (in-memory fold) — the backward-step fence.
        self._last_ingest_wall = 0.0
        # Armed by set_thin: the shed tier's pending WAL records are
        # dropped by the APPENDER thread on its next pass — WalBuffer has
        # exactly one cursor-mover (the egress sender-thread lesson); a
        # governor-thread drop racing the appender's retention trim could
        # regress the on-disk cursor and resurrect shed records at boot.
        self._thin_drop_pending = False
        self._last_sidecar_wall = 0.0
        self._restored_buckets = 0
        # (tiers, span, occupancy generation) — see _occupancy_locked.
        # The generation bumps once per append BATCH / thin flip /
        # replay, so the scan runs at most once per round however many
        # queries land between rounds, and is never stale after a
        # mutation (a wall-time TTL would serve a pre-thin view).
        self._occ_cache: tuple[list[dict], float, int] | None = None
        self._occ_gen = 0
        # Budget hint for the sidecar/footer (the governor owns the actual
        # enforcement; mirroring it here keeps `status` honest about what
        # the disk number is measured AGAINST).
        self.disk_budget_bytes = 0
        # ENOSPC hook (pressure.PressureGovernor.report_io_error).
        self._pressure_hook: Callable[[BaseException], bool] | None = None

    # ------------------------------------------------------------------ boot

    def _tier_dir(self, step: float) -> str:
        return os.path.join(self.dir, f"tier-{step:g}")

    def open(self) -> dict:
        """Create the directory tree, open every tier's WAL buffer, and
        replay pending records back into the rings. Corruption keeps the
        clean prefix (WalBuffer semantics) and is counted, never raised;
        only an uncreatable directory raises OSError."""
        os.makedirs(self.dir, exist_ok=True)
        buffers: list[WalBuffer] = []
        errors: list[str] = []
        for step, _cap in self.tier_spec:
            buf = WalBuffer(self._tier_dir(step),
                            segment_max_bytes=self._segment_max_bytes,
                            fsync=self._fsync)
            info = buf.open()
            if info["corrupt_segments"]:
                self._dropped["corrupt"] += info["corrupt_segments"]
            errors.extend(info["errors"])
            buffers.append(buf)
        self._buffers = tuple(buffers)
        restored = 0
        with self._lock:
            for ti, buf in enumerate(self._buffers):
                step = self.tier_spec[ti][0]
                for payload in buf.iter_payloads():  # lint: disable=lock-io-chain(boot replay: open() runs before the round thread or HTTP queries exist, and holding the store lock keeps the restore atomic against an early query; no contention is possible here)
                    restored += self._replay_record_locked(ti, step, payload)
            # Re-open every series' newest restored bucket as the live
            # accumulator and resume counter-delta tracking from its last
            # value — post-restart samples merge instead of forking, and
            # window rates stay continuous across the boundary.
            for s in self._series.values():
                for t in s.tiers:
                    t.pop_to_accumulator()
                for t in s.tiers:
                    if t.bucket >= 0 and t.a_cnt > 0:
                        s.pv = t.a_last
                        s.last_wall = max(s.last_wall, t.a_twl)
                        break
        self._restored_buckets = restored
        self._occ_gen += 1
        return {
            "series": len(self._series),
            "buckets": restored,
            "corrupt_records": self._dropped["corrupt"],
            "errors": errors,
        }

    def _replay_record_locked(self, tier_idx: int, step: float,
                              payload: bytes) -> int:
        try:
            doc = json.loads(payload)
            rows = doc["rows"]
            if not isinstance(rows, list):
                raise TypeError("rows is not a list")
        except (ValueError, KeyError, TypeError):
            self._dropped["corrupt"] += 1
            return 0
        restored = 0
        for row in rows:
            try:
                name, labels, bucket = row
                if not (isinstance(name, str) and isinstance(labels, dict)
                        and isinstance(bucket, list) and len(bucket) == 11):
                    raise TypeError("bad row shape")
                b = tuple(float(x) for x in bucket)
            except (ValueError, TypeError):
                self._dropped["corrupt"] += 1
                continue
            lbl = {str(k): str(v) for k, v in labels.items()}
            key = series_key(name, lbl)
            s = self._series.get(key)
            if s is None:
                s = self._create_locked(key, name, lbl)
            s.tiers[tier_idx].push(b)
            s.last_wall = max(s.last_wall, b[3])
            restored += 1
        return restored

    # ---------------------------------------------------------------- append

    def _create_locked(self, key: tuple, name: str,
                       labels: dict[str, str]) -> _StoreSeries:
        while len(self._series) >= self.max_series:
            victim = min(self._series,
                         key=lambda k: self._series[k].last_wall)
            del self._series[victim]
        s = self._series[key] = _StoreSeries(name, labels, self.tier_spec)
        return s

    def _enabled(self, tier_idx: int) -> bool:
        # store_thin sheds the FINEST tier (index 0); coarse tiers are the
        # cheapest bytes per second of answerable history and shed never.
        return not (self._thinned and tier_idx == 0 and len(self.tier_spec) > 1)

    def _append_one_locked(
        self, s: _StoreSeries, value: float, now_wall: float,
        finalized: list[list[tuple[dict[str, str], str, tuple]]],
    ) -> None:
        d = value - s.pv
        dpos = d if d > 0.0 else 0.0
        s.pv = value
        s.last_wall = now_wall
        for i, t in enumerate(s.tiers):
            if not self._enabled(i):
                continue
            if t.bucket >= 0 and int(now_wall // t.step) != t.bucket:
                ob = t.open_bucket()
                if ob is not None:
                    finalized[i].append((s.labels, s.name, ob))
            t.add(now_wall, now_wall, value, dpos)

    def _fence_wall_locked(self, now_wall: float) -> float:
        """Backward-clock-step fence (the PR-10 egress discipline, applied
        to this new wall-time consumer): bucket ids must stay monotone —
        TierRing.push's replace-only-newest replay dedup and align_grid's
        forward walk both require time-ordered buckets. A backward NTP
        step therefore clamps ingestion time to the last append's wall
        (samples keep folding into the current bucket until the clock
        catches back up); forward steps pass through untouched."""
        return max(now_wall, self._last_ingest_wall)

    def append_snapshot(self, snapshot: "Snapshot",
                        now_wall: float | None = None) -> int:
        """Fold one root round into the tiers: every tracked family of the
        published snapshot plus the recording-rule outputs evaluated over
        the same snapshot. Returns the number of samples appended. Ring
        mutation happens under the store lock; WAL framing and file I/O
        happen OUTSIDE it (single-appender contract)."""
        now = self._wallclock() if now_wall is None else now_wall
        rule_rows: list[tuple[str, dict[str, str], float]] = []
        for rule in self.rules:
            try:
                for labels, value in evaluate_rule(rule, snapshot):
                    rule_rows.append((rule.name, labels, value))
                self._rules_evaluated += 1
            except Exception as e:  # noqa: BLE001 — one bad rule must not stop the round
                self._rule_failures += 1
                self._rlog.warning(f"rule:{rule.name}",
                                   "store rule %s failed: %s", rule.name, e)
        appended = 0
        finalized: list[list[tuple[dict[str, str], str, tuple]]] = [
            [] for _ in self.tier_spec
        ]
        with self._lock:
            now = self._fence_wall_locked(now)
            for name in self._tracked:
                spec = _SPEC_BY_NAME.get(name)
                if spec is None:
                    continue
                view = snapshot.samples_view(name)
                if not view:
                    continue
                label_names = spec.label_names
                for lvs, value in view.items():
                    key = (name, lvs)
                    s = self._series.get(key)
                    if s is None:
                        s = self._create_locked(
                            key, name, dict(zip(label_names, lvs)))
                    self._append_one_locked(s, float(value), now, finalized)
                    appended += 1
            for rname, labels, value in rule_rows:
                key = series_key(rname, labels)
                s = self._series.get(key)
                if s is None:
                    s = self._create_locked(key, rname, dict(labels))
                self._append_one_locked(s, value, now, finalized)
                appended += 1
            self._samples_total += appended
            self._last_ingest_wall = now
            self._occ_gen += 1
        self._persist_finalized(finalized, now)
        self._maybe_write_sidecar(now)
        return appended

    def append_samples(
        self,
        samples: Iterable[tuple[str, Mapping[str, str], float]],
        now_wall: float | None = None,
    ) -> int:
        """Labeled-sample entry point (tests, harnesses): ``(metric,
        labels, value)`` triples, one wall instant. Same locking split as
        :meth:`append_snapshot`."""
        now = self._wallclock() if now_wall is None else now_wall
        appended = 0
        finalized: list[list[tuple[dict[str, str], str, tuple]]] = [
            [] for _ in self.tier_spec
        ]
        with self._lock:
            now = self._fence_wall_locked(now)
            for name, labels, value in samples:
                key = series_key(name, labels)
                s = self._series.get(key)
                if s is None:
                    s = self._create_locked(key, name, dict(labels))
                self._append_one_locked(s, float(value), now, finalized)
                appended += 1
            self._samples_total += appended
            self._last_ingest_wall = now
            self._occ_gen += 1
        self._persist_finalized(finalized, now)
        self._maybe_write_sidecar(now)
        return appended

    def _persist_finalized(
        self, finalized: list[list[tuple[dict[str, str], str, tuple]]],
        now_wall: float,
    ) -> None:
        """Frame one WAL record per tier carrying every bucket finalized
        this append, then trim each buffer to its tier's own retention.
        Runs on the appender thread, outside the store lock — including
        the deferred store_thin drop: this thread is each buffer's ONE
        cursor-mover (append + retention trim + shed), so the cursor can
        never regress under a racing writer."""
        with self._lock:
            thin_drop = self._thin_drop_pending
            self._thin_drop_pending = False
        if thin_drop and self._buffers:
            buf0 = self._buffers[0]
            n = buf0.drop_oldest(buf0.pending())
            if n:
                with self._lock:
                    self._dropped["shed"] += n
                log.warning("store_thin: shed %d pending WAL record(s) of "
                            "the %gs tier", n, self.tier_spec[0][0])
        ok = True
        for ti, rows in enumerate(finalized):
            if not rows:
                continue
            step, cap = self.tier_spec[ti]
            payload = json.dumps(
                {"t": step,
                 "rows": [[name, labels, list(bucket)]
                          for labels, name, bucket in rows]},
                separators=(",", ":"),
            ).encode()
            buf = self._buffers[ti]
            try:
                buf.append(payload)
            except OSError as e:
                ok = False
                with self._lock:
                    self._append_failures += 1
                hook = self._pressure_hook
                if hook is not None:
                    try:
                        hook(e)
                    except Exception:  # noqa: BLE001 — a broken hook must not fail appends
                        pass
                self._rlog.warning(
                    f"append:{step:g}",
                    "store tier %gs WAL append failed (%s); tiers keep "
                    "serving, durability of this round's buckets is lost",
                    step, e,
                )
                continue
            # Retention: the buffer only needs to replay what the ring can
            # hold — records per tier ≈ one per bucket boundary, so the
            # cap (plus re-finalization slack) IS the retention horizon.
            excess = buf.pending() - (cap + _RETENTION_SLACK_RECORDS)
            if excess > 0:
                n = buf.drop_oldest(excess)
                if n:
                    with self._lock:
                        self._dropped["retention"] += n
        if ok and now_wall > 0:
            # Advances ONLY on fully-durable rounds: a store whose disk
            # refuses writes must age this stamp (the AppendFailing
            # alert's age arm and the footer read it), not report fresh
            # in-memory folds as durable history.
            with self._lock:
                self._last_append_wall = max(self._last_append_wall,
                                             now_wall)

    def set_pressure_hook(
        self, hook: Callable[[BaseException], bool]
    ) -> None:
        """Wire the governor's ``report_io_error`` so a store-side ENOSPC
        arms the disk ladder's fault window immediately."""
        self._pressure_hook = hook

    # ------------------------------------------------- pressure shed hooks

    def set_thin(self, thin: bool) -> None:
        """The disk ladder's ``store_thin`` rung: drop the FINEST tier —
        its rings empty, its WAL records are shed (counted, never silent)
        and appends to it stop — while every coarser tier keeps ingesting
        and answering. Reversible: release re-enables the tier, which
        refills from live appends. A single-tier store refuses (coarse
        tiers shed LAST means the last tier never sheds)."""
        if len(self.tier_spec) < 2:
            if thin:
                self._rlog.warning(
                    "thin", "store_thin requested but only one tier is "
                    "configured — refusing to drop the store's only tier")
            return
        buckets = 0
        with self._lock:
            if thin == self._thinned:
                return
            self._thinned = thin
            self._occ_gen += 1
            if thin:
                for s in self._series.values():
                    t = s.tiers[0]
                    buckets += t.n + (1 if t.bucket >= 0 and t.a_cnt else 0)
                    t.n = 0
                    t.head = 0
                    t.bucket = -1
                    t.a_cnt = 0
                # The tier's pending WAL records are shed by the APPENDER
                # on its next pass — this method may run on the governor
                # thread, and a buffer must have exactly one cursor-mover
                # (see _persist_finalized).
                self._thin_drop_pending = True
            else:
                # A release before the drop executed cancels it: the
                # records' replay would simply restore coverage into the
                # re-enabled tier.
                self._thin_drop_pending = False
        if thin:
            log.warning(
                "disk pressure: store_thin shed the %gs tier (%d buckets; "
                "its WAL records drop on the next round) — coarser tiers "
                "keep the long windows",
                self.tier_spec[0][0], buckets,
            )
        else:
            log.info("disk pressure lifted: store %gs tier re-enabled "
                     "(refills from live rounds)", self.tier_spec[0][0])

    def memory_bytes(self) -> int:
        """In-memory tier-ring bytes — registered with the memory ladder;
        the shed decision and the published gauge read this same number.
        Counts EVERY tier, thinned or not: TierRing preallocates its
        arrays at full capacity and ``store_thin`` only resets counters
        (it frees DISK, not ring memory) — excluding the thinned tier
        would feed the memory ladder phantom headroom and let it skip
        shedding components that actually would free bytes."""
        with self._lock:
            per_series = _SERIES_OVERHEAD_BYTES + sum(
                cap * _BUCKET_BYTES for _step, cap in self.tier_spec
            )
            return len(self._series) * per_series

    def disk_bytes(self) -> int:
        """Pending WAL bytes across tier buffers (cheap: in-memory
        counters, no directory walk — safe from the round thread)."""
        return sum(buf.pending_bytes() for buf in self._buffers)

    def disk_paths(self) -> list[str]:
        """Directories the disk ladder should budget over: the store root
        (sidecar) plus every per-tier segment dir — dir_usage_bytes is
        non-recursive by design, so each tier dir registers itself."""
        return [self.dir] + [self._tier_dir(step)
                             for step, _cap in self.tier_spec]

    # ----------------------------------------------------------------- query

    @staticmethod
    def _matches(labels: dict[str, str], match: Mapping[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in match.items())

    def _choose_tier(self, s: _StoreSeries, step: float,
                     start: float) -> int | None:
        """Tier selection: the COARSEST enabled tier whose resolution
        satisfies the requested step (the finest enabled one when step is
        0 or finer than everything), escalating to a coarser tier when the
        choice no longer covers ``start`` — the HistoryStore rules, minus
        the raw ring the store deliberately does not have.

        Coverage reads ``first_wall()`` (the occupancy read: the oldest
        bucket actually retained), NOT ``oldest_wall()``: that method's
        not-wrapped-means-holds-everything convention is FALSE here — a
        just-released ``store_thin`` tier refills from empty and must not
        claim infinite coverage while the coarse tier still holds the
        days-long span (a long-range query would silently answer minutes
        of post-release data)."""
        enabled = [i for i in range(len(s.tiers)) if self._enabled(i)]
        if not enabled:
            return None
        choice = enabled[0]
        if step > 0:
            for i in enabled:
                if s.tiers[i].step <= step:
                    choice = i
        if s.tiers[choice].first_wall() <= start:
            return choice
        best, best_first = choice, s.tiers[choice].first_wall()
        for i in enabled:
            if i <= choice:
                continue
            fw = s.tiers[i].first_wall()
            if fw <= start:
                return i
            if fw < best_first:
                best, best_first = i, fw
        return best

    def _query_rows(self, metric: str, match: Mapping[str, str],
                    step: float, start: float) -> list[tuple]:
        with self._lock:
            rows: list[tuple] = []
            for s in self._series.values():
                if s.name != metric or not self._matches(s.labels, match):
                    continue
                idx = self._choose_tier(s, step, start)
                if idx is None:
                    continue
                t = s.tiers[idx]
                rows.append((dict(s.labels), t.step, t.copy(),
                             s.last_wall or None))
            return rows

    def query_range(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        start: float | None = None,
        end: float | None = None,
        step: float = 0.0,
        agg: str = "last",
    ) -> list[dict]:
        """Node-shape ``query_range`` rows served from the tiers, each
        carrying ``source: "store"`` plus the usual ``tier`` and
        ``last_sample_wall_ts``. ``step == 0`` returns the finalized
        bucket samples themselves (at their last-sample wall time) —
        the honest continuity view, no grid carry-forward."""
        if end is None:
            end = self._wallclock()
        if start is None:
            start = end - 300.0
        out: list[dict] = []
        for labels, tier_step, payload, last_wall in self._query_rows(
            metric, match or {}, step, start
        ):
            buckets = tier_items(payload)
            points = [
                (b[3], _bucket_value(b, agg)) for b in buckets
            ]
            if step > 0:
                lookback = max(2.0 * step, 2.0 * tier_step, 10.0)
                values = align_grid(points, start, end, step, lookback)
            else:
                values = [[tw, v] for (tw, v) in points
                          if start <= tw <= end]
            if values:
                out.append({
                    "metric": metric, "labels": labels,
                    "values": values, "tier": tier_step,
                    "last_sample_wall_ts": last_wall,
                    "source": "store",
                })
        return out

    def window_stats(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        window_s: float = 60.0,
        now_wall: float | None = None,
    ) -> list[dict]:
        """Trailing-window stats folded exactly from tier buckets
        (history.fold_tier_window — weighted mean, reset-tolerant counter
        rate from within-bucket dpos + rebuilt boundary deltas)."""
        now = self._wallclock() if now_wall is None else now_wall
        lo = now - window_s
        counter = is_counter_metric(metric)
        out: list[dict] = []
        for labels, tier_step, payload, last_wall in self._query_rows(
            metric, match or {}, 0.0, lo
        ):
            buckets = [b for b in tier_items(payload) if b[1] >= lo]
            if not buckets:
                continue
            out.append({
                "metric": metric, "labels": labels,
                "stats": fold_tier_window(buckets, counter),
                "tier": tier_step,
                "last_sample_wall_ts": last_wall,
                "source": "store",
            })
        return out

    def series_list(self) -> list[dict]:
        with self._lock:
            out = []
            for s in self._series.values():
                buckets = 0
                for i, t in enumerate(s.tiers):
                    if self._enabled(i):
                        buckets += t.n + (1 if t.bucket >= 0 and t.a_cnt
                                          else 0)
                out.append({
                    "metric": s.name, "labels": dict(s.labels),
                    "samples": buckets, "source": "store",
                })
            return out

    # ---------------------------------------------------------- introspection

    # Tier occupancy is a full series × tiers scan under the store lock;
    # it backs both the per-round emit AND the per-query envelope
    # summary, so it is amortized generation-keyed (the HistoryStore
    # tier-stats discipline, minus the staleness: a mutation bumps the
    # generation, so readers never see a pre-thin or pre-append view
    # twice the scan just runs at most once per mutation).

    def _occupancy_locked(self) -> tuple[list[dict], float]:
        cached = self._occ_cache
        if cached is not None and cached[2] == self._occ_gen:
            return cached[0], cached[1]
        tiers: list[dict] = []
        span = 0.0
        for i, (step, cap) in enumerate(self.tier_spec):
            buckets = 0
            oldest = float("inf")
            newest = float("-inf")
            for s in self._series.values():
                t = s.tiers[i]
                buckets += t.n + (1 if t.bucket >= 0 and t.a_cnt else 0)
                fw = t.first_wall()
                if fw < oldest:
                    oldest = fw
                nw = t.newest_wall()
                if nw > newest:
                    newest = nw
            tspan = max(newest - oldest, 0.0) if buckets else 0.0
            span = max(span, tspan)
            tiers.append({
                "step_s": step, "capacity": cap, "buckets": buckets,
                "span_s": tspan, "enabled": self._enabled(i),
            })
        self._occ_cache = (tiers, span, self._occ_gen)
        return tiers, span

    def summary(self) -> dict:
        """The 4-field envelope summary (StoreQueryPlane) — O(1) between
        occupancy refreshes."""
        with self._lock:
            tiers, span = self._occupancy_locked()
            return {
                "span_s": span,
                "series": len(self._series),
                "thinned": self._thinned,
                "rules": len(self.rules),
            }

    def stats(self) -> dict:
        with self._lock:
            tiers, span = self._occupancy_locked()
            doc = {
                "dir": self.dir,
                "series": len(self._series),
                "samples_appended": self._samples_total,
                "append_failures": self._append_failures,
                "dropped": dict(self._dropped),
                "restored_buckets": self._restored_buckets,
                "rules": len(self.rules),
                "rules_evaluated_total": self._rules_evaluated,
                "rule_failures": self._rule_failures,
                "last_append_wall": self._last_append_wall,
                "thinned": self._thinned,
                "span_s": span,
                "tiers": tiers,
                "disk_budget_bytes": self.disk_budget_bytes,
            }
        doc["disk_bytes"] = self.disk_bytes()
        doc["memory_bytes"] = self.memory_bytes()
        return doc

    def emit(self, b: "SnapshotBuilder") -> None:
        """Publish the ``tpu_root_store_*`` surface into one root snapshot
        (conditional surface — present only while a store is attached)."""
        st = self.stats()
        for spec in schema.STORE_SPECS:
            b.declare(spec)
        b.add(schema.TPU_ROOT_STORE_APPENDED_SAMPLES_TOTAL,
              float(st["samples_appended"]))
        b.add(schema.TPU_ROOT_STORE_APPEND_FAILURES_TOTAL,
              float(st["append_failures"]))
        b.add(schema.TPU_ROOT_STORE_SERIES, float(st["series"]))
        for tier in st["tiers"]:
            b.add(schema.TPU_ROOT_STORE_TIER_BUCKETS,
                  float(tier["buckets"]), (f"{tier['step_s']:g}",))
        b.add(schema.TPU_ROOT_STORE_SPAN_SECONDS, float(st["span_s"]))
        b.add(schema.TPU_ROOT_STORE_DISK_BYTES, float(st["disk_bytes"]))
        b.add(schema.TPU_ROOT_STORE_MEMORY_BYTES, float(st["memory_bytes"]))
        for reason in ("shed", "retention", "corrupt"):
            b.add(schema.TPU_ROOT_STORE_DROPPED_RECORDS_TOTAL,
                  float(st["dropped"][reason]), (reason,))
        b.add(schema.TPU_ROOT_STORE_RULES, float(st["rules"]))
        b.add(schema.TPU_ROOT_STORE_RULE_FAILURES_TOTAL,
              float(st["rule_failures"]))
        b.add(schema.TPU_ROOT_STORE_LAST_APPEND_TIMESTAMP_SECONDS,
              float(st["last_append_wall"]))
        b.add(schema.TPU_ROOT_STORE_THINNED,
              1.0 if st["thinned"] else 0.0)

    def _maybe_write_sidecar(self, now_wall: float) -> None:
        if now_wall - self._last_sidecar_wall < self._sidecar_interval_s:
            return
        self.write_sidecar(now_wall)

    def write_sidecar(self, now_wall: float | None = None) -> None:
        """Operator-facing sidecar for the ``status --tree`` store footer.
        Best-effort by design (the pressure sidecar's contract): on a full
        disk the footer shows the last state that fit."""
        now = self._wallclock() if now_wall is None else now_wall
        self._last_sidecar_wall = now
        doc = {"wall": now, **self.stats()}
        try:
            atomic_write(os.path.join(self.dir, SIDECAR_NAME),
                         json.dumps(doc).encode())
        except OSError:
            pass

    def close(self) -> None:
        """Graceful shutdown: the still-open accumulator buckets flush as
        records first, so a clean restart loses NOTHING (replay re-opens
        them via pop_to_accumulator and a later re-finalization record
        replaces, never duplicates). A SIGKILL skips this by definition —
        the documented floor is one open bucket per tier of tail loss."""
        finalized: list[list[tuple[dict[str, str], str, tuple]]] = [
            [] for _ in self.tier_spec
        ]
        with self._lock:
            last_ingest = self._last_ingest_wall
            for s in self._series.values():
                for i, t in enumerate(s.tiers):
                    if not self._enabled(i):
                        continue
                    ob = t.open_bucket()
                    if ob is not None:
                        finalized[i].append((s.labels, s.name, ob))
        self._persist_finalized(finalized, last_ingest)
        self.write_sidecar()
        for buf in self._buffers:
            buf.close()


def series_key(name: str, labels: Mapping[str, str]) -> tuple:
    """The store's series identity. Schema-known metrics key by label
    VALUES in spec order — the exact key ``append_snapshot`` builds from
    ``samples_view`` tuples, so restored and live samples can never fork
    into twins (the restore_series lesson from PR 4). Rule outputs (not in
    the schema) key by sorted label items."""
    spec = _SPEC_BY_NAME.get(name)
    if spec is not None:
        return (name, tuple(str(labels.get(ln, ""))
                            for ln in spec.label_names))
    return (name, tuple(sorted(labels.items())))


def _bucket_value(b: tuple, agg: str) -> float:
    if agg == "min":
        return b[4]
    if agg == "max":
        return b[5]
    if agg == "mean":
        return b[6] / b[7] if b[7] else b[9]
    return b[9]  # last


def store_status_summary(path: str) -> dict | None:
    """Read the store's on-disk sidecar for the out-of-process ``status
    --tree`` footer (None when absent/unreadable — no store runs here)."""
    try:
        with open(os.path.join(path, SIDECAR_NAME), encoding="utf-8") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


# --------------------------------------------------------- source-aware plane


SOURCES: tuple[str, ...] = ("merged", "live", "store")


class StoreQueryPlane:
    """Source-aware ``/api/v1`` front: the live two-level fan-out plus the
    store, merged per series. The live fan-out answers for what is
    reachable NOW; the store fills every series key the live merge has no
    coverage for (dead nodes, pre-restart windows, recording-rule series)
    — and ``source=store`` answers from the store alone. Every row
    carries its own ``source`` (live rows are tagged on copies — cached
    envelopes are shared and must never be mutated)."""

    # server.py passes the ?source= parameter only to planes that declare
    # this — the node tier and store-less aggregators 400 it instead.
    handles_source = True

    def __init__(self, live: Any, store: FleetStore,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._live = live
        self._store = store
        self._clock = clock

    # ------------------------------------------------------------- public API

    def series(self, source: str = "merged") -> dict:
        source = self._resolve(source)
        if source == "live":
            return self._tag_live(self._live.series(), "series")
        t0 = self._clock()
        srows = self._store.series_list()
        if source == "store":
            return self._store_env("series", srows, t0)
        env = self._tag_live(self._live.series(), "series")
        live_rows = _rows_of("series", env)
        keys = {_row_key(r) for r in live_rows}
        fills = [r for r in srows if _row_key(r) not in keys]
        return self._merge_env(env, "series", live_rows, fills)

    def query_range(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        start: float | None = None,
        end: float | None = None,
        step: float = 0.0,
        agg: str = "last",
        source: str = "merged",
    ) -> dict:
        source = self._resolve(source)
        if source == "live":
            return self._tag_live(
                self._live.query_range(metric, match, start, end, step,
                                       agg=agg),
                "query_range")
        if source == "store":
            t0 = self._clock()
            rows = self._store.query_range(metric, match, start, end, step,
                                           agg=agg)
            return self._store_env("query_range", rows, t0)
        env = self._tag_live(
            self._live.query_range(metric, match, start, end, step, agg=agg),
            "query_range")
        live_rows = _rows_of("query_range", env)
        # The live plane may have grid-aligned start/end; reuse ITS
        # effective range when it says so, so live and store rows share
        # one grid.
        eff_start = env.get("start", start)
        eff_end = env.get("end", end)
        srows = self._store.query_range(
            metric, match,
            eff_start if isinstance(eff_start, (int, float)) else start,
            eff_end if isinstance(eff_end, (int, float)) else end,
            step, agg=agg)
        keys = {_row_key(r) for r in live_rows}
        fills = [r for r in srows if _row_key(r) not in keys]
        return self._merge_env(env, "query_range", live_rows, fills)

    def window_stats(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        window_s: float = 60.0,
        source: str = "merged",
    ) -> dict:
        source = self._resolve(source)
        if source == "live":
            return self._tag_live(
                self._live.window_stats(metric, match, window_s=window_s),
                "window_stats")
        if source == "store":
            t0 = self._clock()
            rows = self._store.window_stats(metric, match, window_s=window_s)
            return self._store_env("window_stats", rows, t0)
        env = self._tag_live(
            self._live.window_stats(metric, match, window_s=window_s),
            "window_stats")
        live_rows = _rows_of("window_stats", env)
        srows = self._store.window_stats(metric, match, window_s=window_s)
        keys = {_row_key(r) for r in live_rows}
        fills = [r for r in srows if _row_key(r) not in keys]
        return self._merge_env(env, "window_stats", live_rows, fills)

    # --------------------------------------------------------------- internals

    def _resolve(self, source: str) -> str:
        source = source or "merged"
        if source not in SOURCES:
            raise ValueError(
                f"source must be one of {'/'.join(SOURCES)} (got {source!r})")
        if self._live is None:
            if source == "live":
                raise ValueError(
                    "source=live: no live query plane attached "
                    "(--fleet-query off) — this root serves store only")
            return "store"
        return source

    def _tag_live(self, env: Mapping[str, Any], route: str) -> dict:
        """Top-level copy of a live envelope with every row tagged
        ``source: live`` (row copies — cached envelopes stay pristine)."""
        out = dict(env)
        rows = [{**row, "source": row.get("source", "live")}
                if isinstance(row, dict) else row
                for row in _rows_of(route, env)]
        out["data"] = _data_shape(route, rows)
        out.setdefault("source", "live")
        return out

    def _store_summary(self) -> dict:
        # summary() is O(1) between occupancy refreshes — this runs per
        # query, and a full series × tiers scan per query would contend
        # the store lock against the round thread's append.
        return self._store.summary()

    def _store_env(self, route: str, rows: list[dict], t0: float) -> dict:
        """``t0`` is captured by the caller BEFORE the store query runs —
        took_s must bracket the ring walk, not this dict build."""
        return {
            "status": "ok",
            "partial": False,
            "route": route,
            "data": _data_shape(route, rows),
            "source": "store",
            "store": self._store_summary(),
            "took_s": round(self._clock() - t0, 6),
        }

    def _merge_env(self, env: dict, route: str, live_rows: list,
                   fills: list[dict]) -> dict:
        env["data"] = _data_shape(route, list(live_rows) + fills)
        env["source"] = "merged" if fills else "live"
        env["store"] = {"filled_series": len(fills), **self._store_summary()}
        return env

    def close(self) -> None:
        if self._live is not None:
            self._live.close()


def _row_key(row: Mapping[str, Any]) -> tuple:
    try:
        return (row.get("metric", ""),
                tuple(sorted((row.get("labels") or {}).items())))
    except TypeError:
        return ("", ())


# ---------------------------------------------------------------------- demo


def run_retention_demo(
    state_dir: str,
    targets: int = 1000,
    days: float = 7.0,
    sim_round_s: float = 600.0,
    budget_frac: float = 0.7,
    verbose: bool = True,
) -> int:
    """7-day synthetic retention at fleet scale, compressed to a simulated
    wall clock, inside a governor-enforced disk budget (the acceptance
    drill). Tiers are scaled so the coarsest alone spans the full window:
    mid-run the budget is squeezed below current usage, the disk ladder
    must shed ``store_thin`` (finest tier dropped, counted), usage must
    come back under budget, and the full span must STILL answer from the
    coarse tier — including across a kill/replay restart."""
    from tpu_pod_exporter.metrics import SnapshotBuilder
    from tpu_pod_exporter.pressure import (
        PressureGovernor,
        register_store_rungs,
    )

    total_s = days * 86400.0
    # Finest: ~20 h of 10-min buckets; coarsest: the full window in 1-h
    # buckets — the tier the thin rung must leave standing.
    coarse_cap = int(total_s // 3600.0) + 2
    tiers = f"600:120,3600:{coarse_cap}"
    sim = {"wall": 1_700_000_000.0}
    rules = parse_rules(
        "demo:hbm:by_slice = sum(" + schema.TPU_SLICE_HBM_USED_BYTES.name
        + ") by (slice_name)\n"
        "demo:targets:up = sum(" + schema.TPU_AGG_TARGET_UP.name + ")\n"
    )
    store = FleetStore(state_dir, tiers=tiers, rules=rules,
                       wallclock=lambda: sim["wall"])
    store.open()
    # The governor outlives the mid-run store restart, so the rungs are
    # registered with a getter (register_store_rungs store_fn contract);
    # the restart below re-applies the hook + held thin state.
    holder: dict[str, FleetStore] = {"store": store}
    gov = PressureGovernor()
    register_store_rungs(gov, store, store_fn=lambda: holder["store"])

    up_name = schema.TPU_AGG_TARGET_UP.name
    hbm_name = schema.TPU_SLICE_HBM_USED_BYTES.name

    def round_snapshot(r: int) -> "Snapshot":
        b = SnapshotBuilder()
        b.declare(schema.TPU_AGG_TARGET_UP)
        b.declare(schema.TPU_SLICE_HBM_USED_BYTES)
        for i in range(targets):
            b.add(schema.TPU_AGG_TARGET_UP,
                  0.0 if (i + r) % 97 == 0 else 1.0, (f"t{i:04d}",))
        for sl in range(8):
            b.add(schema.TPU_SLICE_HBM_USED_BYTES,
                  float((sl + 1) * 2**30 + r * 4096),
                  (f"slice-{sl}", "v5p", "tpu"))
        return b.build(timestamp=sim["wall"])

    rounds = int(total_s // sim_round_s)
    squeeze_at = rounds // 2
    budget = 0
    sheds_seen = 0
    restarted = False
    problems: list[str] = []
    for r in range(rounds):
        sim["wall"] += sim_round_s
        store.append_snapshot(round_snapshot(r), now_wall=sim["wall"])
        if r == squeeze_at:
            usage = store.disk_bytes()
            budget = max(int(usage * budget_frac), 64 << 10)
            store.disk_budget_bytes = budget
            gov.set_disk_budget_bytes(budget)
            if verbose:
                print(f"  r{r}: squeezing disk budget to {budget}B "
                      f"(usage {usage}B)")
        if r >= squeeze_at:
            gov.tick()
            sheds_seen = max(sheds_seen, gov.stats()["disk"]["sheds"])
        if not restarted and r == squeeze_at + rounds // 8:
            # Kill/replay mid-retention: the restarted store must answer
            # the same span from replayed records alone. The governor
            # survives the swap; its held rung is re-applied to the fresh
            # instance (a real root restart restarts governor and store
            # together — this drill deliberately splits them to prove the
            # replay path under pressure).
            store.close()
            store = FleetStore(state_dir, tiers=tiers, rules=rules,
                               wallclock=lambda: sim["wall"])
            info = store.open()
            store.disk_budget_bytes = budget
            holder["store"] = store
            store.set_pressure_hook(gov.report_io_error)
            if gov.stats()["disk"]["level"] >= 1:
                store.set_thin(True)
            restarted = True
            if verbose:
                print(f"  r{r}: restarted store — replayed "
                      f"{info['buckets']} buckets / {info['series']} series")

    st = store.stats()
    usage = store.disk_bytes()
    # Floor: the coarse tier's own records are unmeetable by ANY policy
    # that keeps the 7-day span (the pressure-demo floor discipline).
    coarse_buf_bytes = store._buffers[-1].pending_bytes()
    floor = coarse_buf_bytes + (64 << 10)
    if sheds_seen < 1:
        problems.append("disk ladder never shed store_thin "
                        "(governor inert under the squeezed budget)")
    if not st["thinned"]:
        problems.append("store not thinned after the squeeze")
    if usage > max(budget, floor):
        problems.append(f"disk usage {usage}B over max(budget {budget}B, "
                        f"coarse floor {floor}B)")
    want_span = total_s * 0.9
    if st["span_s"] < want_span:
        problems.append(f"answerable span {st['span_s']:.0f}s < "
                        f"{want_span:.0f}s — the 7-day window did not "
                        f"survive thinning")
    rows = store.query_range(
        "demo:hbm:by_slice", {"slice_name": "slice-3"},
        start=sim["wall"] - total_s, end=sim["wall"], step=3600.0)
    if not rows or len(rows[0]["values"]) < int(total_s / 3600.0 * 0.8):
        got = len(rows[0]["values"]) if rows else 0
        problems.append(f"rule-backed 7-day query answered {got} grid "
                        f"points (want most of {int(total_s / 3600.0)})")
    up_rows = store.window_stats(
        up_name, {"target": f"t{min(42, targets - 1):04d}"},
        window_s=total_s)
    if not up_rows:
        problems.append("per-target series not answerable over the window")
    if verbose:
        print(f"  {targets} targets · {rounds} rounds over {days:g} "
              f"simulated days · span {st['span_s'] / 86400.0:.1f}d · "
              f"disk {usage}B vs budget {budget}B · sheds {sheds_seen} · "
              f"restart replay {'ok' if restarted else 'SKIPPED'}")
    store.close()
    if problems:
        for p in problems:
            print(f"  FAIL: {p}")
        return 1
    if verbose:
        print("  retention drill OK")
    return 0


def run_query_budget_demo(
    state_dir: str, targets: int = 200, shards: int = 4,
    iterations: int = 25, verbose: bool = True,
) -> int:
    """Stored-rollup query vs the cold two-level fan-out at fleet shape
    (the CI p99 budget): a real farm + leaf tier + root over HTTP, a
    store fed from the root's rounds, then p99 of (a) ``source=store``
    rule-series queries against (b) cache-busted live fan-outs. The
    stored path must win — that is the whole point of recording rules."""
    from tpu_pod_exporter.loadgen.fleet import _ShardSim
    from tpu_pod_exporter.shard import RootQueryPlane

    rules = parse_rules(
        "demo:hbm:by_slice = sum(" + schema.TPU_SLICE_HBM_USED_BYTES.name
        + ") by (slice_name)\n")
    store_holder: dict[str, FleetStore] = {}

    def factory() -> FleetStore:
        s = FleetStore(os.path.join(state_dir, "store"),
                       tiers="0.5:600,5:600", rules=rules)
        s.open()
        store_holder["store"] = s
        return s

    sim = _ShardSim(targets, shards, False, 1, state_dir,
                    timeout_s=5.0, query_plane=True, store_factory=factory)
    try:
        for _ in range(6):
            sim.run_round()
        store = store_holder["store"]
        live = RootQueryPlane(sim.topology, timeout_s=5.0)
        plane = StoreQueryPlane(live, store)
        hbm = schema.TPU_HBM_USED_BYTES.name

        def p99(samples: list[float]) -> float:
            samples = sorted(samples)
            return samples[min(int(len(samples) * 0.99), len(samples) - 1)]

        cold: list[float] = []
        for i in range(iterations):
            t0 = time.perf_counter()
            # Distinct window per iteration busts every generation-keyed
            # leaf cache: this IS the cold fan-out path a dashboard pays
            # without recording rules.
            env = plane.query_range(hbm, start=time.time() - 60.0 - i,
                                    end=time.time(), step=0.0,
                                    source="live")
            cold.append(time.perf_counter() - t0)
            if not _rows_of("query_range", env):
                print("  FAIL: cold fan-out returned no rows")
                return 1
        stored: list[float] = []
        for i in range(iterations):
            t0 = time.perf_counter()
            env = plane.query_range("demo:hbm:by_slice",
                                    start=time.time() - 60.0 - i,
                                    end=time.time(), step=0.5,
                                    source="store")
            stored.append(time.perf_counter() - t0)
            if not _rows_of("query_range", env):
                print("  FAIL: stored-rule query returned no rows")
                return 1
        cold_p99, store_p99 = p99(cold), p99(stored)
        if verbose:
            print(f"  {targets} targets / {shards} shards: stored-rollup "
                  f"p99 {store_p99 * 1e3:.2f}ms vs cold fan-out p99 "
                  f"{cold_p99 * 1e3:.2f}ms")
        if store_p99 >= cold_p99:
            print(f"  FAIL: stored query p99 {store_p99 * 1e3:.2f}ms did "
                  f"not beat the cold fan-out {cold_p99 * 1e3:.2f}ms")
            return 1
        if verbose:
            print("  query-budget drill OK")
        return 0
    finally:
        plane_obj = locals().get("plane")
        if plane_obj is not None:
            plane_obj.close()
        sim.close()


def main(argv: list[str] | None = None) -> int:
    import argparse
    import tempfile

    p = argparse.ArgumentParser(
        prog="tpu-pod-exporter-store",
        description="Fleet TSDB-lite drills: 7-day synthetic retention "
                    "inside a governor-enforced disk budget (store_thin "
                    "exercised), and the stored-rollup-vs-cold-fan-out "
                    "query budget (make store-demo).",
    )
    p.add_argument("--demo", action="store_true",
                   help="run the store drills and fail on any broken "
                        "invariant")
    p.add_argument("--drill", default="all",
                   help="retention | query | all")
    p.add_argument("--targets", type=int, default=1000,
                   help="synthetic targets for the retention drill")
    p.add_argument("--days", type=float, default=7.0,
                   help="simulated retention window, days")
    p.add_argument("--query-targets", type=int, default=200,
                   help="real-HTTP targets for the query-budget drill")
    p.add_argument("--state-dir", default="",
                   help="drill state dir (default: temp)")
    ns = p.parse_args(argv)
    if not ns.demo:
        p.error("need --demo")
    state_dir = ns.state_dir or tempfile.mkdtemp(prefix="store-demo-")
    os.makedirs(state_dir, exist_ok=True)
    rc = 0
    if ns.drill in ("all", "retention"):
        print(f"retention drill: {ns.targets} targets, {ns.days:g} days "
              f"simulated")
        rc = rc or run_retention_demo(
            os.path.join(state_dir, "retention"),
            targets=ns.targets, days=ns.days)
    if ns.drill in ("all", "query"):
        print(f"query-budget drill: {ns.query_targets} targets")
        rc = rc or run_query_budget_demo(
            os.path.join(state_dir, "query"), targets=ns.query_targets)
    if rc == 0:
        print("store-demo OK: days of fleet history inside the disk "
              "budget, store_thin sheds by policy, and stored rollups "
              "beat the cold fan-out")
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(main())
