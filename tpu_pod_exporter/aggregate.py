"""Slice-level aggregator — cross-host rollups without a Prometheus.

On a multi-host slice (e.g. v5p-64: 8 hosts × 4 chips) each host runs one
exporter and cross-host aggregation is a *label join*, normally done by
Prometheus recording rules (SURVEY.md §2.8: exporters never talk to each
other; ICI/DCN are measured quantities, not transports). This optional
component computes the same joins for setups without a Prometheus: it
scrapes every per-host ``/metrics``, sums per-slice and per-workload, and
re-exports the rollups on its own ``/metrics``.

Deliberately an *observer of exporters*, not a peer: it consumes the public
exposition format over HTTP — the same bytes Prometheus would — so it works
against any mix of exporter versions and needs no new protocol. A target
that fails to scrape is reported down (``tpu_aggregator_target_up 0``) and
its chips simply drop out of the sums for that round; partial slices stay
honest via ``tpu_slice_hosts_reporting``.

The aggregator also serves the **federated query plane**
(``tpu_pod_exporter.fleet``, ``--fleet-query``): its own ``/api/v1/*``
routes fan ``query_range``/``window_stats``/``series`` out to every
non-quarantined target and merge per-series answers with partial-result
semantics — one query shows a duty-cycle cliff across all 64 hosts of a
slice, riding each node's history tiers hours back.

Run: ``python -m tpu_pod_exporter.aggregate --targets h0:8000,h1:8000``.
"""

from __future__ import annotations

import argparse
import inspect
import json
import logging
import os
import signal
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Sequence

from tpu_pod_exporter.collector import CollectorLoop
from tpu_pod_exporter.metrics import (
    CounterStore,
    HistogramStore,
    PrefixCache,
    SnapshotBuilder,
    SnapshotStore,
)
from tpu_pod_exporter.metrics import schema
from tpu_pod_exporter.metrics.parse import (
    LayoutCache,
    ParseError,
    parse_exposition_layout,
)

from tpu_pod_exporter.server import MetricsServer
from tpu_pod_exporter.supervisor import CLOSED, STATE_VALUES, CircuitBreaker
from tpu_pod_exporter.trace import format_traceparent
from tpu_pod_exporter import utils
from tpu_pod_exporter.utils import RateLimitedLogger

# The only sample names _consume folds. Passed to parse_exposition as a
# pre-parse filter: a 256-chip body is ~4k lines of which roughly half
# (per-link counters, percents, info/self series) are irrelevant here —
# skipping them before label parsing nearly halves round latency at
# 64-host scale (bench_aggregate.py).
CONSUMED_NAMES = frozenset({
    "tpu_chip_info",
    "tpu_hbm_used_bytes",
    "tpu_hbm_total_bytes",
    "tpu_tensorcore_duty_cycle_percent",
    "tpu_ici_link_bandwidth_bytes_per_second",
    "tpu_dcn_link_bandwidth_bytes_per_second",
    "tpu_host_info",
    "tpu_pod_chip_count",
    "tpu_pod_hbm_used_bytes",
    # The GPU device family's node surface (backend/nvml.py): same fold
    # slots, family-keyed slice accumulators — a mixed fleet's sums never
    # cross families.
    "gpu_chip_info",
    "gpu_hbm_used_bytes",
    "gpu_hbm_total_bytes",
    "gpu_utilization_percent",
    "gpu_pod_chip_count",
    "gpu_pod_memory_used_bytes",
})

log = logging.getLogger("tpu_pod_exporter.aggregate")


def target_base_url(target: str) -> str:
    """``host:port`` (or a full /metrics URL) → the exporter's URL root,
    for the ``/api/v1/*`` history endpoints."""
    if target.startswith(("http://", "https://")):
        return target[: -len("/metrics")] if target.endswith("/metrics") else target
    return f"http://{target}"


def default_history_fetch(url: str, timeout_s: float) -> dict:
    """GET one history-API URL, parsed JSON. Raises on HTTP/parse failure
    (the caller treats any raise as 'no history answer from this target')."""
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:  # noqa: S310 — operator-supplied targets
        return json.loads(resp.read().decode("utf-8", errors="replace"))


def default_fetch(target: str, timeout_s: float,
                  traceparent: str | None = None) -> str:
    """``host:port`` (or full URL) → exposition text.

    Asks for gzip: the exporters serve a lazily-cached compressed body
    (~20× smaller than the ~900 KB plain text at 256 chips), which matters
    when the aggregator scrapes every host of a slice over DCN each round.

    ``traceparent`` (W3C Trace Context) carries the aggregator's round
    trace + scrape span onto the exporter, which records its serve time as
    a scrape span under that remote context — the cross-tier join asserted
    in tests/test_trace.py.
    """
    url = target if target.startswith(("http://", "https://")) else f"http://{target}/metrics"
    headers = {"Accept-Encoding": "gzip"}
    if traceparent:
        headers["traceparent"] = traceparent
    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:  # noqa: S310 — operator-supplied targets
        body = resp.read()
        if (resp.headers.get("Content-Encoding") or "").lower() == "gzip":
            import gzip

            body = gzip.decompress(body)
        return body.decode("utf-8", errors="replace")


class _SliceAgg:
    """Mutable per-(slice, accelerator, family) accumulator for one round."""

    __slots__ = ("hosts", "chip_series_hosts", "chips", "hbm_used",
                 "hbm_total", "used_chips", "total_chips", "duty_sum",
                 "duty_n", "ici_bw", "ici_n", "dcn_bw", "dcn_n")

    def __init__(self) -> None:
        self.hosts: set[str] = set()
        # Hosts seen via ANY per-chip series but (possibly) not via
        # chip_info — only for the mixed-fleet diagnostic, never for counts.
        self.chip_series_hosts: set[str] = set()
        self.chips = 0
        self.hbm_used = 0.0
        self.hbm_total = 0.0
        # (host, chip_id) identity sets, not bare counts: a slice whose
        # chips published NO hbm series (HBM unreadable — collector round 4)
        # must omit the slice HBM rollups, and the percent is honest only
        # when used and total cover the SAME chips — equal counts over
        # disjoint sets (chip A used-only + chip B total-only) could read
        # >100% (code-review r5).
        self.used_chips: set[tuple[str, str]] = set()
        self.total_chips: set[tuple[str, str]] = set()
        self.duty_sum = 0.0
        self.duty_n = 0
        self.ici_bw = 0.0
        # Same rule as duty/HBM: a slice with NO ICI samples (runtime
        # without ICI counters) omits the rollup — 0.0 would read as
        # "interconnect idle", not "unmeasured". Ditto DCN.
        self.ici_n = 0
        self.dcn_bw = 0.0
        self.dcn_n = 0

    # Count/flag surface consumed by emit_rollups — the same attribute
    # names tpu_pod_exporter.shard's SliceStats (rebuilt at the root tier
    # from tpu_leaf_slice_component series) exposes, so one emit path
    # serves both the flat aggregator and the sharded tree's root.
    @property
    def hosts_n(self) -> int:
        return len(self.hosts)

    @property
    def used_n(self) -> int:
        return len(self.used_chips)

    @property
    def total_n(self) -> int:
        return len(self.total_chips)

    @property
    def coverage_eq(self) -> bool:
        """Used and total HBM samples cover the SAME chip set — the slice
        percent is emitted only then (see emit_rollups)."""
        return self.used_chips == self.total_chips

    def orphan_hosts(self) -> set[str]:
        """Hosts contributing per-chip series but no tpu_chip_info rows
        (mixed-fleet diagnostic; empty at the root tier, where the leaf
        already warned)."""
        return self.chip_series_hosts - self.hosts


class _GroupAgg:
    """Mutable per-multislice-group accumulator for one round."""

    __slots__ = ("slices", "hosts_n", "chips", "hbm_used", "hbm_used_n",
                 "ici_bw", "ici_n", "dcn_bw", "dcn_n", "expected_slices")

    def __init__(self) -> None:
        self.slices: set[tuple[str, str, str]] = set()
        # Count, not a set: slice hosts are disjoint (one host belongs to
        # one slice), so summing per-slice counts equals the union size —
        # and the root tier only has counts to sum.
        self.hosts_n = 0
        self.chips = 0
        self.hbm_used = 0.0
        self.hbm_used_n = 0
        self.ici_bw = 0.0
        self.ici_n = 0
        self.dcn_bw = 0.0
        self.dcn_n = 0
        self.expected_slices = 0


class _WorkloadAgg:
    __slots__ = ("chips", "hbm_used", "hbm_used_n", "hosts")

    def __init__(self) -> None:
        self.chips = 0.0
        self.hbm_used = 0.0
        # Same absent-beats-fake-zero rule as _SliceAgg: a workload whose
        # pods emitted chip_count but no hbm series must omit workload HBM.
        self.hbm_used_n = 0
        self.hosts: set[str] = set()

    @property
    def hosts_n(self) -> int:
        return len(self.hosts)


def emit_rollups(b: SnapshotBuilder, slices: dict, workloads: dict,
                 slice_groups: dict,
                 rlog: RateLimitedLogger | None = None) -> None:
    """Fold the round accumulators into rollup series on ``b`` — the ONE
    emit path for ``tpu_slice_*`` / ``tpu_multislice_*`` / ``tpu_workload_*``.

    Shared between :class:`SliceAggregator` (accumulators fresh from
    ``_consume``) and the sharded tree's root tier
    (:class:`tpu_pod_exporter.shard.RootAggregator`, accumulators rebuilt
    by summing per-shard ``tpu_leaf_*`` components), so the root's fleet
    rollups cannot drift from what a flat aggregator over the same scrape
    set would publish — the shard-demo asserts them equal against exactly
    that oracle. Consumes only the count/flag surface (``hosts_n``,
    ``used_n``, ``coverage_eq``, …), never the identity sets, because the
    root only has counts."""
    for key, agg in slices.items():
        # Mixed-fleet diagnostic (advisor r4): an exporter older than the
        # unconditional-chip_info change contributes HBM sums while its
        # chips/hosts_reporting read 0 — a silent undercount during
        # rolling upgrades. Not supported, but loudly not silently.
        orphan_hosts = agg.orphan_hosts()
        if orphan_hosts and rlog is not None:
            rlog.warning(
                f"orphan-hbm:{key[0]}",
                "slice %s: host(s) %s contribute per-chip series but "
                "zero tpu_chip_info rows — exporter too old? chips/"
                "hosts_reporting will undercount",
                key[0], sorted(orphan_hosts),
            )
        b.add(schema.TPU_SLICE_HOSTS_REPORTING, float(agg.hosts_n), key)
        b.add(schema.TPU_SLICE_CHIP_COUNT, float(agg.chips), key)
        # Emitted only when at least one chip actually reported HBM —
        # absent beats fake-zero, same rule the exporter applies to
        # per-chip and per-pod series.
        if agg.used_n:
            b.add(schema.TPU_SLICE_HBM_USED_BYTES, agg.hbm_used, key)
        if agg.total_n:
            b.add(schema.TPU_SLICE_HBM_TOTAL_BYTES, agg.hbm_total, key)
        # Percent only when used and total cover the SAME chip set —
        # mismatched coverage (e.g. a runtime serving bytes_in_use but
        # no bytes_limit on some chips) would yield a misleading or
        # >100% ratio (advisor r4) — and only over a positive capacity:
        # a percent of zero total is undefined, and 0.0 would read as
        # "idle" (same rule as the per-chip series).
        if agg.used_n and agg.coverage_eq and agg.hbm_total > 0:
            b.add(
                schema.TPU_SLICE_HBM_USED_PERCENT,
                schema.hbm_used_percent(agg.hbm_used, agg.hbm_total),
                key,
            )
        if agg.duty_n:
            b.add(
                schema.TPU_SLICE_DUTY_CYCLE_AVG_PERCENT,
                agg.duty_sum / agg.duty_n,
                key,
            )
        if agg.ici_n:
            b.add(schema.TPU_SLICE_ICI_BYTES_PER_SECOND, agg.ici_bw, key)
        if agg.dcn_n:
            b.add(schema.TPU_SLICE_DCN_BYTES_PER_SECOND, agg.dcn_bw, key)

    # Per-family fleet rollups: the slice sums grouped by the accelerator
    # family key (key[2]) — published rather than derived so mixed-fleet
    # dashboards and the store's `by (family)` rules never sum across
    # families by accident. Same absent-beats-fake-zero guards.
    fam_hosts: dict[str, float] = {}
    fam_chips: dict[str, float] = {}
    fam_used: dict[str, list[float]] = {}   # [sum, n]
    fam_total: dict[str, list[float]] = {}  # [sum, n]
    for key, agg in slices.items():
        fam = key[2] if len(key) > 2 else "tpu"
        fam_hosts[fam] = fam_hosts.get(fam, 0.0) + agg.hosts_n
        fam_chips[fam] = fam_chips.get(fam, 0.0) + agg.chips
        u = fam_used.setdefault(fam, [0.0, 0.0])
        u[0] += agg.hbm_used
        u[1] += agg.used_n
        t = fam_total.setdefault(fam, [0.0, 0.0])
        t[0] += agg.hbm_total
        t[1] += agg.total_n
    for fam in fam_chips:
        fkey = (fam,)
        b.add(schema.TPU_FLEET_FAMILY_HOSTS_REPORTING, fam_hosts[fam], fkey)
        b.add(schema.TPU_FLEET_FAMILY_CHIP_COUNT, fam_chips[fam], fkey)
        if fam_used[fam][1]:
            b.add(schema.TPU_FLEET_FAMILY_HBM_USED_BYTES,
                  fam_used[fam][0], fkey)
        if fam_total[fam][1]:
            b.add(schema.TPU_FLEET_FAMILY_HBM_TOTAL_BYTES,
                  fam_total[fam][0], fkey)

    # Multi-slice group rollups: join slices to groups via the
    # tpu_host_info membership map (BASELINE config 5). A slice without
    # a group (single-slice deployment) contributes to no group series,
    # and every sum keeps the absent-beats-fake-zero sample-count guards.
    # Membership is keyed (slice_name, accelerator) — tpu_host_info
    # carries no family — so the slice key's family element is dropped
    # for the lookup.
    groups: dict[str, _GroupAgg] = {}
    for skey, agg in slices.items():
        membership = slice_groups.get(tuple(skey)[:2])
        if membership is None:
            continue
        group, nslices_str = membership
        g = groups.get(group)
        if g is None:
            g = groups[group] = _GroupAgg()
        g.slices.add(skey)
        g.hosts_n += agg.hosts_n
        g.chips += agg.chips
        g.hbm_used += agg.hbm_used
        g.hbm_used_n += agg.used_n
        g.ici_bw += agg.ici_bw
        g.ici_n += agg.ici_n
        g.dcn_bw += agg.dcn_bw
        g.dcn_n += agg.dcn_n
        try:
            g.expected_slices = max(g.expected_slices, int(nslices_str))
        except ValueError:
            pass
    for group, g in groups.items():
        gkey = (group,)
        b.add(schema.TPU_MULTISLICE_SLICES_REPORTING, float(len(g.slices)), gkey)
        if g.expected_slices > 0:
            b.add(
                schema.TPU_MULTISLICE_EXPECTED_SLICES,
                float(g.expected_slices), gkey,
            )
        b.add(schema.TPU_MULTISLICE_HOSTS_REPORTING, float(g.hosts_n), gkey)
        b.add(schema.TPU_MULTISLICE_CHIP_COUNT, float(g.chips), gkey)
        if g.hbm_used_n:
            b.add(schema.TPU_MULTISLICE_HBM_USED_BYTES, g.hbm_used, gkey)
        if g.ici_n:
            b.add(schema.TPU_MULTISLICE_ICI_BYTES_PER_SECOND, g.ici_bw, gkey)
        if g.dcn_n:
            b.add(schema.TPU_MULTISLICE_DCN_BYTES_PER_SECOND, g.dcn_bw, gkey)

    for key, w in workloads.items():
        b.add(schema.TPU_WORKLOAD_CHIP_COUNT, w.chips, key)
        if w.hbm_used_n:  # absent beats fake-zero (advisor r4, medium)
            b.add(schema.TPU_WORKLOAD_HBM_USED_BYTES, w.hbm_used, key)
        b.add(schema.TPU_WORKLOAD_HOSTS, float(w.hosts_n), key)


def read_targets_file(path: str) -> tuple[str, ...]:
    """Parse a targets file: one ``host:port`` (or URL) per line, commas
    also accepted, ``#`` comments and blanks ignored. Deduped in order,
    same as ``--targets``. Raises OSError on an unreadable file — the
    caller decides whether that is fatal (boot) or a keep-last-known
    (reload)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    out: list[str] = []
    for line in text.split("\n"):
        line = line.split("#", 1)[0]
        for part in line.split(","):
            part = part.strip()
            if part:
                out.append(part)
    return tuple(dict.fromkeys(out))


class TargetSet:
    """Dynamic scrape-target membership plus everything keyed per target —
    circuit breakers (with optional ``persist.BreakerStateFile`` carryover)
    and parse-layout caches.

    Extracted from :class:`SliceAggregator` (which used to rebuild its
    target tuple from argv once, at construction) so that target add/remove
    no longer requires a restart, and so the sharded leaf tier
    (:class:`tpu_pod_exporter.shard.LeafAggregator`) can share it: a leaf's
    membership is ``filter_fn`` (its consistent-hash shard) applied to the
    same targets file every other leaf reads.

    Sources of membership, in precedence order:

    - ``targets_file``: one target per line (see :func:`read_targets_file`),
      re-read on :meth:`refresh` whenever its mtime changes — the live
      resharding path. An unreadable or vanished file keeps the last known
      membership (a fat-fingered ``mv`` must not empty the fleet view).
    - the static ``targets`` tuple: the classic ``--targets`` flag, used as
      the whole membership when no file is given, and as the boot fallback
      while the file is unreadable.

    Thread model: all mutation happens on the aggregator's round thread
    (``refresh()`` at round start). Concurrent readers (fleet query plane
    HTTP threads) do per-key ``get`` on the breakers dict — mutated in
    place, one GIL-atomic op at a time, never re-bound — and read the
    targets tuple, which is swapped atomically.
    """

    def __init__(
        self,
        targets: Sequence[str] = (),
        targets_file: str = "",
        filter_fn: Callable[[tuple[str, ...]], Iterable[str]] | None = None,
        breaker_failures: int = 0,
        breaker_backoff_s: float = 10.0,
        breaker_backoff_max_s: float = 120.0,
        breaker_store: Any = None,
        wallclock: Callable[[], float] = time.time,
    ) -> None:
        self._file = targets_file
        self._file_mtime: float | None = None
        self._filter = filter_fn
        self._wallclock = wallclock
        self._breaker_failures = breaker_failures
        self._breaker_backoff_s = breaker_backoff_s
        self._breaker_backoff_max_s = breaker_backoff_max_s
        self._breaker_store = breaker_store
        # Saved breaker docs from a previous process: consumed lazily as
        # targets (re)appear, so a target that reshards INTO this leaf
        # after a restart still inherits its quarantine.
        self._saved_breakers: dict[str, dict] = (
            breaker_store.load() if breaker_store is not None else {}
        )
        self._rlog = RateLimitedLogger(log)
        self._breaker_sigs: dict[str, tuple] = {}
        self.breakers: dict[str, CircuitBreaker] | None = (
            {} if breaker_failures > 0 else None
        )
        self.layouts: dict[str, LayoutCache] = {}
        self.targets: tuple[str, ...] = ()
        # Cumulative membership changes (adds + removes) — the leaf-side
        # reshard counter (tpu_leaf_reshard_moves_total).
        self.moves = 0
        base = tuple(dict.fromkeys(t.strip() for t in targets if t.strip()))
        if targets_file:
            try:
                # mtime BEFORE contents: if the file is replaced between
                # the two calls we record the OLD file's mtime against
                # its own contents and the next refresh re-reads — the
                # reverse order could pin stale membership forever (new
                # mtime recorded against old contents).
                self._file_mtime = os.path.getmtime(targets_file)
                base = read_targets_file(targets_file)
            except OSError as e:
                self._file_mtime = None
                log.warning(
                    "targets file %s unreadable at boot (%s); starting "
                    "from --targets (%d entries) until it appears",
                    targets_file, e, len(base),
                )
        self.set_targets(base)
        self.moves = 0  # boot population is not churn

    def set_targets(self, targets: Sequence[str]) -> tuple[int, int]:
        """Replace membership; returns (added, removed) counts. Per-target
        state is created for newcomers (breakers restored from the saved
        store when present) and dropped for leavers."""
        new = tuple(dict.fromkeys(t.strip() for t in targets if t.strip()))
        if self._filter is not None:
            new = tuple(self._filter(new))
        old = self.targets
        if new == old:
            return 0, 0
        old_set, new_set = set(old), set(new)
        added = [t for t in new if t not in old_set]
        removed = [t for t in old if t not in new_set]
        for t in added:
            self.layouts[t] = LayoutCache()
            if self.breakers is not None:
                br = CircuitBreaker(
                    failure_threshold=self._breaker_failures,
                    backoff_base_s=self._breaker_backoff_s,
                    backoff_max_s=self._breaker_backoff_max_s,
                )
                # pop, not get: the doc is a snapshot of a PAST state.
                # Consumed once, it must not re-quarantine this target on
                # a later remove/re-add bounce after it has RECOVERED —
                # the removal path below stashes current state for the
                # genuine bounce case.
                doc = self._saved_breakers.pop(t, None)
                if doc:
                    try:
                        br.restore_state(doc, wallclock=self._wallclock)
                    except Exception as e:  # noqa: BLE001 — never refuse to start
                        log.warning("breaker restore for %s failed: %s", t, e)
                if br.state != CLOSED:
                    log.warning(
                        "target %s restored %s (reopens=%d, next probe "
                        "in %.1fs) — quarantine carried across restart",
                        t, br.state, br.reopens, br.seconds_until_probe,
                    )
                self._breaker_sigs[t] = (br.state, br.reopens)
                self.breakers[t] = br
        for t in removed:
            self.layouts.pop(t, None)
            if self.breakers is not None:
                br = self.breakers.pop(t, None)
                if br is not None and br.state != CLOSED:
                    # Stash the live quarantine: a target that bounces out
                    # and back (partial file read, flapping inventory)
                    # must restore its backoff, not re-learn a black hole
                    # from closed. Memory-bounded by churned-target count;
                    # the on-disk file only ever holds CURRENT targets.
                    try:
                        self._saved_breakers[t] = br.export_state(
                            wallclock=self._wallclock)
                    except Exception:  # noqa: BLE001 — stash is best-effort
                        pass
            self._breaker_sigs.pop(t, None)
        self.targets = new
        self.moves += len(added) + len(removed)
        return len(added), len(removed)

    def refresh(self) -> tuple[int, int]:
        """Re-read the targets file when its mtime moved; returns (added,
        removed). Called at round start on the round thread. No file =
        static membership, always (0, 0) here."""
        if not self._file:
            return 0, 0
        try:
            mtime = os.path.getmtime(self._file)
        except OSError:
            # Vanished mid-flight: keep last known membership; it will be
            # re-read when the file reappears with a fresh mtime.
            return 0, 0
        if self._file_mtime is not None and mtime == self._file_mtime:
            return 0, 0
        try:
            targets = read_targets_file(self._file)
        except OSError as e:
            log.warning("targets file %s unreadable on reload (%s); "
                        "keeping current %d targets",
                        self._file, e, len(self.targets))
            return 0, 0
        self._file_mtime = mtime
        if not targets and self.targets:
            # A readable-but-EMPTY file on reload is overwhelmingly a torn
            # in-place write (shell `>` truncate-then-write) — not an
            # operator deleting the whole fleet. Applying it would drop
            # every breaker and empty the fleet view for a round; keep
            # the membership and wait for the next mtime bump (a genuine
            # full teardown restarts the process instead).
            log.warning(
                "targets file %s read EMPTY on reload; keeping current %d "
                "targets (truncated mid-write? restart to force empty)",
                self._file, len(self.targets),
            )
            return 0, 0
        added, removed = self.set_targets(targets)
        if added or removed:
            log.info("targets file %s reloaded: +%d/-%d targets (now %d)",
                     self._file, added, removed, len(self.targets))
        return added, removed

    def maybe_save_breakers(self, force: bool = False) -> None:
        """Persist breaker state after rounds where any breaker changed
        state/reopen count (transitions, not per-round churn — the file is
        rewritten a handful of times per incident, not 1 Hz)."""
        if self._breaker_store is None or self.breakers is None:
            return
        changed = force
        for t, br in self.breakers.items():
            sig = (br.state, br.reopens)
            if self._breaker_sigs.get(t) != sig:
                self._breaker_sigs[t] = sig
                changed = True
        if changed:
            try:
                self._breaker_store.save({
                    t: br.export_state(wallclock=self._wallclock)
                    for t, br in self.breakers.items()
                })
            except Exception as e:  # noqa: BLE001 — persistence must not fail rounds
                # Rate-limited: a full disk plus a flapping breaker would
                # otherwise emit one line per round for the whole incident.
                self._rlog.warning("breaker_save",
                                   "breaker state save failed: %s", e)


class RoundRecorder:
    """Append every round's fetched bodies to a JSONL file — the
    aggregator-side twin of the exporter's record/replay backend
    (``backend/recorded.py``): capture a live incident (a slice-wide
    rollup anomaly, a flapping target) once, replay it deterministically
    offline with :class:`ReplayFetch`. One line per round:
    ``{"t": epoch, "bodies": {target: text-or-null}, "durations": {...}}``
    — null marks a target that was down that round, so the replay
    reproduces outages too. Size note: a 256-chip body is ~950 KB, so an
    N-target capture grows ~N MB/round; record incidents, not weeks."""

    def __init__(self, path: str,
                 wallclock: Callable[[], float] = time.time) -> None:
        self._f = open(path, "a", encoding="utf-8")
        self._wallclock = wallclock

    def record(
        self, results: Iterable[tuple[str, str | None, float]],
    ) -> None:
        rec = {
            "t": self._wallclock(),
            "bodies": {t: text for t, text, _d in results},
            "durations": {t: d for t, _text, d in results},
        }
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()  # an incident capture must survive a crash/kill

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:  # noqa: BLE001
            pass


class ReplayFetch:
    """Serve recorded bodies in round order — inject as ``fetch``.

    Thread-safe for the aggregator's one-call-per-target-per-round pool:
    a second request for an already-served target advances to the next
    round. A target recorded as null raises (the round's outage replays
    as an outage); past the last round, ``loop=True`` (the
    RecordedBackend convention) starts over, else every fetch raises."""

    def __init__(self, path: str, loop: bool = True) -> None:
        self._rounds: list[dict] = []
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                    bodies = rec["bodies"]
                    if not isinstance(bodies, dict):
                        raise TypeError(
                            f"bodies must be an object, got {type(bodies).__name__}"
                        )
                except (ValueError, KeyError, TypeError) as e:
                    raise ValueError(f"{path}:{i}: bad round record: {e}") from e
                self._rounds.append(bodies)
        if not self._rounds:
            raise ValueError(f"{path}: no rounds recorded")
        self._loop = loop
        self._idx = 0
        self._served: set[str] = set()
        self._lock = threading.Lock()

    @property
    def targets(self) -> tuple[str, ...]:
        """Target set of the first round (JSON preserves recording order)."""
        return tuple(self._rounds[0])

    def __call__(self, target: str, timeout_s: float) -> str:
        with self._lock:
            if target in self._served:
                self._idx += 1
                self._served = set()
            if self._idx >= len(self._rounds):
                if not self._loop:
                    raise ConnectionError("replay exhausted")
                self._idx = 0
            bodies = self._rounds[self._idx]
            self._served.add(target)
        body = bodies.get(target)
        if body is None:
            raise ConnectionError(f"{target} recorded as down this round")
        return body


class SliceAggregator:
    """Scrape N per-host exporters, publish slice/workload rollups.

    Exposes ``poll_once`` so :class:`~tpu_pod_exporter.collector.CollectorLoop`
    can drive it on the same drift-free schedule as the exporter's own loop.
    ``fetch`` is injectable for tests (no sockets needed).
    """

    def __init__(
        self,
        targets: tuple[str, ...],
        store: SnapshotStore,
        timeout_s: float = 2.0,
        fetch: Callable[..., Any] = default_fetch,
        wallclock: Callable[[], float] = time.time,
        recorder: "RoundRecorder | None" = None,
        loop_overruns_fn: Callable[[], int] | None = None,  # CollectorLoop's
        history_fallback_window_s: float = 0.0,
        history_fetch: Callable[..., Any] = default_history_fetch,
        breaker_failures: int = 3,
        breaker_backoff_s: float = 10.0,
        breaker_backoff_max_s: float = 120.0,
        tracer: Any = None,
        breaker_store: Any = None,  # persist.BreakerStateFile; None = none
        fleet: Any = None,  # fleet.FleetQueryPlane; self-metrics land here
        shipper: Any = None,  # egress.RemoteWriteShipper; None = no egress
        targets_file: str = "",  # live membership: re-read on mtime change
        target_filter: Callable[[tuple[str, ...]], Iterable[str]] | None = None,  # leaf tier's shard cut
        render_splice: bool = True,  # --render-splice; the RUNBOOK kill switch
    ) -> None:
        if not targets and not targets_file:
            raise ValueError("aggregator needs at least one target")
        # Federated /api/v1 query plane (tpu_pod_exporter.fleet): attached
        # after construction (it shares this aggregator's breakers), it
        # serves fan-out queries on HTTP handler threads; the round loop's
        # only involvement is publishing its self-metrics and bumping
        # `rounds` — the result cache's generation, so cached envelopes
        # live exactly one round.
        self._fleet = fleet
        # Attachment seams for conditional planes that ride this tier's
        # rounds and exposition without the aggregator knowing them by
        # name (the streaming dashboard hub is the first user):
        # emit_hooks run inside _publish on the round's SnapshotBuilder;
        # round_hooks run at the very end of poll_once with the new round
        # number (poll-side cost must stay trivial — the stream hub's is
        # one Event.set on its pump).
        self.emit_hooks: list[Callable[[SnapshotBuilder], None]] = []
        self.round_hooks: list[Callable[[int], None]] = []
        # Remote-write egress (tpu_pod_exporter.egress): the aggregator
        # ships its slice/workload rollups the same WAL-buffered way the
        # exporter ships chip series — the round loop's only involvement
        # is one non-blocking enqueue after each snapshot swap plus the
        # self-metric emission (same discipline as persist/fleet).
        self._shipper = shipper
        self.rounds = 0
        # Round tracing (tpu_pod_exporter.trace): one trace per round, one
        # span per target scrape / fallback / publish. The trace context
        # propagates onto the fan-out via a traceparent header — only when
        # the injected fetch accepts one (tests inject plain 2-arg fetches;
        # ReplayFetch has no wire to stamp headers on).
        self._tracer = tracer
        self._fetch_traceparent = False
        try:
            self._fetch_traceparent = (
                "traceparent" in inspect.signature(fetch).parameters
            )
        except (TypeError, ValueError):
            pass
        self._recorder = recorder
        self._loop_overruns_fn = loop_overruns_fn
        self._store = store
        # Splice render across rounds (same machinery as the exporter
        # tier): rollup label sets are stable between target churn events,
        # so each round splices changed cells instead of re-rendering the
        # whole aggregate exposition. Same kill switch as the exporter
        # (--render-splice false), so the RUNBOOK bisection step applies
        # on every tier.
        self._prefix_cache = PrefixCache(splice=render_splice)
        self._timeout_s = timeout_s
        self._fetch = fetch
        # Missed-round continuity (0 disables): when a target's full scrape
        # fails, query its history flight recorder (/api/v1/window_stats)
        # for last-known chip data over this trailing window, so one dropped
        # round doesn't read as "half the slice vanished". The target still
        # reports down (target_up=0) — continuity is labeled, not hidden —
        # and the substitution is counted per target in
        # tpu_aggregator_history_fallbacks_total.
        self._history_window_s = history_fallback_window_s
        self._history_fetch = history_fetch
        # Per-target state lives in a TargetSet: circuit breakers
        # (tpu_pod_exporter.supervisor — a persistently-down target is
        # QUARANTINED with exponential backoff+jitter instead of costing a
        # full timeout_s in the scrape pool every round; while quarantined
        # its history fallback is skipped too; breaker_failures=0
        # disables), quarantine carryover across restarts
        # (tpu_pod_exporter.persist via breaker_store), parse-layout
        # caches (value-only re-parse between churn events — the
        # parse-side twin of the exporter's PrefixCache), and LIVE
        # membership: a --targets-file is re-read at round start whenever
        # its mtime changes, so target add/remove no longer requires a
        # restart, and the sharded leaf tier applies its consistent-hash
        # cut via target_filter.
        self._tset = TargetSet(
            targets,
            targets_file=targets_file,
            filter_fn=target_filter,
            breaker_failures=breaker_failures,
            breaker_backoff_s=breaker_backoff_s,
            breaker_backoff_max_s=breaker_backoff_max_s,
            breaker_store=breaker_store,
            wallclock=wallclock,
        )
        self._wallclock = wallclock
        self._counters = CounterStore()
        # Targets that have ever served a gpu_* family (the aggregator-side
        # twin of the collector's _gpu_surface latch): the history fallback
        # probes GPU metrics only for these, so a missed round on a
        # homogeneous TPU fleet costs zero can-only-404 requests.
        self._gpu_targets: set[str] = set()
        self._rlog = RateLimitedLogger(log)
        # Latency distributions (same contract as the exporter's: p99
        # computable from the exposition). Round durations observe after
        # the swap, so they land one round behind — fine for cumulative
        # histograms.
        self._round_hist = HistogramStore(schema.TPU_AGG_ROUND_HIST)
        self._scrape_hist = HistogramStore(schema.TPU_AGG_TARGET_SCRAPE_HIST)
        # Last round's scrape-plane health (ok, quarantined, total), read
        # by ready_detail() from HTTP threads — swapped atomically.
        self._health: tuple[int, int, int] = (0, 0, 0)
        # Cap, not current membership: ThreadPoolExecutor spawns workers
        # lazily (one per pending task up to the cap), so a 2-target
        # aggregator never creates 16 threads — while a targets-file
        # deployment that boots before the file exists still gets full
        # parallelism when the file appears (membership is LIVE; a pool
        # sized at boot would serialize the grown fleet forever).
        self._pool = ThreadPoolExecutor(
            max_workers=16,
            thread_name_prefix="tpu-agg-scrape",
        )

    # Delegating views over the TargetSet: membership and per-target state
    # are owned there; everything below reads the live view.
    @property
    def _targets(self) -> tuple[str, ...]:
        return self._tset.targets

    @property
    def _breakers(self) -> "dict[str, CircuitBreaker] | None":
        return self._tset.breakers

    @property
    def _parse_layouts(self) -> dict[str, LayoutCache]:
        return self._tset.layouts

    @property
    def targets(self) -> tuple[str, ...]:
        """Current membership (live view — changes on targets-file reload)."""
        return self._tset.targets

    @property
    def breakers(self) -> "dict[str, CircuitBreaker] | None":
        """Per-target breaker map (None when disabled) — shared read-only
        with the fleet query plane for its quarantine-aware skip. The dict
        object is stable across resharding (mutated in place), so holders
        of this reference always see current membership."""
        return self._tset.breakers

    def set_fleet(self, fleet: Any) -> None:
        """Attach the federated query plane (constructed after the
        aggregator because it borrows the breaker map built here)."""
        self._fleet = fleet

    # ------------------------------------------------------------------ round

    def poll_once(self) -> None:
        t0 = time.monotonic()
        self.rounds += 1
        # Live membership: apply a changed targets file BEFORE the round
        # snapshot, so this round already scrapes the new set. The tuple
        # read below is the round's frozen view — per-target state for
        # everything in it exists until at least the next refresh.
        _added, removed = self._tset.refresh()
        if removed:
            # Per-target counter state follows membership out: without
            # this, every target that ever errored keeps its series in
            # the exposition (and its entry in RSS) forever on a
            # churning fleet — same prune discipline as the exporter's
            # chip state.
            keep = {
                (name, (t,))
                for name in (schema.TPU_AGG_SCRAPE_ERRORS_TOTAL.name,
                             schema.TPU_AGG_HISTORY_FALLBACKS_TOTAL.name)
                for t in self._tset.targets
            }
            self._counters.prune(keep)
            self._gpu_targets &= set(self._tset.targets)
        round_targets = self._tset.targets
        tr = self._tracer.start_poll() if self._tracer is not None else None
        # Round-local quarantine set: targets whose breaker skipped the
        # scrape entirely this round (set.add is GIL-atomic; each pool
        # worker touches a distinct target exactly once).
        quarantined: set[str] = set()

        def scrape(target: str) -> tuple[str, str | None, float]:
            br = self._breakers.get(target) if self._breakers else None
            # Explicit span API (not the TLS begin/end): pool workers run
            # concurrently, and PollTrace.span/end_span are safe from any
            # thread (list.append is GIL-atomic).
            span = (
                tr.span("scrape", breaker=br.state if br is not None else "")
                if tr is not None else None
            )
            if br is not None and br.decide() == "skip":
                quarantined.add(target)
                if span is not None:
                    span.add_event(
                        f"quarantined: next probe in "
                        f"{br.seconds_until_probe:.1f}s"
                    )
                    tr.end_span(span, "skipped", target=target)
                return target, None, 0.0
            traceparent = (
                format_traceparent(tr.trace_id, span.span_id)
                if span is not None and self._fetch_traceparent
                else None
            )
            out = self._scrape_one(target, traceparent)
            if span is not None:
                tr.end_span(
                    span, "ok" if out[1] is not None else "err",
                    target=target,
                    bytes=len(out[1]) if out[1] is not None else 0,
                )
            if br is not None:
                if out[1] is None:
                    br.record_failure()
                elif br.consecutive_failures or br.state != CLOSED:
                    # Recovery bypasses the rate limit: the scrape-failure
                    # lines for this target were suppressed to one per
                    # window, and the incident's END must never be.
                    self._rlog.recovery(
                        f"scrape:{target}",
                        "target %s healthy again after %d failed scrape(s)",
                        target, br.consecutive_failures,
                    )
                    br.record_success()
                else:
                    br.record_success()
            return out

        results = list(
            self._pool.map(scrape, round_targets)
        )  # [(target, text|None, duration_s)]
        if self._recorder is not None:
            try:
                self._recorder.record(results)
            except Exception as e:  # noqa: BLE001 — capture must not kill rounds
                self._rlog.warning("recorder", "round record failed: %s", e)
        fallbacks: dict[str, list] = {}
        if self._history_window_s > 0:
            # Quarantined targets are excluded: their scrape was skipped
            # BECAUSE the endpoint is persistently dead, and the history
            # API lives on the same dead port — probing it would burn the
            # very timeout the breaker exists to save.
            failed = [
                t for t, text, _d in results
                if text is None and t not in quarantined
            ]
            if failed:

                def fallback(target: str) -> list | None:
                    span = (
                        tr.span("history_fallback") if tr is not None else None
                    )
                    samples = self._history_fallback(target)
                    if span is not None:
                        tr.end_span(
                            span, "ok" if samples else "err", target=target,
                            samples=len(samples) if samples else 0,
                        )
                    return samples

                for target, samples in zip(
                    failed, self._pool.map(fallback, failed)
                ):
                    if samples:
                        fallbacks[target] = samples
        pspan = tr.span("publish") if tr is not None else None
        self._health = (
            sum(1 for _t, text, _d in results if text is not None),
            len(quarantined),
            len(round_targets),
        )
        self._publish(results, fallbacks=fallbacks, round_started=t0,
                      quarantined=quarantined)
        if tr is not None:
            ok_n = sum(1 for _t, text, _d in results if text is not None)
            tr.end_span(pspan, "ok")
            self._tracer.finish(
                tr,
                status="ok" if ok_n else "err",
                targets=len(round_targets), ok=ok_n,
                quarantined=len(quarantined), fallbacks=len(fallbacks),
            )
        # AFTER the round's spans close: the save fsyncs twice, and disk
        # latency during an incident must not read as publish/round time —
        # the same persist-outside-the-timings discipline the exporter's
        # poll applies.
        self._maybe_save_breakers()
        for hook in self.round_hooks:
            try:
                hook(self.rounds)
            except Exception as e:  # noqa: BLE001 — a hook must never fail a round
                self._rlog.warning("round_hook",
                                   "round hook failed: %s", e)

    def _history_fallback(self, target: str) -> list | None:
        """Last-known chip data from a down target's flight recorder, as
        synthesized ``(name, labels, value)`` samples `_consume` understands.

        Gauges contribute their window-``last`` value; the ICI/DCN byte
        counters contribute their counter-aware window ``rate`` under the
        corresponding bandwidth-gauge name — the same quantity a live round
        would have folded. Any endpoint failure (exporter fully down, no
        history, pre-history version) returns None and the round proceeds
        exactly as before the fallback existed."""
        base = target_base_url(target)
        window = self._history_window_s
        samples: list[tuple[str, dict, float]] = []
        probes = [
            ("tpu_chip_info", "tpu_chip_info", False),
            ("tpu_hbm_used_bytes", "tpu_hbm_used_bytes", False),
            ("tpu_hbm_total_bytes", "tpu_hbm_total_bytes", False),
            ("tpu_tensorcore_duty_cycle_percent",
             "tpu_tensorcore_duty_cycle_percent", False),
            # Pod rollups ride along so workload continuity matches slice
            # continuity — a missed round must not read as "the workload
            # shrank" while the slice sums hold steady.
            ("tpu_pod_chip_count", "tpu_pod_chip_count", False),
            ("tpu_pod_hbm_used_bytes", "tpu_pod_hbm_used_bytes", False),
            ("tpu_ici_transferred_bytes_total",
             "tpu_ici_link_bandwidth_bytes_per_second", True),
            ("tpu_dcn_transferred_bytes_total",
             "tpu_dcn_link_bandwidth_bytes_per_second", True),
        ]
        if target in self._gpu_targets:
            # GPU-family twins, only for targets that have ever served a
            # gpu_* family: a homogeneous TPU fleet's missed rounds never
            # pay six can-only-404 probes inside the degraded window.
            probes += [
                ("gpu_chip_info", "gpu_chip_info", False),
                ("gpu_hbm_used_bytes", "gpu_hbm_used_bytes", False),
                ("gpu_hbm_total_bytes", "gpu_hbm_total_bytes", False),
                ("gpu_utilization_percent", "gpu_utilization_percent",
                 False),
                ("gpu_pod_chip_count", "gpu_pod_chip_count", False),
                ("gpu_pod_memory_used_bytes", "gpu_pod_memory_used_bytes",
                 False),
            ]
        for metric, synth_name, use_rate in probes:
            url = f"{base}/api/v1/window_stats?metric={metric}&window={window:g}"
            try:
                doc = self._history_fetch(url, self._timeout_s)
                rows = doc["data"]["result"]
            except urllib.error.HTTPError as e:
                # The endpoint ANSWERED: 404 here just means that family
                # has no samples (or a pre-history exporter) — cheap, keep
                # trying the remaining metrics; partial history beats none.
                self._rlog.info(
                    f"history:{target}:{metric}",
                    "history fallback for %s/%s unavailable: %s",
                    target, metric, e,
                )
                continue
            except Exception as e:  # noqa: BLE001
                # Connection-level failure (refused, black-holed, timeout):
                # the remaining metrics would each burn another timeout_s
                # in the scrape pool — against a black-holed target that is
                # 6x timeout per round, exactly in the outage the fallback
                # serves. One strike and out.
                self._rlog.info(
                    f"history:{target}",
                    "history fallback for %s aborted: %s", target, e,
                )
                break
            for row in rows:
                try:
                    labels = row["labels"]
                    value = row["stats"]["rate"] if use_rate else row["stats"]["last"]
                    if value is None:
                        continue
                    samples.append((synth_name, labels, float(value)))
                except (KeyError, TypeError, ValueError):
                    continue
        return samples or None

    def _scrape_one(self, target: str,
                    traceparent: str | None = None) -> tuple[str, str | None, float]:
        t0 = time.monotonic()
        try:
            if traceparent is not None:
                text = self._fetch(target, self._timeout_s,
                                   traceparent=traceparent)
            else:
                text = self._fetch(target, self._timeout_s)
        except Exception as e:  # noqa: BLE001 — a down host is data, not death
            self._rlog.warning(f"scrape:{target}", "scrape of %s failed: %s", target, e)
            return target, None, time.monotonic() - t0
        return target, text, time.monotonic() - t0

    # ---------------------------------------------------------------- publish

    def _publish(self, results: Sequence[tuple[str, str | None, float]],
                 fallbacks: dict[str, list] | None = None,
                 round_started: float | None = None,
                 quarantined: set | None = None) -> None:
        b = SnapshotBuilder(prefix_cache=self._prefix_cache)
        for spec in schema.AGGREGATE_SPECS:
            b.declare(spec)
        fallbacks = fallbacks or {}
        quarantined = quarantined or set()

        # (slice_name, accelerator, family) -> accumulator
        slices: dict[tuple[str, str, str], _SliceAgg] = {}
        workloads: dict[tuple[str, str, str], _WorkloadAgg] = {}
        # (slice_name, accelerator) -> (multislice_group, num_slices str)
        slice_groups: dict[tuple[str, str], tuple[str, str]] = {}

        for target, text, duration_s in results:
            ok = text is not None
            if ok:
                # Parse fully before folding: a mid-body ParseError must not
                # leave a half-consumed host in the sums while the target is
                # reported down. Layout-cached: steady-state bodies re-parse
                # values only (labels dicts are shared with the cache;
                # _consume reads them, never mutates).
                try:
                    samples = parse_exposition_layout(
                        text, CONSUMED_NAMES, self._parse_layouts[target]
                    )
                except ParseError as e:
                    ok = False
                    self._rlog.warning(
                        f"parse:{target}", "bad exposition from %s: %s", target, e
                    )
                else:
                    if target not in self._gpu_targets and any(
                        s[0].startswith("gpu_") for s in samples
                    ):
                        # Cheap: samples are already filtered to
                        # CONSUMED_NAMES (a handful of rows per chip).
                        self._gpu_targets.add(target)
                    self._consume(samples, slices, workloads, slice_groups)
            if not ok:
                # A quarantined round was SKIPPED, not attempted — the
                # error counter keeps meaning "failed scrapes", so the
                # breaker must not inflate it while saving timeouts.
                if target not in quarantined:
                    self._counters.inc(
                        schema.TPU_AGG_SCRAPE_ERRORS_TOTAL.name, (target,)
                    )
                fb = fallbacks.get(target)
                if fb:
                    # Missed-round continuity: the target's flight recorder
                    # answered even though its full scrape didn't; fold its
                    # last-known samples so slice chips/hosts/HBM stay
                    # continuous. target_up stays 0 — the round WAS missed.
                    self._consume(fb, slices, workloads, slice_groups)
                    self._counters.inc(
                        schema.TPU_AGG_HISTORY_FALLBACKS_TOTAL.name, (target,)
                    )
            b.add(schema.TPU_AGG_TARGET_UP, 1.0 if ok else 0.0, (target,))
            if self._breakers is not None:
                # .get: a refresh between this round's snapshot and publish
                # cannot happen (same thread), but a target REMOVED by the
                # refresh at the top of this very round still has its round
                # result here only if it was in the snapshot — guard anyway.
                br = self._breakers.get(target)
                if br is not None:
                    b.add(
                        schema.TPU_AGG_TARGET_BREAKER_STATE,
                        STATE_VALUES[br.state],
                        (target,),
                    )
            b.add(schema.TPU_AGG_SCRAPE_DURATION_SECONDS, duration_s, (target,))
            if text is not None:
                # Successful fetches only: a down target's timeout (~2 s
                # every round) would pin the pooled p99 at the top bucket
                # and mask regressions on healthy targets; failures are
                # visible via target_up / scrape_errors instead.
                self._scrape_hist.observe(duration_s)

        # One emit path for every tier: the same function the sharded
        # tree's root uses over accumulators rebuilt from leaf components,
        # so flat and sharded rollups cannot drift (shard-demo oracle).
        emit_rollups(b, slices, workloads, slice_groups, rlog=self._rlog)
        # Subclass hook (the leaf tier emits its tpu_leaf_* component
        # series here); the base aggregator adds nothing.
        self._emit_extra(b, slices, workloads, slice_groups)

        if self._fleet is not None:
            try:
                self._fleet.emit(b)
            except Exception:  # noqa: BLE001 — accounting must never fail a round
                pass
        for emit_hook in self.emit_hooks:
            try:
                emit_hook(b)
            except Exception:  # noqa: BLE001 — accounting must never fail a round
                pass
        if self._shipper is not None:
            try:
                self._shipper.emit(b)
            except Exception:  # noqa: BLE001 — accounting must never fail a round
                pass
        for lv, v in self._counters.items_for(schema.TPU_AGG_SCRAPE_ERRORS_TOTAL.name):
            b.add(schema.TPU_AGG_SCRAPE_ERRORS_TOTAL, v, lv)
        for lv, v in self._counters.items_for(
            schema.TPU_AGG_HISTORY_FALLBACKS_TOTAL.name
        ):
            b.add(schema.TPU_AGG_HISTORY_FALLBACKS_TOTAL, v, lv)
        b.add(schema.TPU_AGG_LAST_ROUND_TIMESTAMP_SECONDS, self._wallclock())
        if self._loop_overruns_fn is not None:
            try:
                b.add(
                    schema.TPU_AGG_POLL_OVERRUNS_TOTAL,
                    float(self._loop_overruns_fn()),
                )
            except Exception:  # noqa: BLE001 — accounting must never fail a round
                pass
        # Self-resource accounting, same contract as the exporter's series:
        # absent beats fake-zero when the platform can't report a value.
        cpu_s = utils.process_cpu_seconds()
        if cpu_s is not None:
            b.add(schema.TPU_AGG_CPU_SECONDS_TOTAL, cpu_s)
        rss = utils.process_rss_bytes()
        if rss is not None:
            b.add(schema.TPU_AGG_RSS_BYTES, rss)
        self._round_hist.emit(b)
        self._scrape_hist.emit(b)
        if round_started is not None:
            # One measurement for both the gauge and the histogram, so
            # histogram_quantile cross-checks against the gauge instead of
            # mysteriously exceeding it by the build+swap span.
            round_dur = time.monotonic() - round_started
            b.add(schema.TPU_AGG_ROUND_DURATION_SECONDS, round_dur)
        snap = b.build(timestamp=self._wallclock(), transfer=True)
        self._store.swap(snap)
        if self._shipper is not None:
            # AFTER the swap (the batch covers exactly what scrapers see);
            # one non-blocking queue put — a wedged receiver can never
            # stretch a round.
            try:
                self._shipper.on_snapshot(snap)
            except Exception:  # noqa: BLE001 — egress must never fail a round
                pass
        if round_started is not None:
            self._round_hist.observe(round_dur)

    @staticmethod
    def _consume(samples: Iterable[tuple[str, dict[str, str], float]],
                 slices: dict, workloads: dict,
                 slice_groups: dict) -> None:
        """Fold one host's parsed ``(name, labels, value)`` tuples into the
        round accumulators. The name dispatch is ordered by sample
        frequency — per-link ICI rows are ~60% of a 256-chip body's
        consumed lines (6 links/chip), so they test first. GPU-family
        names (``gpu_*``, backend/nvml.py) fold into the same accumulator
        slots under ``family="gpu"`` slice keys: the node's metric
        namespace IS the family marker, so one fold path serves both
        device families without ever summing across them."""
        for name, labels, value in samples:
            if name == "tpu_ici_link_bandwidth_bytes_per_second":
                agg = SliceAggregator._slice(slices, labels, "tpu")
                agg.ici_bw += value
                agg.ici_n += 1
                host = labels.get("host")
                if host:
                    agg.chip_series_hosts.add(host)
            elif name == "tpu_chip_info" or name == "gpu_chip_info":
                # The one guaranteed per-chip series (round 4: a chip whose
                # HBM is unreadable publishes NO tpu_hbm_* series, so chip
                # presence and hosts_reporting must not key off those).
                # Presence intentionally keys on chip_info ALONE: exporters
                # have published it unconditionally since the same change,
                # and a dual-source count (chip_info OR hbm series) would
                # risk double-counting; mixed fleets older than that are
                # not supported.
                agg = SliceAggregator._slice(slices, labels, name[:3])
                agg.chips += 1
                # A missing host label must not count as host "" — mixed
                # with exporters that omit the label, all such hosts would
                # collapse into one and undercount hosts_reporting. The
                # sample still contributes to the chip count above.
                host = labels.get("host")
                if host:
                    agg.hosts.add(host)
            elif name == "tpu_hbm_used_bytes" or name == "gpu_hbm_used_bytes":
                agg = SliceAggregator._slice(slices, labels, name[:3])
                agg.hbm_used += value
                agg.used_chips.add(SliceAggregator._chip_key(labels))
                host = labels.get("host")
                if host:
                    agg.chip_series_hosts.add(host)
            elif name == "tpu_hbm_total_bytes" or name == "gpu_hbm_total_bytes":
                agg = SliceAggregator._slice(slices, labels, name[:3])
                agg.hbm_total += value
                agg.total_chips.add(SliceAggregator._chip_key(labels))
                host = labels.get("host")
                if host:
                    agg.chip_series_hosts.add(host)
            elif name in ("tpu_tensorcore_duty_cycle_percent",
                          "gpu_utilization_percent"):
                agg = SliceAggregator._slice(slices, labels, name[:3])
                agg.duty_sum += value
                agg.duty_n += 1
                host = labels.get("host")
                if host:
                    agg.chip_series_hosts.add(host)
            elif name == "tpu_dcn_link_bandwidth_bytes_per_second":
                agg = SliceAggregator._slice(slices, labels, "tpu")
                agg.dcn_bw += value
                agg.dcn_n += 1
                host = labels.get("host")
                if host:
                    agg.chip_series_hosts.add(host)
            elif name == "tpu_host_info":
                # Multi-slice membership join key: slice -> (group,
                # expected slice count). Hosts of one slice agree on both
                # (same MEGASCALE env); last writer wins harmlessly.
                group = labels.get("multislice_group", "")
                if group:
                    key = (
                        labels.get("slice_name", ""),
                        labels.get("accelerator", ""),
                    )
                    slice_groups[key] = (group, labels.get("num_slices", ""))
            elif name in ("tpu_pod_chip_count", "tpu_pod_hbm_used_bytes",
                          "gpu_pod_chip_count", "gpu_pod_memory_used_bytes"):
                pod = labels.get("pod", "")
                if not pod:
                    continue
                # Workload rollups stay family-agnostic (a pod's chips are
                # one family — slices are homogeneous node pools), so both
                # namespaces fold into the same tpu_workload_* keys.
                key = (pod, labels.get("namespace", ""), labels.get("slice_name", ""))
                w = workloads.get(key)
                if w is None:
                    w = workloads[key] = _WorkloadAgg()
                if name.endswith("_pod_chip_count"):
                    w.chips += value
                    host = labels.get("host")
                    if host:  # same missing-label rule as hosts_reporting
                        w.hosts.add(host)
                else:
                    w.hbm_used += value
                    w.hbm_used_n += 1

    @staticmethod
    def _chip_key(labels: dict[str, str]) -> tuple[str, str]:
        """Chip identity within a slice, for used/total coverage matching."""
        return labels.get("host", ""), labels.get("chip_id", "")

    @staticmethod
    def _slice(slices: dict, labels: dict[str, str],
               family: str = "tpu") -> _SliceAgg:
        key = (labels.get("slice_name", ""), labels.get("accelerator", ""),
               family)
        agg = slices.get(key)
        if agg is None:
            agg = slices[key] = _SliceAgg()
        return agg

    def ready_detail(self) -> dict:
        """/readyz detail hook (``server.MetricsServer ready_detail_fn``):
        an aggregator (or sharded leaf) whose ENTIRE scrape plane went
        dark keeps serving its last snapshot over HTTP 200 — stale data
        is still data — but flips ``state`` to ``degraded`` so operators
        and rollouts can tell "healthy view" from "partition-suspected
        view". Per-round detail is included either way."""
        ok, quarantined, total = self._health
        out: dict = {
            "scrape_plane": {
                "targets_ok": ok,
                "quarantined": quarantined,
                "targets": total,
            },
        }
        if total and ok == 0 and self.rounds > 0:
            out["degraded_sources"] = [
                f"scrape-plane: 0/{total} targets reachable "
                f"({quarantined} quarantined) — serving the last "
                f"snapshot; node-side network partition suspected"
            ]
        return out

    def debug_vars(self) -> dict:
        """Introspection payload for /debug/vars — the aggregator twin of
        ExporterApp._debug_vars. Reads are cross-thread but safe: layout
        lists are swapped atomically by the publish thread."""
        tmpl = self._prefix_cache.template
        return {
            "targets": list(self._targets),
            "timeout_s": self._timeout_s,
            "rounds": self.rounds,
            # Splice-render counters (None = --render-splice false); the
            # RUNBOOK's render triage reads the same shape on every tier.
            "render": tmpl.stats() if tmpl is not None else None,
            # Cumulative membership changes (targets-file reloads / leaf
            # resharding); 0 forever on a static --targets deployment.
            "target_moves": self._tset.moves,
            # Federated query plane occupancy (None = fleet queries off).
            "fleet_query": (
                self._fleet.stats() if self._fleet is not None else None
            ),
            # Remote-write egress occupancy (None = egress off).
            "egress": (
                self._shipper.stats() if self._shipper is not None else None
            ),
            # Round-trace ring occupancy (None = tracing off); the traces
            # themselves are at GET /debug/trace.
            "trace": (
                self._tracer.store.stats() if self._tracer is not None
                else None
            ),
            # Per-target parsed-layout sizes: 0 = never parsed (target down
            # since start) OR deliberately uncached (oversize body — see
            # layout_oversize below); steady state ≈ body line count.
            "layout_entries": {
                t: len(layout.entries)
                for t, layout in self._parse_layouts.items()
            },
            # True while a target's body exceeds the layout-cache cap: it
            # parses uncached every round (healthy, just slower); cleared
            # when the body shrinks back under the cap. Without this an
            # operator reading layout_entries=0 would misdiagnose an
            # oversize target as down after the WARNING scrolled away.
            "layout_oversize": {
                t: layout.oversize_logged
                for t, layout in self._parse_layouts.items()
            },
            # Per-target breaker view (None = breakers disabled): state plus
            # how long until a quarantined target's next probe.
            "target_breakers": (
                {
                    t: {
                        "state": br.state,
                        "consecutive_failures": br.consecutive_failures,
                        "reopens": br.reopens,
                        "next_probe_in_s": round(br.seconds_until_probe, 3),
                    }
                    for t, br in self._breakers.items()
                }
                if self._breakers is not None
                else None
            ),
        }

    def _emit_extra(self, b: SnapshotBuilder, slices: dict,
                    workloads: dict, slice_groups: dict) -> None:
        """Subclass hook, called once per round after the rollups landed on
        the builder and before the self-metrics: the sharded leaf tier
        (tpu_pod_exporter.shard.LeafAggregator) emits its accumulator
        component series here. Base aggregator: nothing."""

    def _maybe_save_breakers(self, force: bool = False) -> None:
        """Persist target breaker state on transitions (owned by the
        TargetSet, which also restores it for targets that reshard in)."""
        self._tset.maybe_save_breakers(force=force)

    def close(self) -> None:
        self._maybe_save_breakers(force=True)
        self._pool.shutdown(wait=False)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpu-pod-exporter-aggregate",
        description="Scrape per-host TPU exporters; serve slice-level rollups.",
    )
    p.add_argument("--targets", default="",
                   help="comma-separated host:port (or URL) exporter targets")
    p.add_argument("--targets-file", default="",
                   help="file with one target per line (# comments ok), "
                        "re-read at round start whenever its mtime changes "
                        "— target add/remove without a restart. Takes "
                        "precedence over --targets, which then only seeds "
                        "membership while the file is unreadable")
    p.add_argument("--port", type=int, default=9100)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--interval-s", type=float, default=5.0)
    p.add_argument("--timeout-s", type=float, default=2.0)
    p.add_argument("--max-scrapes-per-s", type=float, default=100.0,
                   help="rate-cap own /metrics (token bucket; 0 disables)")
    p.add_argument("--debug-addr", default="127.0.0.1",
                   help="/debug/* exposure: loopback clients only by "
                        "default; 0.0.0.0 serves them to any client "
                        "(same policy as the exporter's --debug-addr)")
    p.add_argument("--breaker-failures", type=int, default=3,
                   help="consecutive scrape failures before a target is "
                        "quarantined with backoff instead of burning "
                        "--timeout-s every round (0 disables the breaker)")
    p.add_argument("--breaker-backoff-s", type=float, default=0.0,
                   help="first quarantine window; doubles per reopen "
                        "(default 0 = auto: max(2x --interval-s, "
                        "--timeout-s))")
    p.add_argument("--breaker-backoff-max-s", type=float, default=120.0)
    p.add_argument("--state-dir", default="",
                   help="persist per-target breaker state here (atomic "
                        "JSON) so a restarted aggregator keeps its "
                        "quarantines instead of re-learning every dead "
                        "target from closed; empty disables")
    p.add_argument("--render-splice", default="on", choices=("on", "off"),
                   help="incremental exposition render (splice changed "
                        "cells into a pre-rendered body template per "
                        "round); off restores the per-family full "
                        "re-render — the RUNBOOK's bisection step, same "
                        "switch as the exporter tier")
    p.add_argument("--trace", default="on", choices=("on", "off"),
                   help="round tracing: one trace per aggregation round "
                        "with per-target scrape spans, exported at "
                        "/debug/trace; the trace context propagates to "
                        "each exporter via a traceparent header")
    p.add_argument("--trace-max-traces", type=int, default=256)
    p.add_argument("--history-fallback-window", type=float, default=0.0,
                   help="when a target's scrape fails, query its history "
                        "flight recorder (/api/v1/window_stats) over this "
                        "trailing window and fold the last-known chip data "
                        "into the rollups (0 disables; try 3x --interval-s)")
    p.add_argument("--fleet-query", default="on", choices=("on", "off"),
                   help="federated /api/v1 on this aggregator: "
                        "query_range/window_stats/series fan out to every "
                        "non-quarantined target, merge per series, and "
                        "answer with partial-result semantics (a dead "
                        "target degrades the answer, never fails it)")
    p.add_argument("--fleet-query-timeout-s", type=float, default=0.0,
                   help="per-target deadline for fleet query fan-out "
                        "(default 0 = use --timeout-s)")
    p.add_argument("--fleet-query-cache", type=int, default=128,
                   help="fleet query result cache entries, keyed by "
                        "(query, grid, round generation) — absorbs "
                        "dashboard-refresh traffic (0 disables)")
    p.add_argument("--stream", default="on", choices=("on", "off"),
                   help="/api/v1/stream subscriptions over the fleet "
                        "query plane: viewers register a query once and "
                        "receive per-round deltas (SSE + long-poll "
                        "fallback) instead of re-polling; requires "
                        "--fleet-query on")
    p.add_argument("--stream-max-subscribers", type=int, default=10000,
                   help="admission cap on live stream subscriptions "
                        "(past it: 429, retry against a read replica)")
    p.add_argument("--stream-heartbeat-s", type=float, default=10.0,
                   help="stream heartbeat cadence while rounds are "
                        "quiet; 0 disables")
    p.add_argument("--stream-full-sync-s", type=float, default=60.0,
                   help="periodic full-answer frames on every stream "
                        "(delta-only streams rot); 0 disables")
    p.add_argument("--memory-budget-mb", type=float, default=0.0,
                   help="memory budget over the serving components "
                        "(fleet query result cache, stream hub retained "
                        "answers): past it the pressure ladder sheds "
                        "the cache first, then the oldest stream "
                        "subscriptions (counted). 0 = no budget")
    p.add_argument("--egress-url", default="",
                   help="Prometheus remote-write receiver: push the slice/"
                        "workload rollups there, WAL-buffered (empty "
                        "disables — same contract as the exporter's "
                        "--egress-url)")
    p.add_argument("--egress-dir", default="aggregator-egress",
                   help="durable send-buffer directory for --egress-url")
    p.add_argument("--egress-interval-s", type=float, default=0.0,
                   help="min seconds between egress batches (0 = every "
                        "round)")
    p.add_argument("--egress-max-backlog-mb", type=float, default=64.0)
    p.add_argument("--egress-max-backlog-age-s", type=float, default=3600.0)
    p.add_argument("--egress-timeout-s", type=float, default=5.0)
    p.add_argument("--egress-breaker-failures", type=int, default=3)
    p.add_argument("--egress-breaker-backoff-s", type=float, default=1.0)
    p.add_argument("--egress-breaker-backoff-max-s", type=float, default=60.0)
    p.add_argument("--log-level", default="info")
    p.add_argument("--log-format", default="text", choices=("text", "json"),
                   help="json = one Cloud-Logging-shaped object per line")
    p.add_argument("--record-to", default="",
                   help="append every round's fetched bodies to this JSONL "
                        "file (incident capture; ~1 MB/target/round)")
    p.add_argument("--replay-from", default="",
                   help="serve recorded rounds instead of scraping HTTP "
                        "(loops at end); with --targets '-', targets come "
                        "from the recording")
    ns = p.parse_args(argv)
    utils.setup_logging(ns.log_level, ns.log_format)

    fetch = default_fetch
    if ns.replay_from:
        fetch = ReplayFetch(ns.replay_from)
    elif ns.targets.strip() == "-":
        p.error("--targets - (targets from recording) requires --replay-from")
    recorder = RoundRecorder(ns.record_to) if ns.record_to else None
    # Dedup, order-preserved: a doubled target would fold its chips into
    # the rollups twice on the live path and corrupt ReplayFetch's
    # advance-on-repeat round tracking on the replay path.
    targets = tuple(dict.fromkeys(
        t.strip() for t in ns.targets.split(",") if t.strip()
    ))
    if ns.replay_from and targets == ("-",):
        targets = fetch.targets
    if not targets and not ns.targets_file:
        p.error("one of --targets / --targets-file is required")
    store = SnapshotStore()
    trace_store = tracer = None
    if ns.trace == "on":
        from tpu_pod_exporter.trace import Tracer, TraceStore

        # No slow-poll sampler on the aggregator: a slow round is already
        # attributed by its per-target scrape spans (the scrape pool, not
        # the round thread, is where the time goes).
        trace_store = TraceStore(max_traces=ns.trace_max_traces)
        tracer = Tracer(trace_store, slow_poll_s=0.0, root_name="round")
    breaker_backoff_s = (
        ns.breaker_backoff_s if ns.breaker_backoff_s > 0
        else max(2.0 * ns.interval_s, ns.timeout_s)
    )
    breaker_store = None
    if ns.state_dir:
        import os

        from tpu_pod_exporter.persist import BreakerStateFile

        breaker_store = BreakerStateFile(
            os.path.join(ns.state_dir, "aggregator-breakers.json")
        )
    shipper = None
    if ns.egress_url:
        from tpu_pod_exporter.egress import (
            RemoteWriteShipper,
            aggregator_egress_metrics,
            build_breaker,
        )

        shipper = RemoteWriteShipper(
            ns.egress_url,
            ns.egress_dir,
            metrics=aggregator_egress_metrics(),
            interval_s=ns.egress_interval_s,
            timeout_s=ns.egress_timeout_s,
            max_backlog_mb=ns.egress_max_backlog_mb,
            max_backlog_age_s=ns.egress_max_backlog_age_s,
            breaker=build_breaker(
                ns.egress_breaker_failures,
                ns.egress_breaker_backoff_s,
                ns.egress_breaker_backoff_max_s,
            ),
        )
        shipper.load()
        shipper.start()
    agg = SliceAggregator(
        targets, store, timeout_s=ns.timeout_s, fetch=fetch, recorder=recorder,
        # Late-bound closure (the loop is constructed just below; the
        # exporter wires its collector the same way, app.py): overruns
        # surface as tpu_aggregator_poll_overruns_total.
        loop_overruns_fn=lambda: loop.overruns,
        history_fallback_window_s=ns.history_fallback_window,
        breaker_failures=ns.breaker_failures,
        # Auto backoff tracks the round cadence: the first quarantine skips
        # about one round, growing from there; never below the scrape
        # timeout (probing faster than a timeout resolves is pointless).
        breaker_backoff_s=breaker_backoff_s,
        # The ceiling must admit the base (huge --interval-s setups).
        breaker_backoff_max_s=max(ns.breaker_backoff_max_s, breaker_backoff_s),
        tracer=tracer,
        breaker_store=breaker_store,
        shipper=shipper,
        targets_file=ns.targets_file,
        render_splice=ns.render_splice == "on",
    )
    fleet = None
    if ns.fleet_query == "on":
        from tpu_pod_exporter.fleet import FleetQueryPlane

        # Fleet query traces share the round-trace ring under their own
        # root name, so /debug/trace shows rounds and queries side by side.
        query_tracer = None
        if trace_store is not None:
            from tpu_pod_exporter.trace import Tracer

            query_tracer = Tracer(trace_store, slow_poll_s=0.0,
                                  root_name="query")
        fleet = FleetQueryPlane(
            agg.targets,
            timeout_s=(ns.fleet_query_timeout_s
                       if ns.fleet_query_timeout_s > 0 else ns.timeout_s),
            breakers=agg.breakers,
            tracer=query_tracer,
            cache_entries=ns.fleet_query_cache,
            # Cache generation = round counter: one fan-out per query per
            # round, however many dashboard panels refresh.
            generation_fn=lambda: agg.rounds,
            # Live membership: a --targets-file reload changes agg.targets
            # between rounds; each query snapshots the current view.
            targets_fn=lambda: agg.targets,
        )
        agg.set_fleet(fleet)
    hub = pump = None
    if ns.stream == "on" and fleet is not None:
        from tpu_pod_exporter.stream import attach_stream

        hub, pump = attach_stream(
            agg, fleet,
            heartbeat_s=ns.stream_heartbeat_s,
            full_sync_s=ns.stream_full_sync_s,
            max_subscribers=ns.stream_max_subscribers,
        )
    governor = None
    if ns.memory_budget_mb > 0:
        from tpu_pod_exporter.pressure import build_serving_governor

        # Serving-tier memory ladder: result cache sheds first, oldest
        # stream subscriptions last (stream_shed rung, counted).
        governor = build_serving_governor(
            int(ns.memory_budget_mb * (1 << 20)),
            sidecar_dir=ns.state_dir,
            cache_plane=fleet, hub=hub,
        )
    loop = CollectorLoop(agg, interval_s=ns.interval_s)
    server = MetricsServer(
        store, host=ns.host, port=ns.port,
        health_max_age_s=max(10.0 * ns.interval_s, 10.0),
        max_scrapes_per_s=ns.max_scrapes_per_s,
        debug_vars=agg.debug_vars,
        debug_addr=ns.debug_addr,
        trace=trace_store,
        fleet=fleet,
        # Partition-aware readiness: all-targets-dark flips /readyz to
        # state=degraded (still HTTP 200 — the stale view keeps serving).
        ready_detail_fn=agg.ready_detail,
        stream_hub=hub,
    )

    stop = threading.Event()

    def _on_signal(signum: int, frame: object) -> None:  # noqa: ARG001
        log.info("signal %d: draining", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    agg.poll_once()  # synchronous first round so /readyz flips immediately
    loop.start()
    server.start()
    log.info("aggregating %d targets on :%d every %.1fs",
             len(targets), server.port, ns.interval_s)
    stop.wait()
    loop.stop()
    server.stop()
    if pump is not None:
        pump.close()
    if hub is not None:
        hub.close()
    if governor is not None:
        governor.close()
    if fleet is not None:
        fleet.close()
    if shipper is not None:
        shipper.close()
    agg.close()
    if recorder is not None:
        recorder.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
