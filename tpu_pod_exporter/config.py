"""Configuration — flags + environment, with sane defaults.

The reference hardcodes everything: port ``:8000`` (``main.go:71``), 30 s
interval (``main.go:156``), all-namespaces scope (``main.go:77``), metric
names (``main.go:24,31``). Here every knob is a flag with an ``TPE_*``
environment fallback, and backend/attribution sources are selectable at
startup — the fake backends must be reachable from the command line for the
0-device smoke config (SURVEY.md §5 "Config / flag system").
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, fields
from typing import Any


@dataclass
class ExporterConfig:
    port: int = 8000
    host: str = "0.0.0.0"
    interval_s: float = 1.0
    backend: str = "auto"          # auto | fake | jax | libtpu | recorded | nvml
    attribution: str = "auto"      # auto | fake | podresources | checkpoint | none
    resource_name: str = "google.com/tpu"
    # Kubelet resource name GPU-family backends join attribution on (the
    # nvidia device plugin advertises GPUs by UUID under this name); used
    # in place of --resource-name when the backend family is "gpu".
    gpu_resource_name: str = "nvidia.com/gpu"
    fake_chips: int = 0            # chip count when backend=fake
    # Simulated NVML driver (backend=nvml without an NVIDIA driver): GPU
    # count for the default scripted tables. 0 = use the real pynvml
    # binding (or --nvml-sim-spec).
    nvml_sim_gpus: int = 0
    # JSON spec for the simulated NVML driver (per-GPU memory/utilization/
    # process tables + injectable NVML error codes — see
    # backend/nvml.py:sim_driver_from_spec). Wins over --nvml-sim-gpus.
    nvml_sim_spec: str = ""
    recording_path: str = ""       # JSONL trace to replay when backend=recorded
    record_to: str = ""            # if set, record every poll's samples here
    podresources_socket: str = "/var/lib/kubelet/pod-resources/kubelet.sock"
    checkpoint_path: str = "/var/lib/kubelet/device-plugins/kubelet_internal_checkpoint"
    # UID→(name, namespace) source for the checkpoint fallback, so it can
    # emit real pod names instead of pod="uid:<uid>". File wins if both set.
    uid_map_file: str = ""         # static JSON {"<uid>": {"name","namespace"}}
    kubelet_pods_url: str = ""     # e.g. https://127.0.0.1:10250/pods
    kubelet_token_file: str = ""   # bearer token (default SA token if https)
    kubelet_ca_file: str = ""      # CA bundle; unset = skip verify (node-local)
    # Explicit opt-in to sending the bearer token over UNVERIFIED https —
    # without it, token+https+no-CA refuses at startup (credential safety).
    kubelet_insecure_tls: bool = False
    kubelet_pods_refresh_s: float = 30.0
    libtpu_metrics_addr: str = "localhost:8431"
    attribution_max_stale_s: float = 30.0
    # Source supervision (tpu_pod_exporter.supervisor): hard per-phase
    # deadline for device/attribution/process-scan reads. A call that
    # exceeds it is ABANDONED (worker thread fenced off, phase degrades as
    # an error) instead of parking the poll loop inside a wedged gRPC
    # channel or hung /proc read. Default is 2x the longest source RPC
    # timeout (podresources timeout_s=2.0): a healthy-but-slow call gets
    # twice its own budget before being declared wedged. 0 disables
    # supervision entirely (direct in-thread calls, pre-supervision
    # behaviour).
    phase_deadline_s: float = 4.0
    # Circuit breaker per source: this many CONSECUTIVE failures (errors or
    # deadline abandonments) open the breaker; while open, the phase is
    # skipped (degrading as an error) until an exponential backoff+jitter
    # window elapses, then a single half-open probe runs close()+re-open()
    # on the source — a wedged channel is replaced, not retried into.
    # 0 disables the breaker (phase deadlines still apply), matching the
    # aggregator's --breaker-failures contract.
    breaker_failures: int = 3
    breaker_backoff_s: float = 1.0       # first open window; doubles per reopen
    breaker_backoff_max_s: float = 30.0  # backoff ceiling
    # Deterministic fault injection (tpu_pod_exporter.chaos) — TEST ONLY.
    # e.g. "hang:device:0.01,err:attribution:0.05,slow:procscan:500ms";
    # empty = disabled. Injection schedules are reproducible per
    # (spec, chaos_seed).
    chaos_spec: str = ""
    chaos_seed: int = 0
    # End-to-end poll tracing (tpu_pod_exporter.trace): every poll becomes
    # a trace with one span per phase, retained in a bounded in-memory ring
    # and exported as Chrome trace_event JSON via GET /debug/trace
    # (loopback-only by default, like every /debug/* route). On by default —
    # the measured poll-loop overhead budget is <5% (make trace-overhead);
    # --trace off restores the untraced poll path exactly.
    trace: bool = True
    # Slow-poll profiler: a poll running past this many seconds gets its
    # poll thread's (and any supervised worker's) Python stack sampled at
    # ~50 Hz for the remainder of the poll; the collapsed stacks attach to
    # the trace. 0 disables the profiler (spans still recorded).
    trace_slow_poll_s: float = 1.0
    # Bounded trace ring: oldest trace evicted past this many (same
    # hard-bound discipline as --history-max-series).
    trace_max_traces: int = 256
    # /metrics concurrency cap: excess scrapers queue briefly then get 429
    # (0 disables). Protects the TPU host's cores from scrape storms.
    max_concurrent_scrapes: int = 4
    # /metrics rate cap (token bucket, burst 2×; 0 disables): each full-body
    # scrape at 256 chips costs ~0.4 ms of pure kernel-copy CPU, so a storm
    # of them must be refused, not served. 100/s is ~20× any sane setup
    # (a few Prometheus replicas + an aggregator at 1 Hz).
    max_scrapes_per_s: float = 100.0
    # Flight-recorder history (tpu_pod_exporter.history): how far back the
    # node-local /api/v1/* query endpoints can answer. 0 disables history
    # entirely (no store, endpoints 404). Per-series ring capacity is
    # retention / interval (capped at 4096 samples); worst-case memory is
    # history_max_series x capacity x 24 bytes (~59 MB at defaults, only
    # if the series cap is actually reached).
    history_retention_s: float = 300.0
    # Hard cap on stored series; the least-recently-updated series is
    # evicted beyond it (tpu_exporter_history_evicted_series_total). Sized
    # above a 256-chip host's tracked set (~4.4k: 5 per-chip gauges + 2
    # counters x 6 ICI links + pod rollups) so the worst supported shape
    # never thrashes; memory is allocated per series actually present
    # (~32 MB at 256 chips, ~0.6 MB on a v4-8 host).
    history_max_series: int = 8192
    # Multi-resolution downsample tiers behind the raw history ring:
    # comma-separated step:capacity pairs (seconds:buckets). Each bucket
    # folds counter-aware min/max/mean/first/last, so query_range answers
    # hours-old ranges at 10 s/60 s resolution from the same bounded store
    # (~48x the raw retention at the defaults, ~4x per-series memory —
    # still hard-bounded by --history-max-series). "off" disables tiering
    # (raw-ring-only, the pre-tier behaviour).
    history_tiers: str = "10:60,60:240"
    # Crash-safe state persistence (tpu_pod_exporter.persist): directory
    # for the checksummed checkpoint + write-ahead log covering history
    # rings, breaker state, and the last published exposition. On boot the
    # exporter replays it (torn-write tolerant — a corrupt record truncates,
    # never refuses to start) and serves the restored exposition
    # immediately (warm start). Empty (the default) cleanly disables the
    # whole layer. In the DaemonSet, point it at a hostPath so state
    # survives pod replacement, e.g. /var/lib/tpu-pod-exporter.
    state_dir: str = ""
    # Checkpoint cadence: full state (history + breakers + exposition) is
    # rewritten atomically (write-temp, fsync, rename) this often; the WAL
    # resets after each checkpoint, bounding both restore time and WAL
    # growth.
    state_snapshot_interval_s: float = 60.0
    # WAL fsync cadence: a crash loses at most this much of the history
    # tail (plus the in-flight poll). 0 = fsync every record — the
    # strongest guarantee, affordable on local SSD (make
    # persist-fsync-check measures it).
    state_fsync_interval_s: float = 5.0
    # Remote-write egress (tpu_pod_exporter.egress): push the tracked
    # metric families to a Prometheus remote-write receiver, batched per
    # snapshot swap (delta-aware), snappy-compressed, buffered through a
    # crash-safe on-disk WAL so a receiver outage or a restart drops
    # nothing. Empty (the default) disables the whole layer.
    egress_url: str = ""
    # Durable send-buffer directory (CRC-framed segments + fsynced ack
    # cursor). Required when --egress-url is set; in the DaemonSet point
    # it at a hostPath so the backlog survives pod replacement.
    egress_dir: str = "/var/lib/tpu-pod-exporter/egress"
    # Minimum seconds between egress batches: snapshots arriving faster
    # are skipped (not buffered). 0 ships every poll.
    egress_interval_s: float = 1.0
    # Backlog caps while the receiver is unreachable: oldest batches are
    # dropped (counted in tpu_exporter_egress_dropped_total{reason=
    # "backlog"}) past either bound — bounded loss by explicit policy,
    # never unbounded disk growth.
    egress_max_backlog_mb: float = 64.0
    egress_max_backlog_age_s: float = 3600.0
    # Per-send HTTP deadline: a hanging receiver costs the SENDER thread
    # at most this long per attempt; the poll path never waits on egress.
    egress_timeout_s: float = 5.0
    # Receiver circuit breaker (same contract as the source breakers):
    # this many consecutive send failures (timeout/connection/5xx/429)
    # open it; while open, batches buffer to disk and a half-open probe
    # sends a single batch after expo backoff + jitter. 0 disables the
    # breaker (every batch attempted immediately).
    egress_breaker_failures: int = 3
    egress_breaker_backoff_s: float = 1.0
    egress_breaker_backoff_max_s: float = 60.0
    # Resource-pressure governor (tpu_pod_exporter.pressure): byte budget
    # across --state-dir + --egress-dir. Past it (or on any reported
    # ENOSPC/EDQUOT) the disk degradation ladder sheds by policy — WAL
    # thinning, egress compaction/backlog trim, checkpoint halving, WAL
    # off — and recovers rung by rung with hysteresis when space returns.
    # 0 = no byte budget (the ladder still reacts to reported ENOSPC).
    state_max_disk_mb: float = 0.0
    # Memory budget over the byte-accounted in-memory components (history
    # rings, trace ring, fleet query cache): past it the memory ladder
    # sheds coarse-tiers-last — fleet cache off, trace ring halved, raw
    # history rings cut. 0 disables the memory ladder entirely.
    memory_budget_mb: float = 0.0
    # Scrape-storm admission control: hard cap on concurrently OPEN
    # connections (each costs a file descriptor and loop bookkeeping,
    # even on the event loop); over-cap connections
    # get the pre-rendered 429 + Retry-After and are closed — except
    # /healthz + /readyz, which always answer. 0 disables.
    max_open_connections: int = 256
    # Per-client-IP concurrent-request cap (one aggressive scraper must
    # not monopolize the scrape/api fences for everyone else); same 429 +
    # probe-path exemption. 0 disables.
    max_requests_per_client: int = 32
    # Slow-client write defense: per-connection WRITE-PROGRESS deadline
    # on the event loop. A scraper that stalls mid-body (stuck TCP peer,
    # frozen pipe, trickle reader) makes zero write progress for this
    # many seconds and gets its connection dropped; counted in
    # tpu_exporter_client_write_timeouts_total. 0 disables. Write-only:
    # idle keep-alive connections between scrapes are unaffected, and a
    # slowly-draining client stays alive as long as bytes keep moving.
    client_write_timeout_s: float = 10.0
    # Event-loop server worker pool cap: requests that may block (an
    # uncached render, /api/v1 queries, /debug serialization) run on an
    # elastic pool of at most this many threads; the cached-bytes scrape
    # hot path never leaves the loop. The steady state is 0-1 workers —
    # this bounds the worst case (a storm of uncacheable requests), not
    # the common one.
    server_max_workers: int = 8
    # Incremental exposition render: keep a pre-rendered byte template
    # keyed by the series-layout generation and splice only changed value
    # cells per poll (plus per-encoding gzip/OpenMetrics caches invalidated
    # by splice). false restores the per-family full re-render.
    render_splice: bool = True
    # /debug/* exposure: by default debug endpoints only answer loopback
    # clients (run curl on the node). "0.0.0.0" serves them to any client
    # (the pre-round-5 behaviour); the metrics/health/api endpoints are
    # unaffected.
    debug_addr: str = "127.0.0.1"
    process_metrics: bool = False  # procfs scan: which host pids hold which chips
    proc_root: str = "/proc"       # injectable for tests / sidecar mounts
    process_full_scan_every: int = 10  # polls between full /proc walks
    legacy_metrics: bool = False   # also emit the reference's gpu_* metric names
    accelerator: str = ""          # override TPU_ACCELERATOR_TYPE
    slice_name: str = ""
    node_name: str = ""
    worker_id: str = ""
    # Multi-slice group identity override (else MEGASCALE_COORDINATOR_ADDRESS
    # from the GKE multi-slice environment); rides tpu_host_info, never
    # per-chip series.
    multislice_group: str = ""
    log_level: str = "info"
    # "text" (human console) or "json": one JSON object per line with a
    # `severity` field — the shape GKE's Cloud Logging agent parses
    # natively, so exporter WARNINGs become filterable log entries instead
    # of opaque text blobs.
    log_format: str = "text"

    @staticmethod
    def _env_default(name: str, fallback: Any) -> Any:
        raw = os.environ.get(f"TPE_{name.upper()}")
        if raw is None:
            return fallback
        if isinstance(fallback, bool):
            return raw.lower() in ("1", "true", "yes", "on")
        if isinstance(fallback, int):
            return int(raw)
        if isinstance(fallback, float):
            return float(raw)
        return raw

    @classmethod
    def from_args(cls, argv: list[str] | None = None) -> "ExporterConfig":
        defaults = cls()
        p = argparse.ArgumentParser(
            prog="tpu-pod-exporter",
            description="TPU-native per-pod device-metrics exporter for Kubernetes.",
        )
        for f in fields(cls):
            flag = "--" + f.name.replace("_", "-")
            base = getattr(defaults, f.name)
            default = cls._env_default(f.name, base)
            if isinstance(base, bool):
                # argparse type=bool is a trap: bool("false") is True. And a
                # typo ("--legacy-metrics on") must fail loudly, not parse
                # as False.
                def parse_bool(s: str) -> bool:
                    low = s.lower()
                    if low in ("1", "true", "yes", "on"):
                        return True
                    if low in ("0", "false", "no", "off"):
                        return False
                    raise argparse.ArgumentTypeError(
                        f"expected true/false, got {s!r}"
                    )

                p.add_argument(
                    flag, type=parse_bool, default=default, nargs="?", const=True
                )
            else:
                p.add_argument(flag, type=type(base), default=default)
        ns = p.parse_args(argv)
        return cls(**{f.name: getattr(ns, f.name) for f in fields(cls)})
