"""Ground-truth probe of the libtpu runtime-metrics surface.

Answers "what does this runtime actually serve?" (VERDICT r1 #3): the
analog of the reference live-querying every device (``main.go:129-138``),
but aimed at the metric *schema* instead of values — run it once on a real
TPU VM and commit the JSON as the fixture that pins candidate metric names
(e.g. the ICI counter) to reality.

    python -m tpu_pod_exporter.probe [--addr localhost:8431] [--out fixture.json]

Output (one JSON document):
  {"addr": ..., "reachable": bool,
   "supported": [names] | null,          # null = no enumeration RPC
   "metrics": {name: {"rows": N, "attr_keys": [...], "gauge_types": [...],
                      "sample": [{"attr": ..., "value": ...}, ...]}},
   "errors": {name: "grpc code/message"}}

Exit code 0 if the service was reachable, 2 otherwise.
"""

from __future__ import annotations

import argparse
import json
import sys


def probe(addr: str, timeout_s: float = 3.0, max_rows: int = 8) -> dict:
    import grpc

    from tpu_pod_exporter.backend.libtpu import (
        DCN_CANDIDATES,
        DUTY_CYCLE,
        HBM_TOTAL,
        HBM_USAGE,
        ICI_CANDIDATES,
        LibtpuMetricsBackend,
        split_attrs,
    )

    def raw_gauge(m):
        """JSON-safe raw gauge: keep strings as strings and unset as None
        (gauge_value would yield float NaN, which json.dumps emits as the
        non-RFC literal `NaN` — unusable in a committed fixture)."""
        which = m.gauge.WhichOneof("value")
        if which == "as_int":
            return int(m.gauge.as_int)
        if which == "as_double":
            return float(m.gauge.as_double)
        if which == "as_string":
            return m.gauge.as_string
        return None

    def sample_row(m):
        # split_attrs handles both one-attribute (device only) and
        # per-link two-attribute rows; link key omitted when absent.
        dev, link = split_attrs(m)
        row = {"attr": dev, "value": raw_gauge(m)}
        if link is not None:
            row["link"] = link
        return row

    backend = LibtpuMetricsBackend(addr=addr, timeout_s=timeout_s, device_paths={})
    report: dict = {
        "addr": addr,
        "reachable": False,
        "supported": None,
        "metrics": {},
        "errors": {},
    }
    # Transport-level failures (nothing answered); any *other* status code
    # is a real response from the service and proves reachability — a
    # runtime that NOT_FOUNDs every name is answering, not unreachable.
    transport_codes = (
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
    )
    try:
        try:
            report["supported"] = backend.list_supported_metrics()
            report["reachable"] = True
        except grpc.RpcError as e:
            report["errors"]["<ListSupportedMetrics>"] = f"{e.code()}: {e.details()}"
            if e.code() not in transport_codes:
                report["reachable"] = True

        names = report["supported"]
        if names is None:
            # No enumeration RPC: probe the names the backend knows about.
            names = [
                HBM_USAGE, HBM_TOTAL, DUTY_CYCLE,
                *ICI_CANDIDATES, *DCN_CANDIDATES,
            ]
        for name in names:
            try:
                resp = backend.query_raw(name, timeout_s=timeout_s)
            except grpc.RpcError as e:
                report["errors"][name] = f"{e.code()}: {e.details()}"
                if e.code() not in transport_codes:
                    report["reachable"] = True
                continue
            report["reachable"] = True
            rows = resp.metric.metrics
            report["metrics"][name] = {
                "rows": len(rows),
                "attr_keys": sorted({a.key for m in rows for a in m.attribute}),
                "gauge_types": sorted(
                    {m.gauge.WhichOneof("value") or "none" for m in rows}
                ),
                "sample": [sample_row(m) for m in rows[:max_rows]],
            }
    finally:
        backend.close()
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--addr", default="localhost:8431")
    p.add_argument("--timeout-s", type=float, default=3.0)
    p.add_argument("--out", default="", help="also write the JSON to this path")
    args = p.parse_args(argv)
    report = probe(args.addr, timeout_s=args.timeout_s)
    text = json.dumps(report, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0 if report["reachable"] else 2


if __name__ == "__main__":
    sys.exit(main())
