"""Tunnel liveness watchdog: log TPU-tunnel state transitions over time.

The experimental TPU tunnel on this machine has flipped between dead
(all of round 2 — see HARDWARE.md) and live (round 3 judging, round 4
start) with no notice. This watchdog samples the cheap liveness signals
every ``--interval`` seconds and appends a JSONL record *only on state
change* (plus one initial record and a periodic heartbeat), so a whole
round of watching stays a few KiB and the resulting log is a committed
timeline of hardware availability.

Signals sampled (cheapest first; none can hang):
- ``relay``: TCP accept on the tunnel relay ports (jaxenv.TUNNEL_RELAY_PORTS)
- ``libtpu_8431``: TCP accept on the libtpu runtime-metrics gRPC port

Neither signal initializes JAX — a wedged tunnel cannot wedge the
watchdog. Full ``default_backend_usable()`` probes stay manual (they
cost a subprocess + backend init) and are recorded by hwcheck/probe runs.

Reference contrast: the reference assumes NVML is always present and
fatally exits otherwise (main.go:44-48); here availability is itself a
time-varying observable worth recording.
"""

from __future__ import annotations

import argparse
import json
import socket
import time


def _port_open(port: int, timeout: float = 1.0) -> bool:
    try:
        socket.create_connection(("127.0.0.1", port), timeout=timeout).close()
        return True
    except OSError:
        return False


def sample() -> dict:
    from tpu_pod_exporter.jaxenv import TUNNEL_RELAY_PORTS

    return {
        "relay": any(_port_open(p) for p in TUNNEL_RELAY_PORTS),
        "libtpu_8431": _port_open(8431),
    }


def _positive_int(text: str) -> int:
    # 0 would ZeroDivisionError the heartbeat modulo below (advisor r4);
    # reject it at parse time with a usage error instead of a traceback.
    v = int(text)
    if v < 1:
        raise argparse.ArgumentTypeError("must be >= 1")
    return v


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="tunnel-watch.jsonl")
    p.add_argument("--interval", type=float, default=60.0)
    p.add_argument("--heartbeat-every", type=_positive_int, default=60,
                   help="emit a heartbeat record every N samples even without change")
    p.add_argument("--max-seconds", type=float, default=0.0,
                   help="stop after this long (0 = run forever)")
    args = p.parse_args(argv)

    deadline = time.monotonic() + args.max_seconds if args.max_seconds else None
    prev = None
    n = 0
    while deadline is None or time.monotonic() < deadline:
        state = sample()
        n += 1
        # `1 % every` (not a bare 1) so --heartbeat-every 1 records every
        # sample instead of never matching.
        if state != prev or (n % args.heartbeat_every) == 1 % args.heartbeat_every:
            rec = {"t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                   "change": state != prev, **state}
            with open(args.out, "a") as f:
                f.write(json.dumps(rec) + "\n")
            prev = state
        time.sleep(args.interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
