"""tpu_pod_exporter — TPU-native per-pod device-metrics exporter for Kubernetes.

A brand-new framework with the capability surface of
``dorkamotorka/kubernetes-gpu-exporter`` (reference: ``main.go:1-158``), built
TPU-first:

- Device telemetry comes from TPU-native readers (libtpu runtime metrics
  service / JAX device APIs / ``/dev/accel*`` discovery) instead of NVML
  (reference ``main.go:44-54,116-138``).
- Pod attribution comes from the kubelet podresources API
  (``google.com/tpu`` device IDs) instead of a cluster-wide pod list plus
  ``kubectl exec``/``ps`` PID joins (reference ``main.go:74-114``) — which
  removes the reference's three attribution defects (index-vs-value join,
  PID-namespace mismatch, container mistargeting).
- Metrics are ``tpu_*`` Prometheus gauges with a full label schema
  ``{pod, namespace, container, chip_id, ...topology}`` instead of the
  reference's ``{pid, pod}`` pair (``main.go:21-36``).
- Collection stays decoupled from scraping (reference ``main.go:67-72`` vs
  ``main.go:74-157``): the poll loop pre-renders the exposition text and a
  scrape serves cached bytes, making p99 scrape latency independent of
  device-query latency.
"""

from tpu_pod_exporter.version import __version__

__all__ = ["__version__"]
