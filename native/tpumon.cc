// libtpumon — native helpers for tpu-pod-exporter.
//
// TPU-native analog of the reference's single native component (the NVML C
// library reached via cgo, reference main.go:16,44-54,116-138; SURVEY.md
// §2.7 "native-component ledger"). Two jobs:
//
//   1. Device discovery: scan /dev for accel*/vfio nodes without opening
//      them (no runtime lock, no ioctls).
//   2. Exposition rendering: format `prefix value\n` lines for thousands of
//      series per poll. Called once per poll, never per scrape — but at a
//      1 s interval × 256 chips × ~10 series × 7 links this is the hottest
//      CPU in the process, and the <1% node CPU budget is the point.
//
// Pure C ABI (loaded via ctypes — no pybind11 in the image); every function
// is safe to call from any thread; no global state.

#include <cstdio>
#include <cstring>
#include <cstdint>
#include <cstdlib>
#include <cmath>

#include <dirent.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

bool is_all_digits(const char* s) {
  if (!*s) return false;
  for (; *s; ++s)
    if (*s < '0' || *s > '9') return false;
  return true;
}

// Scan root/dev for TPU device nodes. Returns count; if out != null, writes
// newline-separated "/dev/<name>" paths (relative to root) up to cap bytes.
int scan_devices(const char* root, char* out, long cap) {
  char dev_path[4096];
  std::snprintf(dev_path, sizeof(dev_path), "%s/dev", root ? root : "/");

  int count = 0;
  long used = 0;

  DIR* d = opendir(dev_path);
  if (d != nullptr) {
    struct dirent* e;
    while ((e = readdir(d)) != nullptr) {
      if (std::strncmp(e->d_name, "accel", 5) == 0 && is_all_digits(e->d_name + 5)) {
        ++count;
        if (out != nullptr) {
          int n = std::snprintf(out + used, cap > used ? cap - used : 0,
                                "/dev/%s\n", e->d_name);
          if (n > 0 && used + n < cap) used += n;
        }
      }
    }
    closedir(d);
  }

  if (count == 0) {
    // vfio fallback (v6e+): /dev/vfio/<N> numeric nodes.
    char vfio_path[4096];
    std::snprintf(vfio_path, sizeof(vfio_path), "%s/dev/vfio", root ? root : "/");
    DIR* v = opendir(vfio_path);
    if (v != nullptr) {
      struct dirent* e;
      while ((e = readdir(v)) != nullptr) {
        if (is_all_digits(e->d_name)) {
          ++count;
          if (out != nullptr) {
            int n = std::snprintf(out + used, cap > used ? cap - used : 0,
                                  "/dev/vfio/%s\n", e->d_name);
            if (n > 0 && used + n < cap) used += n;
          }
        }
      }
      closedir(v);
    }
  }

  if (count == 0) {
    // Last resort: sysfs accel class (pods with /sys but no raw /dev nodes).
    char sys_path[4096];
    std::snprintf(sys_path, sizeof(sys_path), "%s/sys/class/accel",
                  root ? root : "/");
    DIR* s = opendir(sys_path);
    if (s != nullptr) {
      struct dirent* e;
      while ((e = readdir(s)) != nullptr) {
        if (std::strncmp(e->d_name, "accel", 5) == 0 && is_all_digits(e->d_name + 5)) {
          ++count;
          if (out != nullptr) {
            int n = std::snprintf(out + used, cap > used ? cap - used : 0,
                                  "/dev/%s\n", e->d_name);
            if (n > 0 && used + n < cap) used += n;
          }
        }
      }
      closedir(s);
    }
  }

  if (out != nullptr && cap > 0) out[used < cap ? used : cap - 1] = '\0';
  return count;
}

// Two-digit lookup table for the integer fast path — snprintf("%lld") costs
// ~100-200 ns per call, and at 256 chips × ~16 series × 1 s nearly every
// sample value is integral (bytes, counters, rounded rates).
const char kDigits[201] =
    "0001020304050607080910111213141516171819"
    "2021222324252627282930313233343536373839"
    "4041424344454647484950515253545556575859"
    "6061626364656667686970717273747576777879"
    "8081828384858687888990919293949596979899";

inline int format_ll(long long v, char* out) {
  char tmp[24];
  int n = 0;
  bool neg = v < 0;
  unsigned long long u = neg ? 0ULL - (unsigned long long)v : (unsigned long long)v;
  while (u >= 100) {
    unsigned r = (unsigned)(u % 100);
    u /= 100;
    tmp[n++] = kDigits[r * 2 + 1];
    tmp[n++] = kDigits[r * 2];
  }
  if (u >= 10) {
    tmp[n++] = kDigits[u * 2 + 1];
    tmp[n++] = kDigits[u * 2];
  } else {
    tmp[n++] = (char)('0' + u);
  }
  int len = 0;
  if (neg) out[len++] = '-';
  while (n > 0) out[len++] = tmp[--n];
  return len;
}

// Format one sample value, Prometheus-style. Matches the Python encoder's
// contract (integral values without exponent/decimal, shortest-round-trip
// otherwise, NaN/+Inf/-Inf spelled out).
inline int format_value(double v, char* out, int cap) {
  if (std::isnan(v)) return std::snprintf(out, cap, "NaN");
  if (std::isinf(v)) return std::snprintf(out, cap, v > 0 ? "+Inf" : "-Inf");
  if (v == std::floor(v) && std::fabs(v) < 9007199254740992.0 /* 2^53 */) {
    return format_ll((long long)v, out);
  }
  // %.17g always round-trips; try %.15g / %.16g first for shorter output.
  char tmp[64];
  for (int prec = 15; prec <= 17; ++prec) {
    std::snprintf(tmp, sizeof(tmp), "%.*g", prec, v);
    if (std::strtod(tmp, nullptr) == v) break;
  }
  return std::snprintf(out, cap, "%s", tmp);
}

}  // namespace

extern "C" {

// Number of local TPU device nodes under root ("/" in production; test
// trees elsewhere). Never opens a device. Returns -1 on null root.
int tpumon_count_devices(const char* root) {
  if (root == nullptr) return -1;
  return scan_devices(root, nullptr, 0);
}

// Write newline-separated device paths into out (cap bytes, NUL-terminated).
// Returns the device count (which may exceed what fit in the buffer).
int tpumon_list_devices(const char* root, char* out, long cap) {
  if (root == nullptr || out == nullptr || cap <= 0) return -1;
  return scan_devices(root, out, cap);
}

// Render n exposition lines "prefix value\n" into out. prefixes[i] is the
// precomputed `metric{label="…"}` part (UTF-8, no trailing space). Returns
// bytes written, or -1 if out was too small (caller grows and retries).
long tpumon_render(const char** prefixes, const double* values, long n,
                   char* out, long cap) {
  if (prefixes == nullptr || values == nullptr || out == nullptr) return -1;
  long used = 0;
  char val[64];
  for (long i = 0; i < n; ++i) {
    const char* p = prefixes[i];
    long plen = (long)std::strlen(p);
    int vlen = format_value(values[i], val, sizeof(val));
    if (used + plen + 1 + vlen + 1 > cap) return -1;
    std::memcpy(out + used, p, plen);
    used += plen;
    out[used++] = ' ';
    std::memcpy(out + used, val, vlen);
    used += vlen;
    out[used++] = '\n';
  }
  return used;
}

// Like tpumon_render, but takes precomputed prefix lengths — the per-poll
// strlen over every prefix (~250 KB of label bytes at 256 chips) is pure
// waste when the caller's layout cache already knows the lengths.
long tpumon_render2(const char** prefixes, const int* plens,
                    const double* values, long n, char* out, long cap) {
  if (prefixes == nullptr || plens == nullptr || values == nullptr ||
      out == nullptr)
    return -1;
  long used = 0;
  char val[64];
  for (long i = 0; i < n; ++i) {
    long plen = plens[i];
    int vlen = format_value(values[i], val, sizeof(val));
    if (used + plen + 1 + vlen + 1 > cap) return -1;
    std::memcpy(out + used, prefixes[i], plen);
    used += plen;
    out[used++] = ' ';
    std::memcpy(out + used, val, vlen);
    used += vlen;
    out[used++] = '\n';
  }
  return used;
}

// Scan proc_root for processes holding device files whose readlink target
// starts with one of the newline-separated `prefixes`. Writes one record per
// (pid, device) pair into out: "pid\tdevice\tcomm\n" (comm sanitized: tabs/
// newlines replaced). The hot part of the exporter's process-attribution
// full scan — O(processes × fds) readlinks — kept native so a busy node's
// /proc walk stays off the Python interpreter (SURVEY.md §2.7 ledger;
// per-holder cgroup identity stays in the Python caller, holders are few).
//
// Returns the pair count on success (which may exceed what fit: caller
// compares against what it parsed and grows the buffer), -1 on bad args or
// unreadable proc_root (caller must treat as scan *failure*, not empty).
long tpumon_scan_proc(const char* proc_root, const char* prefixes,
                      char* out, long cap) {
  if (proc_root == nullptr || prefixes == nullptr || out == nullptr || cap <= 0)
    return -1;
  DIR* proc = opendir(proc_root);
  if (proc == nullptr) return -1;

  // Split prefixes once into (ptr, len) pairs; cap at 16 prefixes.
  const char* pfx[16];
  int pfx_len[16];
  int npfx = 0;
  for (const char* p = prefixes; *p && npfx < 16;) {
    const char* nl = std::strchr(p, '\n');
    int len = nl ? (int)(nl - p) : (int)std::strlen(p);
    if (len > 0) {
      pfx[npfx] = p;
      pfx_len[npfx] = len;
      ++npfx;
    }
    p = nl ? nl + 1 : p + len;
  }

  long count = 0;
  long used = 0;
  out[0] = '\0';
  struct dirent* pe;
  while ((pe = readdir(proc)) != nullptr) {
    if (!is_all_digits(pe->d_name)) continue;

    char fd_dir[4352];
    std::snprintf(fd_dir, sizeof(fd_dir), "%s/%s/fd", proc_root, pe->d_name);
    DIR* fds = opendir(fd_dir);
    if (fds == nullptr) continue;  // exited / unreadable: normal, skip

    // Per-process device dedupe (a process rarely holds >16 devices; extra
    // fds to the same device are the common case instead). A process that
    // genuinely exceeds the cap makes the whole scan return -1 so the
    // caller's (unbounded) Python walk takes over — silently truncating here
    // would make the verify path disagree with the cache forever.
    char devs[16][256];
    int ndevs = 0;
    bool overflow = false;
    struct dirent* fe;
    while ((fe = readdir(fds)) != nullptr) {
      if (fe->d_name[0] == '.') continue;
      char link_path[4608];
      std::snprintf(link_path, sizeof(link_path), "%s/%s", fd_dir, fe->d_name);
      char target[256];
      ssize_t tlen = readlink(link_path, target, sizeof(target) - 1);
      if (tlen <= 0) continue;
      target[tlen] = '\0';
      // "/dev/accel0 (deleted)" → "/dev/accel0" (recreated node, wedged
      // holder — exactly what the metric exists to expose).
      const char kDeleted[] = " (deleted)";
      size_t dlen = sizeof(kDeleted) - 1;
      if ((size_t)tlen > dlen &&
          std::strcmp(target + tlen - dlen, kDeleted) == 0)
        target[tlen - dlen] = '\0';
      bool match = false;
      for (int i = 0; i < npfx && !match; ++i)
        match = std::strncmp(target, pfx[i], pfx_len[i]) == 0;
      if (!match) continue;
      bool dup = false;
      for (int i = 0; i < ndevs && !dup; ++i)
        dup = std::strcmp(devs[i], target) == 0;
      if (dup) continue;
      if (ndevs == 16) {
        overflow = true;
        break;
      }
      std::snprintf(devs[ndevs++], sizeof(devs[0]), "%s", target);
    }
    closedir(fds);
    if (overflow) {
      closedir(proc);
      return -1;
    }
    if (ndevs == 0) continue;

    // comm, sanitized to match the Python scanner byte-for-byte (the verify
    // path compares Python-scanned holders against this cache): trim
    // leading/trailing ASCII whitespace, then '?'-replace interior tab and
    // newline (the record separators).
    char comm[64] = "";
    char comm_path[4352];
    std::snprintf(comm_path, sizeof(comm_path), "%s/%s/comm", proc_root,
                  pe->d_name);
    FILE* cf = std::fopen(comm_path, "re");
    if (cf != nullptr) {
      char raw[64];
      size_t n = std::fread(raw, 1, sizeof(raw) - 1, cf);
      std::fclose(cf);
      raw[n] = '\0';
      size_t start = 0;
      while (start < n && std::strchr(" \t\n\r\v\f", raw[start]) != nullptr &&
             raw[start] != '\0')
        ++start;
      while (n > start && std::strchr(" \t\n\r\v\f", raw[n - 1]) != nullptr &&
             raw[n - 1] != '\0')
        --n;
      std::memcpy(comm, raw + start, n - start);
      comm[n - start] = '\0';
      for (char* c = comm; *c; ++c)
        if (*c == '\t' || *c == '\n') *c = '?';
    }

    for (int i = 0; i < ndevs; ++i) {
      ++count;
      int n = std::snprintf(out + used, cap > used ? cap - used : 0,
                            "%s\t%s\t%s\n", pe->d_name, devs[i], comm);
      if (n > 0 && used + n < cap) used += n;
    }
  }
  closedir(proc);
  if (cap > 0) out[used < cap ? used : cap - 1] = '\0';
  return count;
}

// Whole-body value-only parse against a cached layout — the inverse of
// tpumon_render2, for the aggregator's steady state (the parse-side twin
// of the exporter's render layout cache). One entry per line of the
// previous round's body:
//   kinds[i] == 0: verbatim line (comment/blank) — the raw line must
//                  byte-equal keys[i].
//   kinds[i] == 1: name-filtered sample — the line must start with
//                  keys[i] followed by a space/tab; the rest is ignored.
//   kinds[i] == 2: consumed sample — prefix like kind 1, then the first
//                  whitespace token of the tail must parse fully as a
//                  float (written to out_values in kind-2 order); any
//                  trailing timestamp/garbage is ignored EXCEPT braces,
//                  which change the line's brace grammar entirely.
//
// Returns the number of kind-2 values written on a PERFECT whole-body
// match (every line consumed by its entry, every entry consumed), else
// -1 — the caller falls back to the Python parser, which owns all
// divergence/rebuild semantics. Deliberately conservative: anything the
// Python hit path would not accept byte-for-byte (leading whitespace,
// braces in tails, hex floats strtod would take but Python float()
// rejects, oversized value tokens) returns -1 rather than guessing.
long tpumon_parse_layout(const char* text, long n_text, const char** keys,
                         const int* klens, const unsigned char* kinds,
                         long n_entries, double* out_values) {
  if (text == nullptr || keys == nullptr || klens == nullptr ||
      kinds == nullptr || out_values == nullptr || n_text < 0)
    return -1;
  long i = 0;       // entry cursor
  long nvals = 0;   // kind-2 values written
  const char* p = text;
  const char* end = text + n_text;
  // Python's text.split("\n") yields a segment after the final newline
  // too (possibly empty) — mirror that exactly.
  for (;;) {
    const char* nl = (const char*)std::memchr(p, '\n', (size_t)(end - p));
    const char* line = p;
    long llen = (nl != nullptr ? nl : end) - p;
    if (i >= n_entries) return -1;  // body grew
    const char* key = keys[i];
    long klen = klens[i];
    unsigned char kind = kinds[i];
    ++i;
    if (kind == 0) {
      if (llen != klen || std::memcmp(line, key, (size_t)llen) != 0)
        return -1;
    } else {
      if (llen <= klen || std::memcmp(line, key, (size_t)klen) != 0)
        return -1;
      char b = line[klen];
      if (b != ' ' && b != '\t') return -1;
      if (kind == 2) {
        // Tail: optional ASCII whitespace, one value token, then
        // anything brace-free (the Python hit path drops timestamps the
        // same way). NULs can't slip through: the token is copied into a
        // bounded NUL-terminated buffer and must be consumed entirely.
        const char* t = line + klen + 1;
        const char* tend = line + llen;
        while (t < tend && (*t == ' ' || *t == '\t' || *t == '\r' ||
                            *t == '\v' || *t == '\f'))
          ++t;
        const char* tok = t;
        while (t < tend && *t != ' ' && *t != '\t' && *t != '\r' &&
               *t != '\v' && *t != '\f')
          ++t;
        long toklen = t - tok;
        if (toklen <= 0 || toklen >= 64) return -1;
        char val[64];
        std::memcpy(val, tok, (size_t)toklen);
        val[toklen] = '\0';
        // strtod accepts tokens Python float() does not — reject every
        // such shape so the native path never widens the grammar:
        // hex floats ("0x1p3"), nan payloads ("nan(123)"), and — under a
        // comma-decimal LC_NUMERIC in an embedding process — "1,5".
        for (long k = 0; k < toklen; ++k) {
          char c = val[k];
          if (c == 'x' || c == 'X' || c == '(' || c == ')' || c == ',')
            return -1;
        }
        char* endptr = nullptr;
        double v = std::strtod(val, &endptr);
        if (endptr != val + toklen) return -1;
        // The rest of the tail is ignored like Python's split()[0] — but
        // braces would change the reference brace grammar: reject.
        if (std::memchr(t, '{', (size_t)(tend - t)) != nullptr ||
            std::memchr(t, '}', (size_t)(tend - t)) != nullptr)
          return -1;
        out_values[nvals++] = v;
      }
    }
    if (nl == nullptr) break;
    p = nl + 1;
  }
  if (i != n_entries) return -1;  // body shrank
  return nvals;
}

// ABI version for the ctypes loader to sanity-check.
int tpumon_abi_version(void) { return 4; }

}  // extern "C"
