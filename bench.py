#!/usr/bin/env python
"""Benchmark: scrape latency + exporter CPU at v5p-64-host scale.

Measures the BASELINE.md target metric — p99 scrape latency over real HTTP
with the exporter polling at a 1 s interval while serving a 256-chip fake
host (the v5p-64 "256 chips" worst case concentrated on one exporter
instance), with every chip attributed to a pod and 6 ICI links per chip
(~4.4k live series). The reference publishes no numbers (its README is
4 lines; SURVEY.md §6), so vs_baseline is measured against the driver
target: p99 < 50 ms ⇒ vs_baseline = 50 / p99 (>1 is better than target).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}
"""

from __future__ import annotations

import json
import socket
import sys
import time


def percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(int(round((p / 100.0) * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[idx]


def http_get(host: str, port: int, path: str) -> bytes:
    """Tiny raw-socket HTTP/1.1 client so the bench measures the exporter,
    not urllib's connection-pool overhead."""
    with socket.create_connection((host, port), timeout=5) as s:
        s.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n".encode())
        chunks = []
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks.append(b)
    return b"".join(chunks)


def main() -> int:
    chips = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    scrapes = int(sys.argv[2]) if len(sys.argv) > 2 else 400
    import resource

    from tpu_pod_exporter.app import ExporterApp
    from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
    from tpu_pod_exporter.backend.fake import bench_backend
    from tpu_pod_exporter.config import ExporterConfig

    backend = bench_backend(chips)
    # 32 pods × 8 chips each — the multi-pod attribution shape of config 3/4.
    pods = []
    per_pod = max(chips // 32, 1)
    for p in range(0, chips, per_pod):
        ids = [str(i) for i in range(p, min(p + per_pod, chips))]
        pods.append(simple_allocation(f"train-{p // per_pod}", ids, namespace="ml"))
    attr = FakeAttribution(pods)

    cfg = ExporterConfig(
        port=0, host="127.0.0.1", interval_s=1.0, accelerator="v5p-64",
        slice_name="bench-slice", node_name="bench-host", worker_id="0",
    )
    app = ExporterApp(cfg, backend=backend, attribution=attr)
    app.start()
    try:
        # Warm up (connection path, first snapshots).
        for _ in range(10):
            http_get("127.0.0.1", app.port, "/metrics")

        cpu0 = resource.getrusage(resource.RUSAGE_SELF)
        wall0 = time.monotonic()
        lat: list[float] = []
        body_len = 0
        for _ in range(scrapes):
            t0 = time.perf_counter()
            body = http_get("127.0.0.1", app.port, "/metrics")
            lat.append((time.perf_counter() - t0) * 1e3)
            body_len = len(body)
        wall1 = time.monotonic()
        cpu1 = resource.getrusage(resource.RUSAGE_SELF)

        lat.sort()
        p50 = percentile(lat, 50)
        p99 = percentile(lat, 99)
        burst_cpu_s = (cpu1.ru_utime - cpu0.ru_utime) + (cpu1.ru_stime - cpu0.ru_stime)
        burst_wall_s = max(wall1 - wall0, 1e-9)

        # Steady state: the BASELINE CPU target is "exporter CPU at a 1 s
        # poll interval with 1 Hz scrapes", not under a scrape burst.
        # Measured over 8 s; includes the (mostly idle) bench client.
        cpu0 = resource.getrusage(resource.RUSAGE_SELF)
        wall0 = time.monotonic()
        while time.monotonic() - wall0 < 8.0:
            http_get("127.0.0.1", app.port, "/metrics")
            time.sleep(1.0)
        wall1 = time.monotonic()
        cpu1 = resource.getrusage(resource.RUSAGE_SELF)
        steady_cpu_s = (cpu1.ru_utime - cpu0.ru_utime) + (cpu1.ru_stime - cpu0.ru_stime)
        cpu_pct = 100.0 * steady_cpu_s / max(wall1 - wall0, 1e-9)

        series = app.store.current().series_count
        baseline_ms = 50.0
        result = {
            "metric": f"scrape_p99_ms_{chips}chips_1s_poll",
            "value": round(p99, 3),
            "unit": "ms",
            "vs_baseline": round(baseline_ms / p99, 2) if p99 > 0 else None,
            "p50_ms": round(p50, 3),
            "series": series,
            "body_bytes": body_len,
            "steady_cpu_percent_1hz": round(cpu_pct, 2),
            "burst_scrapes_per_s": round(scrapes / burst_wall_s, 1),
            "burst_cpu_percent": round(100.0 * burst_cpu_s / burst_wall_s, 1),
            "scrapes": scrapes,
        }
        print(json.dumps(result))
        return 0
    finally:
        app.stop()


if __name__ == "__main__":
    sys.exit(main())
