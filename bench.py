#!/usr/bin/env python
"""Benchmark: scrape latency, serving throughput + exporter CPU at scale.

Measures the BASELINE.md target metric — p99 scrape latency over real HTTP
with the exporter polling at a 1 s interval while serving a 256-chip fake
host (the v5p-64 "256 chips" worst case concentrated on one exporter
instance), with every chip attributed to a pod and 6 ICI links per chip
(~4.4k live series). The reference publishes no numbers (its README is
4 lines; SURVEY.md §6), so vs_baseline is measured against the driver
target: p99 < 50 ms ⇒ vs_baseline = 50 / p99 (>1 is better than target).

Since ISSUE 13 the bench round runs the exporter with EVERY subsystem on —
tracing, persistence (checkpoint+WAL), remote-write egress (against an
in-process receiver), and the resource-pressure governor — because that is
the configuration the serving numbers must hold under. The scrape-rate cap
is disabled in the child (it is policy, not capacity; the bench measures
capacity and records that the cap was off).

Phases, each reported in the single JSON output line:
  1. paced latency     — 400 scrapes at 80 Hz over fresh connections
  2. keep-alive burst  — back-to-back scrapes on persistent connections
                         (plain + gzip), the event-loop hot path
  3. legacy storm      — Connection: close per scrape (r01-r05 comparable)
  4. steady CPU        — 1 Hz scrapes for 8 s, exporter CPU from /proc
  5. scale check       — repeat paced latency at 2048 chips (~8 MB body)
                         to show serving stays copy-bound, not render-bound
  6. slow clients      — 48 connections against the 2048-chip child that
                         never read their response: the fds-not-threads
                         witness (child thread count must stay flat; every
                         staller must be dropped and counted by the
                         write-progress deadline). Runs at 2048 chips
                         because the ~8 MB body dwarfs the kernel socket
                         buffers, so the server-side write genuinely stalls.

The exporter runs in a CHILD process (``--serve`` mode) and its CPU is read
from ``/proc/<pid>/stat``, so the steady-state number is exporter-only —
the bench client's own cost is reported separately instead of conflated
(VERDICT r3 #7).

CI smoke gate: ``python bench.py --burst-smoke [min_per_s]`` runs only the
keep-alive burst against a 256-chip all-on child and fails below the given
floor (default 200/s — a generous shared-runner margin under the >=1000/s
BENCH-box acceptance).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time


def percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(int(round((p / 100.0) * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[idx]


def http_get(host: str, port: int, path: str) -> bytes:
    """Tiny raw-socket HTTP/1.1 client so the bench measures the exporter,
    not urllib's connection-pool overhead."""
    with socket.create_connection((host, port), timeout=5) as s:
        s.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n".encode())
        chunks = []
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks.append(b)
    return b"".join(chunks)


def http_get_json(host: str, port: int, path: str) -> dict:
    raw = http_get(host, port, path)
    head, _, body = raw.partition(b"\r\n\r\n")
    return json.loads(body)


class KeepAliveClient:
    """One persistent HTTP/1.1 connection issuing sequential scrapes —
    the event-loop hot path (no accept, no admission re-entry, no
    connection churn in the measurement)."""

    def __init__(self, host: str, port: int, gzip: bool = False,
                 path: str = "/metrics") -> None:
        self.sock = socket.create_connection((host, port), timeout=5)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        extra = "Accept-Encoding: gzip\r\n" if gzip else ""
        self.request = (
            f"GET {path} HTTP/1.1\r\nHost: x\r\n{extra}\r\n".encode()
        )
        self.buf = b""

    def scrape(self) -> tuple[int, int]:
        """Returns (status, body_bytes)."""
        self.sock.sendall(self.request)
        while b"\r\n\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("server closed keep-alive connection")
            self.buf += chunk
        head, _, rest = self.buf.partition(b"\r\n\r\n")
        status = int(head.split(b" ", 2)[1])
        clen = 0
        for line in head.split(b"\r\n")[1:]:
            if line.lower().startswith(b"content-length:"):
                clen = int(line.split(b":", 1)[1])
        while len(rest) < clen:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("server closed mid-body")
            rest += chunk
        self.buf = rest[clen:]
        return status, clen

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def proc_cpu_seconds(pid: int) -> float:
    """utime+stime of one process, from /proc/<pid>/stat."""
    with open(f"/proc/{pid}/stat") as f:
        fields = f.read().rsplit(") ", 1)[1].split()  # comm may contain spaces
    utime_ticks = int(fields[11])  # field 14, 0-indexed after comm/state
    stime_ticks = int(fields[12])  # field 15
    return (utime_ticks + stime_ticks) / os.sysconf("SC_CLK_TCK")


def proc_threads(pid: int) -> int:
    """Thread count of one process, from /proc/<pid>/status."""
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("Threads:"):
                return int(line.split()[1])
    return -1


def build_bench_app(chips: int, state_root: str, egress_url: str):
    from tpu_pod_exporter.app import ExporterApp
    from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
    from tpu_pod_exporter.backend.fake import bench_backend
    from tpu_pod_exporter.config import ExporterConfig

    backend = bench_backend(chips)
    # 32 pods × 8 chips each — the multi-pod attribution shape of config 3/4.
    pods = []
    per_pod = max(chips // 32, 1)
    for p in range(0, chips, per_pod):
        ids = [str(i) for i in range(p, min(p + per_pod, chips))]
        pods.append(simple_allocation(f"train-{p // per_pod}", ids, namespace="ml"))
    attr = FakeAttribution(pods)

    cfg = ExporterConfig(
        port=0, host="127.0.0.1", interval_s=1.0, accelerator="v5p-64",
        slice_name="bench-slice", node_name="bench-host", worker_id="0",
        # Capacity, not policy: the rate cap and the per-client admission
        # cap are deliberate refusal knobs; the bench measures what the
        # server CAN serve (recorded in the JSON as rate_cap="off" /
        # client_cap="off" so rounds are read correctly). The slow-client
        # drill in particular holds 48 concurrent stalled requests from
        # one IP — under the production per-client cap those would be
        # 429-refused at admission instead of exercising the
        # write-progress deadline the drill exists to measure.
        max_scrapes_per_s=0.0,
        max_requests_per_client=0,
        # Short write deadline so the slow-client phase completes in
        # bench time (production default stays 10 s).
        client_write_timeout_s=2.0,
        # ISSUE 13 acceptance: every subsystem on. Tracing is on by
        # default; persistence + egress + governor are wired here.
        state_dir=os.path.join(state_root, "state"),
        egress_url=egress_url,
        egress_dir=os.path.join(state_root, "egress"),
        state_max_disk_mb=256.0,
        # Roomy (scaled with the series count): the bench measures
        # serving, not the memory ladder — a mid-round shed rung would
        # change what later phases measure. The 2048-chip child idles
        # near 550 MB RSS, so a flat 512 MB budget would leave the
        # governor permanently shedding during the scale phases.
        memory_budget_mb=max(512.0, float(chips)),
    )
    return ExporterApp(cfg, backend=backend, attribution=attr)


def serve(chips: int, egress_url: str) -> int:
    """Child mode: run the bench-shaped exporter (tracing + persistence +
    egress + governor all ON) until stdin closes. The remote-write
    receiver lives in the PARENT so its decode cost never pollutes the
    child's /proc CPU accounting."""
    import tempfile

    with tempfile.TemporaryDirectory(prefix="tpe-bench-") as state_root:
        app = build_bench_app(chips, state_root, egress_url)
        app.start()
        try:
            print(json.dumps({"port": app.port, "pid": os.getpid()}), flush=True)
            sys.stdin.read()  # parent closes the pipe (or dies) → we exit
        finally:
            app.stop()
    return 0


def spawn_child(chips: int, egress_url: str) -> tuple[subprocess.Popen, int, int]:
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve", str(chips),
         egress_url],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        text=True,
    )
    info = json.loads(child.stdout.readline())
    return child, info["port"], info["pid"]


def reap_child(child: subprocess.Popen) -> None:
    try:
        child.stdin.close()
    except Exception:  # noqa: BLE001
        pass
    try:
        child.wait(timeout=10)
    except subprocess.TimeoutExpired:
        child.kill()


def keepalive_burst(port: int, seconds: float, gzip: bool = False) -> float:
    """Served scrapes/s over one persistent connection, tight loop."""
    client = KeepAliveClient("127.0.0.1", port, gzip=gzip)
    try:
        client.scrape()  # warm the encoding cache (first gzip compresses)
        served = 0
        t0 = time.monotonic()
        while time.monotonic() - t0 < seconds:
            status, _ = client.scrape()
            if status == 200:
                served += 1
        return served / max(time.monotonic() - t0, 1e-9)
    finally:
        client.close()


def slow_client_drill(port: int, child_pid: int, conns: int = 48) -> dict:
    """The fds-not-threads witness: open `conns` connections that request
    a full body and then never read. On the event loop each one costs a
    file descriptor and a write buffer; the child's thread count must stay
    flat, and every staller must be dropped + counted by the
    write-progress deadline (client_write_timeout_s=2 in the bench app).
    Run against the 2048-chip child: its ~8 MB body dwarfs the kernel
    socket buffers, so the server-side write genuinely stalls (a ~1 MB
    body can vanish into loopback buffers and "complete")."""
    threads_before = proc_threads(child_pid)
    stallers = []
    for _ in range(conns):
        # Tiny receive window so the server-side body write genuinely
        # stalls rather than fitting into kernel buffers. SO_RCVBUF must
        # be set BEFORE connect to shrink the advertised TCP window —
        # after connect it is advisory at best and the ~8 MB body would
        # vanish into auto-tuned loopback buffering, "completing" the
        # write with nothing stalled and nothing to evict.
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        s.settimeout(5)
        s.connect(("127.0.0.1", port))
        s.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        stallers.append(s)
    time.sleep(1.0)  # all bodies queued, all writes stalled
    threads_during = proc_threads(child_pid)
    # The exporter must still serve fast clients while 48 writes stall.
    t0 = time.perf_counter()
    body = http_get("127.0.0.1", port, "/metrics")
    fast_lat_ms = (time.perf_counter() - t0) * 1e3
    responsive = b" 200 " in body.split(b"\r\n", 1)[0]
    # Wait for the write-progress deadline to evict every staller, then
    # read the authoritative count AFTER closing them: while the drill
    # runs, a /debug/vars read can time out for tens of seconds on a
    # 1-core box (the 2048-chip poll, the eviction wave and the GIL all
    # contend), but tpu_exporter_client_write_timeouts_total is a
    # monotonic total — reading it once the storm subsides loses nothing.
    dropped = 0
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        try:
            stats = http_get_json(
                "127.0.0.1", port, "/debug/vars").get("server", {})
            dropped = stats.get("write_timeouts", 0)
        except (OSError, ValueError):
            pass
        if dropped >= conns:
            break
        time.sleep(0.5)
    for s in stallers:
        try:
            s.close()
        except OSError:
            pass
    if dropped < conns:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            try:
                stats = http_get_json(
                    "127.0.0.1", port, "/debug/vars").get("server", {})
                dropped = stats.get("write_timeouts", 0)
                break
            except (OSError, ValueError):
                time.sleep(1.0)
    threads_after = proc_threads(child_pid)
    # ISSUE 15 satellite: the worker pool a storm grew must REAP back to
    # baseline once idle (r06 regression: threads_after 17 vs 10 — the
    # notify-rotation reap bug). The pool's idle grace is 10 s; keep a
    # trickle of fast scrapes flowing meanwhile, because that trickle is
    # exactly the traffic pattern that defeated the old reap.
    threads_after_reap = threads_after
    reap_deadline = time.monotonic() + 25.0
    while time.monotonic() < reap_deadline:
        try:
            http_get("127.0.0.1", port, "/metrics")
        except OSError:
            pass
        threads_after_reap = proc_threads(child_pid)
        if threads_after_reap <= threads_before + 1:
            break
        time.sleep(1.0)
    return {
        "conns": conns,
        "threads_before": threads_before,
        "threads_during": threads_during,
        "threads_after": threads_after,
        "threads_after_reap": threads_after_reap,
        "reaped_to_baseline": threads_after_reap <= threads_before + 1,
        "write_timeout_drops": dropped,
        "responsive_during_stall": responsive,
        "fast_client_latency_ms_during_stall": round(fast_lat_ms, 3),
    }


def paced_latency(port: int, scrapes: int, pace_hz: float) -> tuple[list[float], int]:
    """p-latency sample over fresh connections, paced like a real scraper
    fleet. Returns (sorted latencies ms, last body length)."""
    lat: list[float] = []
    body_len = 0
    next_at = time.monotonic()
    for _ in range(scrapes):
        next_at += 1.0 / pace_hz
        t0 = time.perf_counter()
        body = http_get("127.0.0.1", port, "/metrics")
        lat.append((time.perf_counter() - t0) * 1e3)
        body_len = len(body)
        delay = next_at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
    lat.sort()
    return lat, body_len


def burst_smoke(min_per_s: float) -> int:
    """CI gate: keep-alive gzip burst (the encoding Prometheus sends)
    against a 256-chip all-on child."""
    from tpu_pod_exporter.chaos import ChaosReceiver

    receiver = ChaosReceiver([], host="127.0.0.1", port=0)
    receiver.start()
    child, port, _pid = spawn_child(256, receiver.url)
    try:
        for _ in range(5):
            http_get("127.0.0.1", port, "/metrics")
        rate = keepalive_burst(port, seconds=3.0, gzip=True)
        ok = rate >= min_per_s
        print(json.dumps({
            "metric": "burst_smoke_keepalive_gzip_per_s",
            "value": round(rate, 1),
            "min": min_per_s,
            "ok": ok,
        }))
        return 0 if ok else 1
    finally:
        reap_child(child)
        receiver.stop()


def dashboard_bench(subs: int, targets: int, out_path: str) -> int:
    """BENCH_r07: the streaming dashboard plane vs the pull baseline,
    plus the r06 follow-ups (identity keep-alive fast path via sendmsg
    scatter-gather; worker-pool idle reap). Writes ``out_path``."""
    from tpu_pod_exporter.chaos import ChaosReceiver
    from tpu_pod_exporter.loadgen.fleet import run_dashboard_demo

    results: dict = {"bench": "r07", "chips": 256}
    receiver = ChaosReceiver([], host="127.0.0.1", port=0)
    receiver.start()
    child, port, child_pid = spawn_child(256, receiver.url)
    try:
        for _ in range(5):
            http_get("127.0.0.1", port, "/metrics")
        # Identity fast path: r06 measured 322/s plain vs 12051/s gzip —
        # the ~975 KB identity body was copy/syscall-bound. The sendmsg
        # scatter-gather path coalesces head+body into one syscall per
        # send window. Median of 3 bursts: the plain number swings ±30%
        # on a shared box (kernel copy + scheduler noise), and a single
        # lucky/unlucky burst would record a lie in either direction.
        def median3(gz: bool) -> float:
            rates = sorted(keepalive_burst(port, seconds=2.0, gzip=gz)
                           for _ in range(3))
            return round(rates[1], 1)

        results["keepalive_plain_per_s"] = median3(False)
        results["keepalive_gzip_per_s"] = median3(True)
        results["keepalive_note"] = (
            "median of 3 bursts; plain (identity ~975 KB body) remains "
            "kernel-copy-bound — sendmsg coalescing buys the head+body "
            "syscall, not the copy"
        )
    finally:
        reap_child(child)
        receiver.stop()
    # Slow-client drill (2048-chip body) with the reap-to-baseline check.
    receiver = ChaosReceiver([], host="127.0.0.1", port=0)
    receiver.start()
    child, port, child_pid = spawn_child(2048, receiver.url)
    try:
        for _ in range(3):
            http_get("127.0.0.1", port, "/metrics")
        results["slow_clients"] = slow_client_drill(port, child_pid)
    finally:
        reap_child(child)
        receiver.stop()
    # Dashboard storm vs pull baseline (in-process harness; scale is the
    # local acceptance run — make dashboard-demo runs the full 5k).
    import tempfile

    with tempfile.TemporaryDirectory(prefix="bench-dash-") as tmp:
        dash = run_dashboard_demo(
            targets, 4, 2, subs, rounds=8, replicas=2, state_root=tmp,
            push_p99_budget_s=1.5,
        )
    results["dashboard"] = {
        k: dash.get(k) for k in (
            "ok", "targets", "subs", "replicas", "connected", "rounds",
            "frames_delivered", "push_p99_s", "gaps", "dups",
            "equality_checked", "equality_failures", "rss_delta_mb",
            "pull_baseline", "replica_kill", "shed", "took_s",
        )
    }
    ok = (bool(dash.get("ok"))
          and results["slow_clients"]["reaped_to_baseline"])
    results["ok"] = ok
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(results, f, indent=1)
        f.write("\n")
    print(json.dumps(results, indent=1))
    print(f"wrote {out_path}: {'OK' if ok else 'FAILED'}")
    return 0 if ok else 1


def main() -> int:
    args = [a for a in sys.argv[1:]]
    if args and args[0] == "--serve":
        return serve(int(args[1]), args[2] if len(args) > 2 else "")
    if args and args[0] == "--burst-smoke":
        return burst_smoke(float(args[1]) if len(args) > 1 else 200.0)
    if args and args[0] == "--dashboard":
        subs = int(args[1]) if len(args) > 1 else 2000
        targets = int(args[2]) if len(args) > 2 else 100
        out = args[3] if len(args) > 3 else "BENCH_r07.json"
        return dashboard_bench(subs, targets, out)
    chips = int(args[0]) if args else 256
    scrapes = int(args[1]) if len(args) > 1 else 400
    from tpu_pod_exporter.chaos import ChaosReceiver

    # Remote-write sink in the PARENT (bench-client side of the CPU split).
    receiver = ChaosReceiver([], host="127.0.0.1", port=0)
    receiver.start()
    try:
        return _run_rounds(chips, scrapes, receiver.url)
    finally:
        receiver.stop()


def _run_rounds(chips: int, scrapes: int, egress_url: str) -> int:
    import resource

    child, port, child_pid = spawn_child(chips, egress_url)
    try:
        # Warm up (connection path, first snapshots, series layout cache).
        for _ in range(10):
            http_get("127.0.0.1", port, "/metrics")

        # Phase 1 — paced latency: what a real (1 Hz × N replicas) scraper
        # fleet sees, far below capacity.
        lat, body_len = paced_latency(port, scrapes, pace_hz=80.0)
        p50 = percentile(lat, 50)
        p99 = percentile(lat, 99)

        # Phase 2 — keep-alive burst: the event-loop hot path, plain and
        # gzip (what Prometheus actually sends).
        ka_plain = keepalive_burst(port, seconds=4.0)
        ka_gzip = keepalive_burst(port, seconds=4.0, gzip=True)

        # Phase 3 — legacy storm (Connection: close per scrape), CPU-metered:
        # comparable with the burst_* figures of BENCH_r01-r05.
        served = rejected = 0
        ccpu0 = proc_cpu_seconds(child_pid)
        wall0 = time.monotonic()
        while time.monotonic() - wall0 < 6.0:
            resp = http_get("127.0.0.1", port, "/metrics")
            if b" 429 " in resp.split(b"\r\n", 1)[0]:
                rejected += 1
            else:
                served += 1
        wall1 = time.monotonic()
        ccpu1 = proc_cpu_seconds(child_pid)
        burst_cpu_s = ccpu1 - ccpu0  # exporter-only, via /proc
        burst_wall_s = max(wall1 - wall0, 1e-9)

        # Phase 4 — steady state: the BASELINE CPU target is "exporter CPU
        # at a 1 s poll interval with 1 Hz scrapes", not under a burst.
        # Exporter-only (child /proc) and bench-client (self rusage) CPU
        # are reported separately.
        scpu0 = resource.getrusage(resource.RUSAGE_SELF)
        ccpu0 = proc_cpu_seconds(child_pid)
        wall0 = time.monotonic()
        while time.monotonic() - wall0 < 8.0:
            body = http_get("127.0.0.1", port, "/metrics")
            time.sleep(1.0)
        wall1 = time.monotonic()
        ccpu1 = proc_cpu_seconds(child_pid)
        scpu1 = resource.getrusage(resource.RUSAGE_SELF)
        steady_wall = max(wall1 - wall0, 1e-9)
        exporter_cpu_pct = 100.0 * (ccpu1 - ccpu0) / steady_wall
        client_cpu_s = (
            (scpu1.ru_utime - scpu0.ru_utime) + (scpu1.ru_stime - scpu0.ru_stime)
        )
        client_cpu_pct = 100.0 * client_cpu_s / steady_wall

        # Series count + render-cache stats come from the exporter itself.
        series = None
        for line in body.decode(errors="replace").splitlines():
            if line.startswith("tpu_exporter_series "):
                series = int(float(line.split()[1]))
        dbg = http_get_json("127.0.0.1", port, "/debug/vars")
        render_stats = dbg.get("render")
    finally:
        reap_child(child)

    # Phase 5 — scale check: 2048 chips (~8× the series, ~8 MB body). The
    # splice render keeps the poll loop incremental and the event loop
    # keeps serving copy-bound; p99 is expected to scale with BODY BYTES
    # (a kernel-copy cost no server design removes), not with render work,
    # so the flatness witness is p99-per-MB.
    # Phase 6 — slow clients (fds, not threads), against the same child:
    # its ~8 MB body dwarfs the kernel socket buffers, so each staller's
    # server-side write genuinely stalls instead of vanishing into
    # loopback buffering.
    scale_chips = 2048
    child, port, scale_pid = spawn_child(scale_chips, egress_url)
    try:
        for _ in range(5):
            http_get("127.0.0.1", port, "/metrics")
        scale_lat, scale_body = paced_latency(port, scrapes=80, pace_hz=10.0)
        scale_p99 = percentile(scale_lat, 99)
        scale_p50 = percentile(scale_lat, 50)
        slow = slow_client_drill(port, scale_pid)
    finally:
        reap_child(child)

    baseline_ms = 50.0
    result = {
        "metric": f"scrape_p99_ms_{chips}chips_1s_poll",
        "value": round(p99, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / p99, 2) if p99 > 0 else None,
        "p50_ms": round(p50, 3),
        "series": series,
        "body_bytes": body_len,
        # All-on round (ISSUE 13): which subsystems were live in the child.
        "subsystems": {
            "tracing": True, "persistence": True, "egress": True,
            "governor": True, "rate_cap": "off", "client_cap": "off",
        },
        # Exporter-only (child process /proc accounting):
        "steady_cpu_percent_1hz": round(exporter_cpu_pct, 2),
        # The scrape client's own cost, formerly conflated into the
        # number above:
        "bench_client_cpu_percent_1hz": round(client_cpu_pct, 2),
        # Keep-alive burst: the event-loop hot path (ISSUE 13 acceptance:
        # >=1000/s served at 256 chips on the BENCH box).
        "burst_keepalive_per_s": round(ka_plain, 1),
        "burst_keepalive_gzip_per_s": round(ka_gzip, 1),
        # Legacy storm (connection churn included), r01-r05-comparable:
        "burst_scrapes_per_s": round((served + rejected) / burst_wall_s, 1),
        "burst_cpu_percent": round(100.0 * burst_cpu_s / burst_wall_s, 1),
        "burst_served_per_s": round(served / burst_wall_s, 1),
        "burst_rejected_per_s": round(rejected / burst_wall_s, 1),
        "slow_clients": slow,
        "render": render_stats,
        # Scale check (p99 tracks body bytes, not series-render work):
        "scale_2048": {
            "chips": scale_chips,
            "p50_ms": round(scale_p50, 3),
            "p99_ms": round(scale_p99, 3),
            "body_bytes": scale_body,
            "p99_ms_per_mb": round(scale_p99 / (scale_body / 1e6), 3)
            if scale_body else None,
        },
        "p99_ms_per_mb_256": round(p99 / (body_len / 1e6), 3)
        if body_len else None,
        "scrapes": scrapes,
        # Latency and CPU are strongly machine-dependent (a 1-core CI
        # host roughly doubles p99 vs a multi-core box because scrapes
        # collide with the poll); record the hardware so cross-round
        # BENCH_r{N}.json comparisons aren't misread as regressions.
        "cpu_cores": os.cpu_count(),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
