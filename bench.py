#!/usr/bin/env python
"""Benchmark: scrape latency + exporter CPU at v5p-64-host scale.

Measures the BASELINE.md target metric — p99 scrape latency over real HTTP
with the exporter polling at a 1 s interval while serving a 256-chip fake
host (the v5p-64 "256 chips" worst case concentrated on one exporter
instance), with every chip attributed to a pod and 6 ICI links per chip
(~4.4k live series). The reference publishes no numbers (its README is
4 lines; SURVEY.md §6), so vs_baseline is measured against the driver
target: p99 < 50 ms ⇒ vs_baseline = 50 / p99 (>1 is better than target).

The exporter runs in a CHILD process (``--serve`` mode) and its CPU is read
from ``/proc/<pid>/stat``, so the steady-state number is exporter-only —
the bench client's own cost is reported separately instead of conflated
(VERDICT r3 #7).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time


def percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(int(round((p / 100.0) * (len(sorted_vals) - 1))), len(sorted_vals) - 1)
    return sorted_vals[idx]


def http_get(host: str, port: int, path: str) -> bytes:
    """Tiny raw-socket HTTP/1.1 client so the bench measures the exporter,
    not urllib's connection-pool overhead."""
    with socket.create_connection((host, port), timeout=5) as s:
        s.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n".encode())
        chunks = []
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks.append(b)
    return b"".join(chunks)


def proc_cpu_seconds(pid: int) -> float:
    """utime+stime of one process, from /proc/<pid>/stat."""
    with open(f"/proc/{pid}/stat") as f:
        fields = f.read().rsplit(") ", 1)[1].split()  # comm may contain spaces
    utime_ticks = int(fields[11])  # field 14, 0-indexed after comm/state
    stime_ticks = int(fields[12])  # field 15
    return (utime_ticks + stime_ticks) / os.sysconf("SC_CLK_TCK")


def build_bench_app(chips: int):
    from tpu_pod_exporter.app import ExporterApp
    from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
    from tpu_pod_exporter.backend.fake import bench_backend
    from tpu_pod_exporter.config import ExporterConfig

    backend = bench_backend(chips)
    # 32 pods × 8 chips each — the multi-pod attribution shape of config 3/4.
    pods = []
    per_pod = max(chips // 32, 1)
    for p in range(0, chips, per_pod):
        ids = [str(i) for i in range(p, min(p + per_pod, chips))]
        pods.append(simple_allocation(f"train-{p // per_pod}", ids, namespace="ml"))
    attr = FakeAttribution(pods)

    cfg = ExporterConfig(
        port=0, host="127.0.0.1", interval_s=1.0, accelerator="v5p-64",
        slice_name="bench-slice", node_name="bench-host", worker_id="0",
    )
    return ExporterApp(cfg, backend=backend, attribution=attr)


def serve(chips: int) -> int:
    """Child mode: run the bench-shaped exporter until stdin closes."""
    app = build_bench_app(chips)
    app.start()
    try:
        print(json.dumps({"port": app.port, "pid": os.getpid()}), flush=True)
        sys.stdin.read()  # parent closes the pipe (or dies) → we exit
    finally:
        app.stop()
    return 0


def main() -> int:
    args = [a for a in sys.argv[1:]]
    if args and args[0] == "--serve":
        return serve(int(args[1]))
    chips = int(args[0]) if args else 256
    scrapes = int(args[1]) if len(args) > 1 else 400
    import resource

    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve", str(chips)],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        text=True,
    )
    try:
        info = json.loads(child.stdout.readline())
        port, child_pid = info["port"], info["pid"]

        # Warm up (connection path, first snapshots, series layout cache).
        for _ in range(10):
            http_get("127.0.0.1", port, "/metrics")

        # Latency phase, PACED below the exporter's scrape-rate cap
        # (config.max_scrapes_per_s, default 100/s): p99 must measure what
        # a real scraper sees, and real scrapers are 1 Hz — an unpaced
        # tight loop would measure the 429 wall instead.
        pace_hz = 80.0
        lat: list[float] = []
        body_len = 0
        paced_rejects = 0
        next_at = time.monotonic()
        for _ in range(scrapes):
            next_at += 1.0 / pace_hz
            t0 = time.perf_counter()
            body = http_get("127.0.0.1", port, "/metrics")
            lat.append((time.perf_counter() - t0) * 1e3)
            if b" 429 " in body.split(b"\r\n", 1)[0]:
                paced_rejects += 1
            else:
                body_len = len(body)
            delay = next_at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
        if paced_rejects:
            # ANY mid-run reject poisons the latency sample (tarpit sleeps
            # and 29-byte rejects would masquerade as scrape latencies).
            print(json.dumps({
                "error": "paced latency phase hit the rate cap",
                "rejects": paced_rejects,
            }))
            return 1

        lat.sort()
        p50 = percentile(lat, 50)
        p99 = percentile(lat, 99)

        # Storm phase: hammer /metrics flat out. The rate cap means the
        # exporter serves ~max_scrapes_per_s full bodies and answers the
        # rest with the pre-rendered 429 — the number that matters is how
        # much of a core the storm can steal from the TPU host.
        served = rejected = 0
        ccpu0 = proc_cpu_seconds(child_pid)
        wall0 = time.monotonic()
        while time.monotonic() - wall0 < 6.0:
            resp = http_get("127.0.0.1", port, "/metrics")
            if b" 429 " in resp.split(b"\r\n", 1)[0]:
                rejected += 1
            else:
                served += 1
        wall1 = time.monotonic()
        ccpu1 = proc_cpu_seconds(child_pid)
        burst_cpu_s = ccpu1 - ccpu0  # exporter-only, via /proc
        burst_wall_s = max(wall1 - wall0, 1e-9)

        # Steady state: the BASELINE CPU target is "exporter CPU at a 1 s
        # poll interval with 1 Hz scrapes", not under a scrape burst.
        # Exporter-only (child /proc) and bench-client (self rusage) CPU
        # are reported separately.
        scpu0 = resource.getrusage(resource.RUSAGE_SELF)
        ccpu0 = proc_cpu_seconds(child_pid)
        wall0 = time.monotonic()
        while time.monotonic() - wall0 < 8.0:
            http_get("127.0.0.1", port, "/metrics")
            time.sleep(1.0)
        wall1 = time.monotonic()
        ccpu1 = proc_cpu_seconds(child_pid)
        scpu1 = resource.getrusage(resource.RUSAGE_SELF)
        steady_wall = max(wall1 - wall0, 1e-9)
        exporter_cpu_pct = 100.0 * (ccpu1 - ccpu0) / steady_wall
        client_cpu_s = (
            (scpu1.ru_utime - scpu0.ru_utime) + (scpu1.ru_stime - scpu0.ru_stime)
        )
        client_cpu_pct = 100.0 * client_cpu_s / steady_wall

        # Series count comes from the exporter's own self-metric.
        series = None
        for line in body.decode(errors="replace").splitlines():
            if line.startswith("tpu_exporter_series "):
                series = int(float(line.split()[1]))
        baseline_ms = 50.0
        result = {
            "metric": f"scrape_p99_ms_{chips}chips_1s_poll",
            "value": round(p99, 3),
            "unit": "ms",
            "vs_baseline": round(baseline_ms / p99, 2) if p99 > 0 else None,
            "p50_ms": round(p50, 3),
            "series": series,
            "body_bytes": body_len,
            # Exporter-only (child process /proc accounting):
            "steady_cpu_percent_1hz": round(exporter_cpu_pct, 2),
            # The scrape client's own cost, formerly conflated into the
            # number above:
            "bench_client_cpu_percent_1hz": round(client_cpu_pct, 2),
            "burst_scrapes_per_s": round((served + rejected) / burst_wall_s, 1),
            "burst_cpu_percent": round(100.0 * burst_cpu_s / burst_wall_s, 1),
            "burst_served_per_s": round(served / burst_wall_s, 1),
            "burst_rejected_per_s": round(rejected / burst_wall_s, 1),
            "scrapes": scrapes,
            # Latency and CPU are strongly machine-dependent (a 1-core CI
            # host roughly doubles p99 vs a multi-core box because scrapes
            # collide with the poll); record the hardware so cross-round
            # BENCH_r{N}.json comparisons aren't misread as regressions.
            "cpu_cores": os.cpu_count(),
        }
        print(json.dumps(result))
        return 0
    finally:
        try:
            child.stdin.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            child.wait(timeout=10)
        except subprocess.TimeoutExpired:
            child.kill()


if __name__ == "__main__":
    sys.exit(main())
